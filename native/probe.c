/* Native pending-call progress probe.
 *
 * The progress watchdog proves the main thread's eval loop is alive by scheduling a
 * callback onto it with Py_AddPendingCall (the CPython liveness trick of the
 * reference's inprocess/progress_watchdog.py:47-195). A ctypes-wrapped Python
 * trampoline has a flaw: it executes Python bytecode on the main thread, so a
 * PyThreadState_SetAsyncExc-injected restart exception can be delivered *inside the
 * trampoline frame*, where ctypes swallows it ("Exception ignored on calling ctypes
 * callback") and the restart signal is lost or misattributed as a SystemError.
 *
 * This callback is pure C: it runs on the main thread with the GIL held but never
 * enters the bytecode eval loop, so pending async exceptions cannot fire inside it.
 * It records a monotonic timestamp + counter read by the watchdog thread.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdatomic.h>
#include <stdint.h>
#include <time.h>

static _Atomic int64_t g_probe_count = 0;
static _Atomic int64_t g_probe_last_ns = 0;

static int64_t monotonic_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + (int64_t)ts.tv_nsec;
}

/* Runs on the main thread inside the interpreter's pending-call drain. */
static int probe_callback(void *arg) {
    (void)arg;
    atomic_store(&g_probe_last_ns, monotonic_ns());
    atomic_fetch_add(&g_probe_count, 1);
    return 0;
}

/* Schedule one probe; returns False if the interpreter's pending-call queue is
 * full (caller retries next tick). Safe to call from any thread. */
static PyObject *probe_schedule(PyObject *self, PyObject *noargs) {
    (void)self;
    (void)noargs;
    int rc = Py_AddPendingCall(probe_callback, NULL);
    if (rc != 0) {
        Py_RETURN_FALSE;
    }
    Py_RETURN_TRUE;
}

static PyObject *probe_count(PyObject *self, PyObject *noargs) {
    (void)self;
    (void)noargs;
    return PyLong_FromLongLong(atomic_load(&g_probe_count));
}

static PyObject *probe_last_ns(PyObject *self, PyObject *noargs) {
    (void)self;
    (void)noargs;
    return PyLong_FromLongLong(atomic_load(&g_probe_last_ns));
}

static PyMethodDef ProbeMethods[] = {
    {"schedule", probe_schedule, METH_NOARGS,
     "Queue a pure-C pending call onto the main thread; True if queued."},
    {"count", probe_count, METH_NOARGS,
     "Number of probe callbacks the main thread has executed."},
    {"last_ns", probe_last_ns, METH_NOARGS,
     "CLOCK_MONOTONIC ns of the most recent executed probe (0 if none)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef probemodule = {
    PyModuleDef_HEAD_INIT,
    "_probe_native",
    "Pure-C main-thread liveness probe for the progress watchdog.",
    -1,
    ProbeMethods,
    NULL,
    NULL,
    NULL,
    NULL,
};

PyMODINIT_FUNC PyInit__probe_native(void) {
    return PyModule_Create(&probemodule);
}
