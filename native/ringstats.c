/* Pooled fixed-capacity ring buffers with C-side summary statistics.
 *
 * The native analogue of the reference's CUPTI stats machinery: BufferPool's
 * one-allocation buffer management (BufferPool.h:24-38), CircularBuffer<float>'s
 * bounded rings with linearize() (CircularBuffer.h:22-70), and computeStats'
 * sort-based min/max/median/avg/std over retained samples
 * (CuptiProfiler.cpp:44-74). One RingPool holds every signal's window in a single
 * contiguous block: pushes are two array writes, stats sort at most `capacity`
 * doubles in preallocated scratch — no per-sample Python objects, no allocation
 * after construction.
 *
 * Exposed as tpu_resiliency._ringstats (plain CPython C API; this repo binds
 * native code without pybind11). Python-level fallback:
 * telemetry/ring_buffer.py.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <math.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    PyObject_HEAD
    Py_ssize_t n_rings;
    Py_ssize_t capacity;
    double *data;      /* [n_rings * capacity] */
    Py_ssize_t *next;  /* [n_rings] write cursor */
    Py_ssize_t *count; /* [n_rings] valid samples (<= capacity) */
    double *scratch;   /* [capacity] sort buffer */
} RingPool;

static void
RingPool_dealloc(RingPool *self)
{
    PyMem_Free(self->data);
    PyMem_Free(self->next);
    PyMem_Free(self->count);
    PyMem_Free(self->scratch);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static int
RingPool_init(RingPool *self, PyObject *args, PyObject *kwds)
{
    static char *kwlist[] = {"n_rings", "capacity", NULL};
    Py_ssize_t n_rings, capacity;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "nn", kwlist, &n_rings, &capacity))
        return -1;
    if (n_rings <= 0 || capacity <= 0) {
        PyErr_SetString(PyExc_ValueError, "n_rings and capacity must be positive");
        return -1;
    }
    if (n_rings > PY_SSIZE_T_MAX / capacity) {
        PyErr_SetString(PyExc_OverflowError, "n_rings * capacity overflows");
        return -1;
    }
    self->n_rings = n_rings;
    self->capacity = capacity;
    self->data = PyMem_Calloc((size_t)(n_rings * capacity), sizeof(double));
    self->next = PyMem_Calloc((size_t)n_rings, sizeof(Py_ssize_t));
    self->count = PyMem_Calloc((size_t)n_rings, sizeof(Py_ssize_t));
    self->scratch = PyMem_Calloc((size_t)capacity, sizeof(double));
    if (!self->data || !self->next || !self->count || !self->scratch) {
        PyErr_NoMemory();
        return -1;
    }
    return 0;
}

static int
check_ring(RingPool *self, Py_ssize_t ring)
{
    if (ring < 0 || ring >= self->n_rings) {
        PyErr_Format(PyExc_IndexError, "ring %zd out of range [0, %zd)", ring,
                     self->n_rings);
        return -1;
    }
    return 0;
}

static PyObject *
RingPool_push(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    double value;
    if (!PyArg_ParseTuple(args, "nd", &ring, &value))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    double *buf = self->data + ring * self->capacity;
    buf[self->next[ring]] = value;
    self->next[ring] = (self->next[ring] + 1) % self->capacity;
    if (self->count[ring] < self->capacity)
        self->count[ring]++;
    Py_RETURN_NONE;
}

static PyObject *
RingPool_push_many(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "nO", &ring, &seq))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    /* Fast path: any C-contiguous float64 buffer (numpy array, memoryview) is
       ingested without boxing a PyFloat per sample. */
    Py_buffer view;
    if (PyObject_GetBuffer(seq, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) == 0) {
        if (view.itemsize == sizeof(double) &&
            (view.format == NULL || strcmp(view.format, "d") == 0)) {
            const double *src = (const double *)view.buf;
            Py_ssize_t n = view.len / (Py_ssize_t)sizeof(double);
            double *buf = self->data + ring * self->capacity;
            for (Py_ssize_t i = 0; i < n; i++) {
                buf[self->next[ring]] = src[i];
                self->next[ring] = (self->next[ring] + 1) % self->capacity;
                if (self->count[ring] < self->capacity)
                    self->count[ring]++;
            }
            PyBuffer_Release(&view);
            Py_RETURN_NONE;
        }
        PyBuffer_Release(&view);
    } else {
        PyErr_Clear();
    }
    PyObject *fast = PySequence_Fast(seq, "push_many expects a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    double *buf = self->data + ring * self->capacity;
    for (Py_ssize_t i = 0; i < n; i++) {
        double v = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(fast, i));
        if (v == -1.0 && PyErr_Occurred()) {
            Py_DECREF(fast);
            return NULL;
        }
        buf[self->next[ring]] = v;
        self->next[ring] = (self->next[ring] + 1) % self->capacity;
        if (self->count[ring] < self->capacity)
            self->count[ring]++;
    }
    Py_DECREF(fast);
    Py_RETURN_NONE;
}

static void
linearize_into(RingPool *self, Py_ssize_t ring, double *out)
{
    double *buf = self->data + ring * self->capacity;
    Py_ssize_t n = self->count[ring];
    if (n < self->capacity) {
        memcpy(out, buf, (size_t)n * sizeof(double));
    } else {
        Py_ssize_t head = self->next[ring];
        memcpy(out, buf + head, (size_t)(self->capacity - head) * sizeof(double));
        memcpy(out + (self->capacity - head), buf, (size_t)head * sizeof(double));
    }
}

static PyObject *
RingPool_linearize(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    if (!PyArg_ParseTuple(args, "n", &ring))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    Py_ssize_t n = self->count[ring];
    PyObject *bytes = PyBytes_FromStringAndSize(NULL, n * (Py_ssize_t)sizeof(double));
    if (!bytes)
        return NULL;
    linearize_into(self, ring, (double *)PyBytes_AS_STRING(bytes));
    return bytes; /* oldest -> newest, float64; wrap with np.frombuffer */
}

static int
cmp_double(const void *a, const void *b)
{
    double da = *(const double *)a, db = *(const double *)b;
    return (da > db) - (da < db);
}

static PyObject *
RingPool_stats(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    if (!PyArg_ParseTuple(args, "n", &ring))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    Py_ssize_t n = self->count[ring];
    if (n == 0) {
        PyErr_SetString(PyExc_ValueError, "stats of an empty ring");
        return NULL;
    }
    double *s = self->scratch;
    linearize_into(self, ring, s);
    double mn = s[0], mx = s[0], sum = 0.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        double v = s[i];
        if (v < mn) mn = v;
        if (v > mx) mx = v;
        sum += v;
    }
    double avg = sum / (double)n;
    /* Two-pass variance: the naive sumsq/n - avg^2 form catastrophically cancels
       for large-mean/small-spread samples (numpy uses the same two-pass shape,
       keeping native and fallback stats interchangeable). */
    double ssd = 0.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        double d = s[i] - avg;
        ssd += d * d;
    }
    double std = sqrt(ssd / (double)n);
    qsort(s, (size_t)n, sizeof(double), cmp_double);
    double med = (n % 2) ? s[n / 2] : 0.5 * (s[n / 2 - 1] + s[n / 2]);
    /* (count, min, max, med, avg, std, total) — computeStats parity + total,
       which the scoring pipeline uses as the signal weight. */
    return Py_BuildValue("(ndddddd)", n, mn, mx, med, avg, std, sum);
}

static PyObject *
RingPool_count(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    if (!PyArg_ParseTuple(args, "n", &ring))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    return PyLong_FromSsize_t(self->count[ring]);
}

static PyObject *
RingPool_reset(RingPool *self, PyObject *args)
{
    Py_ssize_t ring;
    if (!PyArg_ParseTuple(args, "n", &ring))
        return NULL;
    if (check_ring(self, ring) < 0)
        return NULL;
    self->next[ring] = 0;
    self->count[ring] = 0;
    Py_RETURN_NONE;
}

static PyObject *
RingPool_reset_all(RingPool *self, PyObject *Py_UNUSED(ignored))
{
    memset(self->next, 0, (size_t)self->n_rings * sizeof(Py_ssize_t));
    memset(self->count, 0, (size_t)self->n_rings * sizeof(Py_ssize_t));
    Py_RETURN_NONE;
}

static PyMethodDef RingPool_methods[] = {
    {"push", (PyCFunction)RingPool_push, METH_VARARGS, "push(ring, value)"},
    {"push_many", (PyCFunction)RingPool_push_many, METH_VARARGS,
     "push_many(ring, seq_of_floats)"},
    {"linearize", (PyCFunction)RingPool_linearize, METH_VARARGS,
     "linearize(ring) -> bytes of float64, oldest->newest"},
    {"stats", (PyCFunction)RingPool_stats, METH_VARARGS,
     "stats(ring) -> (count, min, max, med, avg, std, total)"},
    {"count", (PyCFunction)RingPool_count, METH_VARARGS, "count(ring) -> int"},
    {"reset", (PyCFunction)RingPool_reset, METH_VARARGS, "reset(ring)"},
    {"reset_all", (PyCFunction)RingPool_reset_all, METH_NOARGS, "reset_all()"},
    {NULL, NULL, 0, NULL},
};

static PyMemberDef RingPool_members[] = {
    {NULL},
};

static PyObject *
RingPool_get_n_rings(RingPool *self, void *closure)
{
    return PyLong_FromSsize_t(self->n_rings);
}

static PyObject *
RingPool_get_capacity(RingPool *self, void *closure)
{
    return PyLong_FromSsize_t(self->capacity);
}

static PyGetSetDef RingPool_getset[] = {
    {"n_rings", (getter)RingPool_get_n_rings, NULL, "ring count", NULL},
    {"capacity", (getter)RingPool_get_capacity, NULL, "per-ring capacity", NULL},
    {NULL},
};

static PyTypeObject RingPoolType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "tpu_resiliency._ringstats.RingPool",
    .tp_basicsize = sizeof(RingPool),
    .tp_dealloc = (destructor)RingPool_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "Pooled fixed-capacity ring buffers with C-side stats",
    .tp_methods = RingPool_methods,
    .tp_members = RingPool_members,
    .tp_getset = RingPool_getset,
    .tp_init = (initproc)RingPool_init,
    .tp_new = PyType_GenericNew,
};

static PyModuleDef ringstats_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "tpu_resiliency._ringstats",
    .m_doc = "Native ring-buffer stats collector (CUPTI CircularBuffer analogue)",
    .m_size = -1,
};

PyMODINIT_FUNC
PyInit__ringstats(void)
{
    if (PyType_Ready(&RingPoolType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&ringstats_module);
    if (!m)
        return NULL;
    Py_INCREF(&RingPoolType);
    if (PyModule_AddObject(m, "RingPool", (PyObject *)&RingPoolType) < 0) {
        Py_DECREF(&RingPoolType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
