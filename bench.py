"""North-star benchmark (BASELINE.json config 4): score 4096-rank heartbeat+perf fused
telemetry — per-rank per-signal timing windows reduced to straggler scores — on one TPU
chip, vs a host-side emulation of the reference's Python scoring path.

Baseline emulation re-implements, from the spec in SURVEY.md §2.5/§3.5 (NOT copied), what
the reference's ``ReportGenerator.generate_report`` does on host per report: per-rank
dicts of per-signal sample lists → per-signal medians + totals (Python loop over dict
entries), pack medians to a flat vector, min-reduce across ranks, unpack, weighted score
loop, straggler thresholding. The device path is ``telemetry.scoring.score_round`` (and
the Pallas fused-median variant) running as one compiled program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}; details go to stderr.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

R, S, W = 4096, 64, 32
SLOW_FRACTION = 0.05
SLOWDOWN = 1.6
ITERS = 50


def make_telemetry(seed=0):
    rng = np.random.default_rng(seed)
    base = rng.uniform(0.8, 1.2, size=(1, S, 1)).astype(np.float32)
    data = base * (1.0 + 0.05 * rng.standard_normal((R, S, W)).astype(np.float32))
    n_slow = int(R * SLOW_FRACTION)
    slow_ranks = rng.choice(R, size=n_slow, replace=False)
    data[slow_ranks] *= SLOWDOWN
    counts = np.full((R, S), W, dtype=np.int32)
    truth = np.zeros(R, dtype=bool)
    truth[slow_ranks] = True
    return data, counts, truth


def baseline_host_scoring(data, counts, threshold=0.75):
    """Reference-style host scoring: dict-of-lists telemetry, Python pack/unpack loops."""
    # per-rank summaries as the reference holds them: dict rank -> {signal_name: samples}
    telemetry = {
        r: {f"sig{s}": data[r, s, : counts[r, s]].tolist() for s in range(S)} for r in range(R)
    }
    t0 = time.perf_counter()
    medians, totals = {}, {}
    for r, sigs in telemetry.items():
        med_r, tot_r = {}, {}
        for name, samples in sigs.items():
            arr = np.asarray(samples)
            med_r[name] = float(np.median(arr))
            tot_r[name] = float(arr.sum())
        medians[r] = med_r
        totals[r] = tot_r
    # pack → min-reduce across ranks → unpack (the all_reduce(MIN) emulation)
    names = sorted(medians[0])
    packed = np.array([[medians[r][n] for n in names] for r in range(R)])
    ref = packed.min(axis=0)
    # weighted per-rank score loop
    scores = {}
    for r in range(R):
        num = den = 0.0
        for j, n in enumerate(names):
            w = totals[r][n]
            num += w * (ref[j] / medians[r][n])
            den += w
        scores[r] = num / den
    stragglers = {r for r, sc in scores.items() if sc < threshold}
    elapsed = time.perf_counter() - t0
    return elapsed, scores, stragglers


def f1(pred_mask, truth):
    tp = int((pred_mask & truth).sum())
    fp = int((pred_mask & ~truth).sum())
    fn = int((~pred_mask & truth).sum())
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def _program_ms(profiler, substring):
    """Median per-execution time (ms) of the profiled program whose name contains
    ``substring``; None when the window captured no such program."""
    for name, st in profiler.get_stats().items():
        if substring in name:
            return st["med"] * 1e3
    return None


def device_scoring(data, counts, variant="xla"):
    """Measure one scoring round's TRUE device time via the framework's own
    XLA-profiler capture (``telemetry/device_profiler.py``).

    Wall-clock loops are not trustworthy here: on remote-dispatch runtimes (the
    TPU tunnel) ``block_until_ready`` does not reliably flush a dispatch chain,
    which made earlier rounds report fantasy sub-0.1 ms scores — the device
    profiler reads the executed program's ``device_duration_ps`` instead."""
    import jax
    import jax.numpy as jnp

    from tpu_resiliency.telemetry import scoring
    from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

    if variant in ("pallas", "pallas-pairwise", "pallas-radix"):
        from tpu_resiliency.ops.scoring_pallas import fused_median_weights

        mode = {"pallas": "loop", "pallas-pairwise": "pairwise",
                "pallas-radix": "radix"}[variant]

        def score_program(d, c, e, h):
            mw = fused_median_weights(d, c, mode=mode)
            return scoring.score_round(d, c, e, h, medians_and_weights=mw)

    else:
        def score_program(d, c, e, h):
            return scoring.score_round(d, c, e, h)

    fn = jax.jit(score_program)
    d = jnp.asarray(data)
    c = jnp.asarray(counts)
    ewma = jnp.ones((R,))
    hist = jnp.full((R, S), jnp.inf)
    out = fn(d, c, ewma, hist)
    jax.block_until_ready(out)
    if jax.default_backend() == "tpu":
        prof = DeviceTimeProfiler()
        with prof:
            for _ in range(ITERS):
                out = fn(d, c, out.ewma, hist)
            jax.block_until_ready(out)
        per_step_ms = _program_ms(prof, "score_program")
        if per_step_ms is None:
            raise RuntimeError("profiler captured no score_program executions")
        return per_step_ms / 1e3, out
    # Local backends (CPU simulation): block_until_ready is reliable, and the
    # host trace only records dispatch times — use a blocking wall clock.
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(d, c, out.ewma, hist)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS, out


def device_ring_scoring(data, counts, report_interval=100):
    """The real north-star hot loop, decomposed the way a train loop pays for it:

    - **push**: every step appends its ``[R, S]`` timings to the device-resident
      sharded rings from inside the jitted step (donated carry) — paid per step;
    - **score**: the fused scoring program runs once per *report* (reference default
      cadence is minutes; ``report_interval`` steps here is conservative).

    The honest per-step cost is ``push + score / report_interval``. Round 2
    reported only the two endpoints (score-only 0.09 ms; push+score-every-step
    9.09 ms) — neither is what users pay."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from tpu_resiliency.telemetry.sharded import MeshTelemetry

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("rank",))
    mt = MeshTelemetry(
        mesh, "rank", n_ranks=R,
        signal_names=tuple(f"sig{s}" for s in range(S)), window=W,
    )
    from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

    state = mt.init_state()
    # Pre-split step rows: indexing a device array with a fresh static index inside
    # the timed loop would compile a new slice program per index.
    rows = [jnp.asarray(data[:, :, i]) for i in range(W)]
    for i in range(W):
        state = mt.push(state, rows[i])
    # warm both programs
    state, out = mt.score(state)
    jax.block_until_ready((state, out))

    if jax.default_backend() == "tpu":
        # Device-true per-program times (see device_scoring on why wall clocks lie).
        prof = DeviceTimeProfiler()
        with prof:
            for i in range(ITERS * 4):
                state = mt.push(state, rows[i % W])
            jax.block_until_ready(state)
            for i in range(5):
                state = mt.push(state, rows[i % W])  # keep counts alive between scores
                state, out = mt.score(state)
            jax.block_until_ready((state, out))
        per_push_ms = _program_ms(prof, "_push_impl")
        per_score_ms = _program_ms(prof, "_score_reset_impl")
        if per_push_ms is None or per_score_ms is None:
            raise RuntimeError(
                f"profiler missed ring programs: {sorted(prof.get_stats())}"
            )
        per_push = per_push_ms / 1e3
        per_score = per_score_ms / 1e3
    else:
        # Local backends: blocking wall clock (host trace records dispatch only).
        t0 = time.perf_counter()
        for i in range(ITERS * 4):
            state = mt.push(state, rows[i % W])
        jax.block_until_ready(state)
        per_push = (time.perf_counter() - t0) / (ITERS * 4)
        t0 = time.perf_counter()
        for i in range(5):
            state = mt.push(state, rows[i % W])
            state, out = mt.score(state)
            jax.block_until_ready((state, out))
        per_score = max((time.perf_counter() - t0) / 5 - per_push, 0.0)
    per_step = per_push + per_score / report_interval

    # Rebuild a full window so the F1 check sees real scores, not a 1-sample round.
    for i in range(W):
        state = mt.push(state, rows[i])
    _, out = mt.score(state)
    return per_step, per_push, per_score, out


REPORT_INTERVAL = 100


def probe_backend_alive(timeout: float | None = None, attempts: int | None = None) -> bool:
    """Can this environment's default JAX backend actually run an op? Probed in a
    THROWAWAY subprocess with a hard timeout: a wedged remote-dispatch tunnel
    hangs `import jax`-adjacent calls forever, and the parent must stay usable to
    fall back to CPU and still emit a result line.

    Retries with growing backoff before giving up: single-tenant tunnels release
    their slot with a lag after the previous client exits, and a transiently
    wedged proxy often recovers within a minute. Round 3 fell back to CPU after
    one 15 s retry and the official bench artifact became a CPU number — the
    fallback must be a last resort, not the first response."""
    if timeout is None:
        timeout = float(os.environ.get("TPU_BENCH_PROBE_TIMEOUT", "240"))
    if attempts is None:
        attempts = int(os.environ.get("TPU_BENCH_PROBE_ATTEMPTS", "3"))
    for attempt in range(attempts):
        # Every attempt gets the FULL window: a retry that lands just after
        # the tunnel slot frees is a fresh subprocess paying the same
        # cold-compile + handshake cost as attempt 1 — shortchanging it
        # reproduces the round-3 "official artifact became a CPU number"
        # incident this function exists to prevent.
        try:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; jax.numpy.ones((2,)).block_until_ready(); "
                    "print('ok', jax.default_backend())",
                ],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                return True
            print(
                f"backend probe attempt {attempt + 1}/{attempts} failed "
                f"(rc={r.returncode}): {r.stderr[-500:]}",
                file=sys.stderr,
            )
        except Exception as e:
            print(
                f"backend probe attempt {attempt + 1}/{attempts} failed: {e!r}",
                file=sys.stderr,
            )
        if attempt < attempts - 1:
            delay = 20.0 * (attempt + 1)
            print(f"retrying backend probe in {delay:.0f} s", file=sys.stderr)
            time.sleep(delay)
    return False


def run_variant_inprocess(variant: str) -> dict:
    """Measure one device variant; invoked in a fresh subprocess by main() so
    variants can't contaminate each other's dispatch latency (observed: measuring
    the ring path after host-baseline + another compiled variant in one process
    inflates push dispatch ~30×; isolated processes reproduce 0.02-0.03 ms)."""
    import jax

    data, counts, truth = make_telemetry()
    if variant == "rings":
        per_step, per_push, per_score, out = device_ring_scoring(
            data, counts, REPORT_INTERVAL
        )
        mask = np.asarray(out.straggler)
        return {
            "per_step": per_step,
            "per_push": per_push,
            "per_score": per_score,
            "f1": f1(mask, truth),
            # The backend the measurement ACTUALLY ran on: a child whose
            # tunnel wedged mid-round can silently fall back to CPU while the
            # parent still believes it probed a live TPU.
            "backend": jax.default_backend(),
        }
    per_step, out = device_scoring(data, counts, variant=variant)
    mask = np.asarray(out.straggler)
    return {"per_step": per_step, "f1": f1(mask, truth), "backend": jax.default_backend()}


def run_variant_subprocess(variant: str) -> dict | None:
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--variant", variant],
            capture_output=True,
            text=True,
            timeout=900,
        )
        if r.returncode != 0:
            print(f"device[{variant}] failed:\n{r.stderr[-2000:]}", file=sys.stderr)
            return None
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as e:
        print(f"device[{variant}] failed: {e!r}", file=sys.stderr)
        return None


def main():
    if not probe_backend_alive():
        # The default backend (e.g. the TPU tunnel) is unreachable or wedged:
        # degrade to CPU so the round still records a (clearly labeled) result.
        print(
            "default JAX backend unresponsive; falling back to JAX_PLATFORMS=cpu",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["TPU_BENCH_CPU_FALLBACK"] = "1"  # variant subprocesses pick ITERS=5
        import jax

        # A site plugin may force-set the platform at interpreter boot; the env
        # var alone does not override an already-selected config.
        jax.config.update("jax_platforms", "cpu")

    data, counts, truth = make_telemetry()

    base_s, base_scores, base_stragglers = baseline_host_scoring(data, counts)
    base_mask = np.zeros(R, dtype=bool)
    base_mask[list(base_stragglers)] = True
    print(
        f"baseline host scoring: {base_s * 1e3:.1f} ms/report, "
        f"F1={f1(base_mask, truth):.3f}",
        file=sys.stderr,
    )

    import jax

    print(f"jax backend: {jax.default_backend()}, devices: {jax.devices()}", file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    backend = jax.default_backend()
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = "unknown"
    backend_tag = "" if on_tpu else f" [backend={backend}]"

    meas_backends: set = set()

    results = {}
    for name in ["xla"] + (
        ["pallas", "pallas-pairwise", "pallas-radix"] if on_tpu else []
    ):
        res = run_variant_subprocess(name)
        if res is not None:
            results[name] = (res["per_step"], res["f1"])
            meas_backends.add(res.get("backend", backend))
            print(
                f"device[{name}]: {res['per_step'] * 1e3:.4f} ms/step, F1={res['f1']:.3f} "
                f"[{res.get('backend', '?')}]",
                file=sys.stderr,
            )

    report_interval = REPORT_INTERVAL
    rings = None
    rings_inprocess = False
    res = run_variant_subprocess("rings")
    if res is None and not results:
        # Every subprocess failed (e.g. a runtime that refuses a second client):
        # degrade to an in-process measurement rather than emitting nothing.
        print("all variant subprocesses failed; measuring in-process", file=sys.stderr)
        try:
            res = run_variant_inprocess("rings")
            rings_inprocess = True
        except Exception as e:
            print(f"in-process rings failed too: {e!r}", file=sys.stderr)
            res = None
    if res is not None:
        per_step, per_push, per_score = res["per_step"], res["per_push"], res["per_score"]
        rings = (per_step, per_push, per_score, res["f1"])
        meas_backends.add(res.get("backend", backend))
        print(
            f"device[rings, honest hot loop]: push {per_push * 1e3:.4f} ms/step + "
            f"score {per_score * 1e3:.3f} ms/report / {report_interval} steps "
            f"= {per_step * 1e3:.4f} ms/step, F1={rings[3]:.3f}",
            file=sys.stderr,
        )

    for name, (s, f) in results.items():
        print(f"score-only[{name}]: {s * 1e3:.4f} ms/report", file=sys.stderr)
    if rings is None and not results:
        line = {
            "metric": "telemetry hot-loop cost (ALL VARIANTS FAILED; see stderr)",
            "value": None,
            "unit": "ms/step",
            "vs_baseline": None,
            "backend": backend,
            "device_kind": device_kind,
        }
        if backend != "tpu":
            line["backend_fallback"] = True
        print(json.dumps(line))
        return
    if rings is None:
        # Fall back to the score-only fused number if the ring path broke. This is
        # a per-REPORT latency — label the unit accordingly so downstream readers
        # never compare it against the per-step hot-loop metric.
        best_name, (best_s, best_f1) = min(results.items(), key=lambda kv: kv[1][0])
        metric = (
            f"fused telemetry scoring latency ({best_name}, score-only), {R} ranks x "
            f"{S} signals x {W} window (F1={best_f1:.3f}){backend_tag}"
        )
        value_s = best_s
        vs = base_s / best_s
        unit = "ms/report"
    else:
        per_step, per_push, per_score, rings_f1 = rings
        caveat = (
            " [IN-PROCESS FALLBACK: subject to same-process dispatch contamination, "
            "see BASELINE.md measurement-integrity note]"
            if rings_inprocess
            else ""
        )
        metric = (
            f"telemetry hot-loop cost, {R} ranks x {S} signals x {W} window: in-jit "
            f"ring push/step + fused scoring/report amortized over {report_interval} "
            f"steps (push {per_push * 1e3:.4f} ms, score {per_score * 1e3:.3f} ms, "
            f"F1={rings_f1:.3f}){caveat}{backend_tag}"
        )
        value_s = per_step
        # Baseline pays its host report at the same cadence plus zero per-step cost
        # (its per-step ingestion is host-dict appends, unmeasurably small but also
        # off-device); compare amortized report cost against amortized honest cost.
        vs = (base_s / report_interval) / per_step
        unit = "ms/step"
    # The backend that PRODUCED the reported numbers: a variant subprocess can
    # silently fall back to CPU (wedged tunnel mid-round) while the parent's
    # probe saw a live TPU — trust the measurements' own report over the
    # parent's view.
    if meas_backends:
        effective_backend = (
            meas_backends.pop() if len(meas_backends) == 1
            else "mixed:" + ",".join(sorted(meas_backends))
        )
    else:
        effective_backend = backend
    if effective_backend != backend:
        device_kind = effective_backend  # parent's device_kind describes the wrong backend
    line = {
        "metric": metric,
        "value": round(value_s * 1e3, 4),
        "unit": unit,
        "vs_baseline": round(vs, 2),
        "backend": effective_backend,
        "device_kind": device_kind,
    }
    if effective_backend != "tpu":
        # The BASELINE.md baseline is a host-Python number measured to be beaten
        # by a DEVICE program; a CPU-simulated device path "beating" it is not
        # the product claim. Never let a fallback run masquerade as one.
        line["backend_fallback"] = True
        line["vs_baseline"] = None
    print(json.dumps(line))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default=None, help="internal: measure one variant")
    args = ap.parse_args()
    if args.variant:
        if os.environ.get("TPU_BENCH_CPU_FALLBACK") == "1":
            ITERS = 5  # module scope: rebinds the global
            import jax

            jax.config.update("jax_platforms", "cpu")
        print(json.dumps(run_variant_inprocess(args.variant)))
    else:
        main()
