"""North-star benchmark (BASELINE.json config 4): score 4096-rank heartbeat+perf fused
telemetry — per-rank per-signal timing windows reduced to straggler scores — on one TPU
chip, vs a host-side emulation of the reference's Python scoring path.

Baseline emulation re-implements, from the spec in SURVEY.md §2.5/§3.5 (NOT copied), what
the reference's ``ReportGenerator.generate_report`` does on host per report: per-rank
dicts of per-signal sample lists → per-signal medians + totals (Python loop over dict
entries), pack medians to a flat vector, min-reduce across ranks, unpack, weighted score
loop, straggler thresholding. The device path is ``telemetry.scoring.score_round`` (and
the Pallas fused-median variant) running as one compiled program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}; details go to stderr.
"""

import json
import sys
import time

import numpy as np

R, S, W = 4096, 64, 32
SLOW_FRACTION = 0.05
SLOWDOWN = 1.6
ITERS = 50


def make_telemetry(seed=0):
    rng = np.random.default_rng(seed)
    base = rng.uniform(0.8, 1.2, size=(1, S, 1)).astype(np.float32)
    data = base * (1.0 + 0.05 * rng.standard_normal((R, S, W)).astype(np.float32))
    n_slow = int(R * SLOW_FRACTION)
    slow_ranks = rng.choice(R, size=n_slow, replace=False)
    data[slow_ranks] *= SLOWDOWN
    counts = np.full((R, S), W, dtype=np.int32)
    truth = np.zeros(R, dtype=bool)
    truth[slow_ranks] = True
    return data, counts, truth


def baseline_host_scoring(data, counts, threshold=0.75):
    """Reference-style host scoring: dict-of-lists telemetry, Python pack/unpack loops."""
    # per-rank summaries as the reference holds them: dict rank -> {signal_name: samples}
    telemetry = {
        r: {f"sig{s}": data[r, s, : counts[r, s]].tolist() for s in range(S)} for r in range(R)
    }
    t0 = time.perf_counter()
    medians, totals = {}, {}
    for r, sigs in telemetry.items():
        med_r, tot_r = {}, {}
        for name, samples in sigs.items():
            arr = np.asarray(samples)
            med_r[name] = float(np.median(arr))
            tot_r[name] = float(arr.sum())
        medians[r] = med_r
        totals[r] = tot_r
    # pack → min-reduce across ranks → unpack (the all_reduce(MIN) emulation)
    names = sorted(medians[0])
    packed = np.array([[medians[r][n] for n in names] for r in range(R)])
    ref = packed.min(axis=0)
    # weighted per-rank score loop
    scores = {}
    for r in range(R):
        num = den = 0.0
        for j, n in enumerate(names):
            w = totals[r][n]
            num += w * (ref[j] / medians[r][n])
            den += w
        scores[r] = num / den
    stragglers = {r for r, sc in scores.items() if sc < threshold}
    elapsed = time.perf_counter() - t0
    return elapsed, scores, stragglers


def f1(pred_mask, truth):
    tp = int((pred_mask & truth).sum())
    fp = int((pred_mask & ~truth).sum())
    fn = int((~pred_mask & truth).sum())
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def device_scoring(data, counts, use_pallas):
    import jax
    import jax.numpy as jnp

    from tpu_resiliency.telemetry import scoring

    if use_pallas:
        from tpu_resiliency.ops.scoring_pallas import fused_median_weights

        def run(d, c, e, h):
            mw = fused_median_weights(d, c)
            return scoring.score_round(d, c, e, h, medians_and_weights=mw)

        fn = jax.jit(run)
    else:
        def run(d, c, e, h):
            return scoring.score_round(d, c, e, h)

        fn = jax.jit(run)

    d = jnp.asarray(data)
    c = jnp.asarray(counts)
    ewma = jnp.ones((R,))
    hist = jnp.full((R, S), jnp.inf)
    out = fn(d, c, ewma, hist)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        # chain each step on the previous round's EWMA so steps are data-dependent
        # (no overlap artifacts in the timing)
        out = fn(d, c, out.ewma, hist)
    jax.block_until_ready(out)
    per_step = (time.perf_counter() - t0) / ITERS
    return per_step, out


def device_ring_scoring(data, counts):
    """The full north-star hot loop: device-resident sharded rings fed in-jit
    (donated carry) + the mesh scoring program, every step. Ingestion cost is
    included — this is what a train step actually pays."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from tpu_resiliency.telemetry.sharded import MeshTelemetry

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("rank",))
    mt = MeshTelemetry(
        mesh, "rank", n_ranks=R,
        signal_names=tuple(f"sig{s}" for s in range(S)), window=W,
    )
    state = mt.init_state()
    # Pre-split step rows: indexing a device array with a fresh static index inside
    # the timed loop would compile a new slice program per index.
    rows = [jnp.asarray(data[:, :, i]) for i in range(W)]
    for i in range(W):
        state = mt.push(state, rows[i])
    # warm both programs
    state, out = mt.score(state)
    jax.block_until_ready((state, out))
    t0 = time.perf_counter()
    for i in range(ITERS):
        state = mt.push(state, rows[i % W])
        state, out = mt.score(state)
    jax.block_until_ready((state, out))
    per_step = (time.perf_counter() - t0) / ITERS
    # Rebuild a full window so the F1 check sees real scores, not a 1-sample round.
    for i in range(W):
        state = mt.push(state, rows[i])
    _, out = mt.score(state)
    return per_step, out


def main():
    data, counts, truth = make_telemetry()

    base_s, base_scores, base_stragglers = baseline_host_scoring(data, counts)
    base_mask = np.zeros(R, dtype=bool)
    base_mask[list(base_stragglers)] = True
    print(
        f"baseline host scoring: {base_s * 1e3:.1f} ms/report, "
        f"F1={f1(base_mask, truth):.3f}",
        file=sys.stderr,
    )

    import jax

    print(f"jax backend: {jax.default_backend()}, devices: {jax.devices()}", file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"

    results = {}
    variants = [("xla", False)] + ([("pallas", True)] if on_tpu else [])
    for name, use_pallas in variants:
        try:
            per_step, out = device_scoring(data, counts, use_pallas)
            mask = np.asarray(out.straggler)
            results[name] = (per_step, f1(mask, truth))
            print(
                f"device[{name}]: {per_step * 1e3:.3f} ms/step, F1={results[name][1]:.3f}",
                file=sys.stderr,
            )
        except Exception as e:
            print(f"device[{name}] failed: {e!r}", file=sys.stderr)
    try:
        per_step, out = device_ring_scoring(data, counts)
        mask = np.asarray(out.straggler)
        print(
            f"device[rings: in-jit push + score]: {per_step * 1e3:.3f} ms/step, "
            f"F1={f1(mask, truth):.3f}",
            file=sys.stderr,
        )
        results["rings"] = (per_step, f1(mask, truth))
    except Exception as e:
        print(f"device[rings] failed: {e!r}", file=sys.stderr)

    best_name, (best_s, best_f1) = min(results.items(), key=lambda kv: kv[1][0])
    print(f"best variant: {best_name}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": f"fused telemetry scoring latency, {R} ranks x {S} signals x {W} window (F1={best_f1:.3f})",
                "value": round(best_s * 1e3, 4),
                "unit": "ms/step",
                "vs_baseline": round(base_s / best_s, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
