"""Recovery-latency benchmark: in-process restart vs in-job respawn, measured.

The reference claims the benefit qualitatively — in-process restart "removes
scheduler job launch, container start, interpreter init, dependency load, CUDA
context creation from the recovery path" (``docs/source/inprocess/index.rst:13-22``)
— but publishes no numbers (BASELINE.md). This harness measures both restart layers
of THIS framework on the same machine:

- **In-process engine latency** (world 2, forked ranks): a rank's fn raises; the
  latency is fault → fn re-entry on the SAME process, covering quiesce, abort,
  finalize, health check, iteration barrier, and rank reassignment — everything the
  engine adds on top of the user's own re-init. Measured on the faulting rank and
  on the healthy peer (whose figure adds cross-rank fault propagation).
- **In-job respawn latency** (tpu-ft-launcher, 2 workers): a worker exits nonzero;
  the latency is worker exit → re-spawned worker's ``main()`` entry, covering agent
  detection, the rendezvous round, process spawn, and interpreter+import startup.

Usage::

    python scripts/bench_restart.py [--restarts N] [--out FILE]

Prints one JSON line per layer and writes ``BENCH_restart.json``.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------- in-process --


def _inproc_rank(rank: int, port: int, n_restarts: int, q) -> None:
    os.environ.update(
        RANK=str(rank),
        WORLD_SIZE="2",
        TPU_RESILIENCY_STORE_PORT=str(port),
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
    )
    from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper

    fault_times: list[float] = []
    entry_times: list[float] = []

    @Wrapper(
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=30.0,
        hard_timeout=60.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=20.0,
        barrier_timeout=60.0,
        completion_timeout=60.0,
    )
    def train(call: CallWrapper):
        entry_times.append(time.monotonic())
        if call.iteration < n_restarts:
            if rank == 0:
                time.sleep(0.05)  # let the peer enter its fn before the fault
                fault_times.append(time.monotonic())
                raise RuntimeError(f"bench fault {call.iteration}")
            # Healthy peer: park until the engine interrupts us.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                time.sleep(0.01)
            return "peer-timeout"
        return "done"

    result = train()
    q.put((rank, result, fault_times, entry_times))


def bench_inprocess(n_restarts: int) -> dict:
    port = free_port()
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_inproc_rank, args=(r, port, n_restarts, q))
        for r in range(2)
    ]
    t0 = time.monotonic()
    for p in procs:
        p.start()
    out = {}
    deadline = time.monotonic() + 120
    while len(out) < 2 and time.monotonic() < deadline:
        try:
            rank, result, faults, entries = q.get(timeout=1.0)
            out[rank] = (result, faults, entries)
        except Exception:
            pass
    for p in procs:
        p.join(20.0)
        if p.is_alive():
            p.terminate()
    assert out[0][0] == "done" and out[1][0] == "done", out

    _, faults, entries0 = out[0]
    _, _, entries1 = out[1]
    # Faulting rank: fault i happens in iteration i; re-entry is entries[i+1].
    own = [entries0[i + 1] - faults[i] for i in range(n_restarts)]
    # Healthy peer: its re-entry i+1 measured from the same fault instant.
    peer = [entries1[i + 1] - faults[i] for i in range(n_restarts)]
    return {
        "restarts": n_restarts,
        "faulting_rank_ms": {
            "median": sorted(own)[len(own) // 2] * 1e3,
            "min": min(own) * 1e3,
            "max": max(own) * 1e3,
        },
        "healthy_peer_ms": {
            "median": sorted(peer)[len(peer) // 2] * 1e3,
            "min": min(peer) * 1e3,
            "max": max(peer) * 1e3,
        },
        "startup_to_first_entry_s": entries0[0] - t0,
    }


# ------------------------------------------------------------------- in-job --

WORKER = """
import os, sys, time
stamp_dir = sys.argv[1]
count = int(os.environ.get("TPU_FT_RESTART_COUNT", "0"))
with open(os.path.join(stamp_dir, f"entry_{count}_{os.environ['RANK']}"), "w") as f:
    f.write(repr(time.time()))
if count == 0 and os.environ["RANK"] == "0":
    with open(os.path.join(stamp_dir, "exit_0"), "w") as f:
        f.write(repr(time.time()))
    sys.exit(1)
time.sleep(0.5)
"""


def bench_injob(warm_spares: int = 0) -> dict:
    """Respawn latency, decomposed from the launcher's own structured event stream
    (wall-clock, same clock as the worker stamps): worker exit → failure detection →
    next rendezvous round closing → respawned worker's first Python statement. The
    last segment is dominated by the environment's interpreter/plugin startup tax,
    measured separately as a median-of-3 floor with the same env.

    ``warm_spares`` > 0 measures the warm path: parked pre-imported
    interpreters (``launcher/park.py``) serve the restart round, removing the
    interpreter floor from the critical path."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    floors = []
    for _ in range(3):
        t0 = time.monotonic()
        subprocess.run([sys.executable, "-c", "pass"], env=env, check=True)
        floors.append((time.monotonic() - t0) * 1e3)
    startup_ms = sorted(floors)[1]

    with tempfile.TemporaryDirectory() as td:
        worker = os.path.join(td, "worker.py")
        with open(worker, "w") as f:
            f.write(WORKER)
        stamps = os.path.join(td, "stamps")
        os.makedirs(stamps)
        events = os.path.join(td, "events.jsonl")
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_resiliency.launcher.launch",
                "--nproc-per-node", "2", "--max-restarts", "2",
                # Private ephemeral store: the default endpoint port may be
                # transiently occupied by unrelated jobs/tests on this host.
                "--rdzv-endpoint", "127.0.0.1:0",
                "--monitor-interval", "0.1",
                "--events-file", events,
                "--warm-spares", str(warm_spares),
                "--warm-spare-preload", "json",
                worker, stamps,
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=180,
            cwd=td,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]

        def read(name):
            with open(os.path.join(stamps, name)) as f:
                return float(f.read())

        evs = [json.loads(line) for line in open(events)]
        t_fail = next(e["ts"] for e in evs if e.get("kind") == "worker_failed")
        rounds = [e["ts"] for e in evs if e.get("kind") == "rendezvous_round"]
        t_round1 = next(ts for ts in rounds if ts > t_fail)

        t_exit = read("exit_0")
        t_reentry = read("entry_1_0")
        return {
            "respawn_ms": (t_reentry - t_exit) * 1e3,
            "detect_ms": (t_fail - t_exit) * 1e3,
            "rendezvous_ms": (t_round1 - t_fail) * 1e3,
            # monitor forks + Popen of both workers (concurrent) + one interpreter
            # startup on the critical path
            "spawn_and_startup_ms": (t_reentry - t_round1) * 1e3,
            "python_startup_floor_ms": startup_ms,
        }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--restarts", type=int, default=5)
    ap.add_argument("--out", default=os.path.join(REPO_ROOT, "BENCH_restart.json"))
    args = ap.parse_args()

    inproc = bench_inprocess(args.restarts)
    print(json.dumps({"layer": "in-process", **inproc}))
    injob = bench_injob()
    print(json.dumps({"layer": "in-job", **injob}))
    injob_warm = bench_injob(warm_spares=2)
    print(json.dumps({"layer": "in-job-warm", **injob_warm}))

    speedup = injob["respawn_ms"] / inproc["faulting_rank_ms"]["median"]
    summary = {
        "in_process": inproc,
        "in_job": injob,
        "in_job_warm_spares": injob_warm,
        "speedup_in_process_vs_in_job": speedup,
        "warm_spare_respawn_speedup": injob["respawn_ms"] / injob_warm["respawn_ms"],
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({
        "metric": "recovery latency: in-process engine (median, faulting rank) vs in-job respawn",
        "in_process_ms": round(inproc["faulting_rank_ms"]["median"], 1),
        "in_job_ms": round(injob["respawn_ms"], 1),
        "in_job_warm_ms": round(injob_warm["respawn_ms"], 1),
        "speedup": round(speedup, 1),
    }))


if __name__ == "__main__":
    main()
