"""Recovery-latency benchmark: in-process restart vs in-job respawn, measured.

The reference claims the benefit qualitatively — in-process restart "removes
scheduler job launch, container start, interpreter init, dependency load, CUDA
context creation from the recovery path" (``docs/source/inprocess/index.rst:13-22``)
— but publishes no numbers (BASELINE.md). This harness measures the restart
layers of THIS framework on the same machine:

- **In-process engine latency** (world 2, forked ranks): a rank's fn raises; the
  latency is fault → fn re-entry on the SAME process, covering quiesce, abort,
  finalize, health check, iteration barrier, and rank reassignment — everything the
  engine adds on top of the user's own re-init. Measured on the faulting rank and
  on the healthy peer (whose figure adds cross-rank fault propagation).
- **In-job respawn latency** (tpu-ft-launcher, 2 workers): a worker exits nonzero;
  the latency is worker exit → re-spawned worker's ``main()`` entry, decomposed
  from the launcher's own event stream into **detect** (fault injection →
  ``wait_change`` return, the ``failure_detected`` event) / **teardown**
  (failure handling + worker stop) / **rendezvous** (restart request → next
  round placed) / **promote + first-step-ready** (round placed → promoted
  worker's first Python statement). The warm leg parks runtime-warmed spares
  and rides the fast-path rendezvous; the cold leg is the full ladder + spawn.
- **Fast-path rendezvous micro-bench**: N simulated agents on loopback run
  replacement rounds with the full open/join/close ladder vs the single-CAS
  round-reuse fast path.
- **Compile-cache restart leg**: a jitting worker crashes once; round 1 must
  record a persistent-compilation-cache **hit** and a (much) cheaper re-jit.

Usage::

    python scripts/bench_restart.py [--restarts N] [--out FILE] [--smoke]

Prints one JSON line per layer and writes ``BENCH_restart.json``.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import socket
import statistics
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------- in-process --


def _inproc_rank(rank: int, port: int, n_restarts: int, q) -> None:
    os.environ.update(
        RANK=str(rank),
        WORLD_SIZE="2",
        TPU_RESILIENCY_STORE_PORT=str(port),
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
    )
    from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper

    fault_times: list[float] = []
    entry_times: list[float] = []

    @Wrapper(
        monitor_interval=0.05,
        last_call_wait=0.1,
        soft_timeout=30.0,
        hard_timeout=60.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=20.0,
        barrier_timeout=60.0,
        completion_timeout=60.0,
    )
    def train(call: CallWrapper):
        entry_times.append(time.monotonic())
        if call.iteration < n_restarts:
            if rank == 0:
                time.sleep(0.05)  # let the peer enter its fn before the fault
                fault_times.append(time.monotonic())
                raise RuntimeError(f"bench fault {call.iteration}")
            # Healthy peer: park until the engine interrupts us.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                time.sleep(0.01)
            return "peer-timeout"
        return "done"

    result = train()
    q.put((rank, result, fault_times, entry_times))


def bench_inprocess(n_restarts: int) -> dict:
    port = free_port()
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_inproc_rank, args=(r, port, n_restarts, q))
        for r in range(2)
    ]
    t0 = time.monotonic()
    for p in procs:
        p.start()
    out = {}
    deadline = time.monotonic() + 120
    while len(out) < 2 and time.monotonic() < deadline:
        try:
            rank, result, faults, entries = q.get(timeout=1.0)
            out[rank] = (result, faults, entries)
        except Exception:
            pass
    for p in procs:
        p.join(20.0)
        if p.is_alive():
            p.terminate()
    assert out[0][0] == "done" and out[1][0] == "done", out

    _, faults, entries0 = out[0]
    _, _, entries1 = out[1]
    # Faulting rank: fault i happens in iteration i; re-entry is entries[i+1].
    own = [entries0[i + 1] - faults[i] for i in range(n_restarts)]
    # Healthy peer: its re-entry i+1 measured from the same fault instant.
    peer = [entries1[i + 1] - faults[i] for i in range(n_restarts)]
    return {
        "restarts": n_restarts,
        "faulting_rank_ms": {
            "median": sorted(own)[len(own) // 2] * 1e3,
            "min": min(own) * 1e3,
            "max": max(own) * 1e3,
        },
        "healthy_peer_ms": {
            "median": sorted(peer)[len(peer) // 2] * 1e3,
            "min": min(peer) * 1e3,
            "max": max(peer) * 1e3,
        },
        "startup_to_first_entry_s": entries0[0] - t0,
    }


# ------------------------------------------------------------------- in-job --

# Round 0 rank 0: optionally wait for a warm spare (deterministic promotion —
# detection+rendezvous are now fast enough that an immediate crash can beat
# the spare's own warm-up), stamp the fault instant, exit 1. Round 1: stamp
# re-entry.
WORKER = """
import glob, os, sys, time
stamp_dir = sys.argv[1]
spares_glob = sys.argv[2] if len(sys.argv) > 2 else ""
count = int(os.environ.get("TPU_FT_RESTART_COUNT", "0"))
with open(os.path.join(stamp_dir, f"entry_{count}_{os.environ['RANK']}"), "w") as f:
    f.write(repr(time.time()))
if count == 0 and os.environ["RANK"] == "0":
    if spares_glob:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            ready = [p for p in glob.glob(spares_glob) if not p.endswith(".tmp")]
            if ready:
                break
            time.sleep(0.02)
        else:
            sys.exit(17)  # spare never went warm: fail loudly, not flakily
    with open(os.path.join(stamp_dir, "exit_0"), "w") as f:
        f.write(repr(time.time()))
    sys.exit(1)
time.sleep(0.5)
"""


def bench_injob(warm_spares: int = 0, fast_path: bool = True) -> dict:
    """Respawn latency, decomposed from the launcher's own structured event
    stream by ``tools/critpath.py:restart_decomposition`` — the SAME code
    path ``tpu-critpath`` runs for operators, anchored here at the worker's
    own fault/re-entry stamps (same wall clock as the stream):

    - ``detect_ms``: fault injection (the worker's exit stamp) →
      ``failure_detected`` (the supervise loop's ``wait_change`` return) —
      reaper-event wakeup, identical for cold and promoted workers.
    - ``teardown_ms``: ``failure_detected`` → ``restart_requested`` (failure
      records, hang census, worker-group stop).
    - ``rendezvous_ms``: ``restart_requested`` → the replacement
      ``rendezvous_round`` (fast path: one CAS + barrier; ladder otherwise).
    - ``promote_ms`` / ``first_step_ready_ms``: round placed →
      ``worker_promoted`` → the promoted worker's first Python statement
      (cold runs report the combined segment as ``spawn_and_startup_ms``).

    The interpreter/plugin startup tax is measured separately as a
    median-of-3 floor with the same env."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    floors = []
    for _ in range(3):
        t0 = time.monotonic()
        subprocess.run([sys.executable, "-c", "pass"], env=env, check=True)
        floors.append((time.monotonic() - t0) * 1e3)
    startup_ms = sorted(floors)[1]

    with tempfile.TemporaryDirectory() as td:
        worker = os.path.join(td, "worker.py")
        with open(worker, "w") as f:
            f.write(WORKER)
        stamps = os.path.join(td, "stamps")
        os.makedirs(stamps)
        events = os.path.join(td, "events.jsonl")
        run_dir = os.path.join(td, "run")
        argv = [
            sys.executable, "-m", "tpu_resiliency.launcher.launch",
            "--nproc-per-node", "2", "--max-restarts", "2",
            # Private ephemeral store: the default endpoint port may be
            # transiently occupied by unrelated jobs/tests on this host.
            "--rdzv-endpoint", "127.0.0.1:0",
            "--monitor-interval", "0.1",
            "--events-file", events,
            "--run-dir", run_dir,
            "--warm-spares", str(warm_spares),
            "--warm-spare-preload", "json",
            "--warm-spare-warmup", "runtime" if warm_spares else "imports",
        ]
        if not fast_path:
            argv.append("--no-rdzv-fast-path")
        argv.append(worker)
        argv.append(stamps)
        if warm_spares:
            argv.append(os.path.join(run_dir, "spares", "ready_*"))
        proc = subprocess.run(
            argv, env=env, capture_output=True, text=True, timeout=180, cwd=td,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]

        def read(name):
            with open(os.path.join(stamps, name)) as f:
                return float(f.read())

        from tpu_resiliency.tools.critpath import restart_decomposition

        evs = [json.loads(line) for line in open(events)]
        t_exit = read("exit_0")
        t_reentry = read("entry_1_0")
        dec = restart_decomposition(evs, fault_ts=t_exit, resume_ts=t_reentry)
        assert dec is not None, "no restart episode in the event stream"
        segs = {s["name"]: s["duration_ms"] for s in dec["segments"]}
        out = {
            "respawn_ms": (t_reentry - t_exit) * 1e3,
            "detect_ms": segs["detect"],
            "teardown_ms": segs["teardown"],
            "rendezvous_ms": segs["rendezvous"],
            "fast_path_rendezvous": dec["fast_path"],
            "python_startup_floor_ms": startup_ms,
        }
        if warm_spares:
            assert dec["promoted"], "warm leg never promoted a spare"
            out["promote_ms"] = segs["promote"]
            # Clamped: the promoted shim starts executing the instant the spec
            # hits its pipe, which can beat the launcher's own event stamp by
            # a fraction of a millisecond.
            out["first_step_ready_ms"] = max(0.0, segs["first_step_ready"])
        else:
            out["spawn_and_startup_ms"] = segs["spawn_and_startup"]
        return out


# -------------------------------------------------- fast-path rendezvous ----


def bench_rendezvous_fastpath(nodes: int = 16, rounds: int = 8) -> dict:
    """Replacement-round latency, full ladder vs fast path: N simulated agents
    on one loopback store run ``rounds`` restart rounds per mode; the figure
    is the wall time from the restart request until EVERY agent is placed."""
    from tpu_resiliency.launcher.rendezvous import (
        RendezvousSettings,
        StoreRendezvous,
    )
    from tpu_resiliency.platform.store import CoordStore, KVServer

    server = KVServer(host="127.0.0.1", port=0)
    try:

        def run_mode(fast: bool) -> list[float]:
            prefix = f"bench_{'fast' if fast else 'ladder'}/"
            stores, rdzvs = [], []
            for i in range(nodes):
                st = CoordStore("127.0.0.1", server.port, prefix=prefix)
                rdzvs.append(
                    StoreRendezvous(
                        st, f"n{i}",
                        RendezvousSettings(
                            min_nodes=nodes, max_nodes=nodes,
                            last_call_timeout=0.3,
                            keep_alive_interval=0.1, keep_alive_timeout=10.0,
                            poll_interval=0.05, fast_path=fast,
                        ),
                    )
                )
                stores.append(st)

            def place_all(prev: int) -> None:
                errs: list = []

                def run(r):
                    try:
                        r.next_round(prev)
                    except Exception as e:
                        errs.append(e)

                ts = [threading.Thread(target=run, args=(r,)) for r in rdzvs]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(30.0)
                assert not errs, errs

            place_all(-1)
            times = []
            for rnd in range(rounds):
                rdzvs[0].request_restart(f"bench {rnd}")
                t0 = time.monotonic()
                place_all(rnd)
                times.append((time.monotonic() - t0) * 1e3)
            for r in rdzvs:
                r.stop_keepalive()
            for s in stores:
                s.close()
            return times

        ladder = run_mode(False)
        fast = run_mode(True)
        return {
            "nodes": nodes,
            "rounds": rounds,
            "full_ladder_ms": {
                "median": statistics.median(ladder),
                "min": min(ladder), "max": max(ladder),
            },
            "fast_path_ms": {
                "median": statistics.median(fast),
                "min": min(fast), "max": max(fast),
            },
            "speedup": statistics.median(ladder) / statistics.median(fast),
        }
    finally:
        server.close()


# ------------------------------------------------------- compile cache ------

JIT_WORKER = """
import json, os, sys, time
from tpu_resiliency.platform import device
device.apply_platform_env()  # applies the compile cache + records its event
import jax, jax.numpy as jnp
count = int(os.environ.get("TPU_FT_RESTART_COUNT", "0"))
t0 = time.monotonic()
f = jax.jit(lambda x: jnp.tanh(x @ x.T).sum())
jax.block_until_ready(f(jnp.ones((256, 256), jnp.float32)))
jit_ms = (time.monotonic() - t0) * 1e3
with open(os.path.join(sys.argv[1], f"jit_{count}.json"), "w") as fh:
    json.dump({"jit_ms": jit_ms}, fh)
if count == 0:
    sys.exit(1)
"""


def bench_compile_cache() -> dict:
    """A jitting worker crashes once; the replacement round must find the
    persistent compilation cache warm (outcome=hit) and re-jit cheaper."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    with tempfile.TemporaryDirectory() as td:
        worker = os.path.join(td, "worker.py")
        with open(worker, "w") as f:
            f.write(JIT_WORKER)
        stamps = os.path.join(td, "stamps")
        os.makedirs(stamps)
        events = os.path.join(td, "events.jsonl")
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_resiliency.launcher.launch",
                "--standalone", "--nproc-per-node", "1", "--max-restarts", "2",
                "--no-ft-monitors", "--monitor-interval", "0.1",
                "--events-file", events,
                "--compile-cache-dir", os.path.join(td, "compile_cache"),
                "--run-dir", os.path.join(td, "run"),
                worker, stamps,
            ],
            env=env, capture_output=True, text=True, timeout=300, cwd=td,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        evs = [json.loads(line) for line in open(events)]
        cc = [e for e in evs if e.get("kind") == "compile_cache"]
        assert len(cc) >= 2, cc
        outcomes = [e["outcome"] for e in cc]

        def read(name):
            return json.load(open(os.path.join(stamps, name)))

        return {
            "first_jit_ms": read("jit_0.json")["jit_ms"],
            "restart_jit_ms": read("jit_1.json")["jit_ms"],
            "outcomes": outcomes,
            "restart_hit": outcomes[-1] == "hit",
            "cache_bytes": cc[-1].get("bytes", 0),
        }


# -------------------------------------------------------------------- main --


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--restarts", type=int, default=5)
    ap.add_argument("--out", default=os.path.join(REPO_ROOT, "BENCH_restart.json"))
    ap.add_argument(
        "--smoke", action="store_true",
        help="reduced-rep sanity pass for CI: runs every leg once and asserts "
        "the structural claims (promotion, fast path, cache hit) without "
        "writing the committed bench file",
    )
    args = ap.parse_args()

    if args.smoke:
        injob_warm = bench_injob(warm_spares=2)
        print(json.dumps({"layer": "in-job-warm", **injob_warm}))
        assert injob_warm["fast_path_rendezvous"], "fast-path rendezvous not taken"
        assert "promote_ms" in injob_warm, "no promotion on the warm path"
        fastpath = bench_rendezvous_fastpath(nodes=2, rounds=2)
        print(json.dumps({"layer": "rendezvous-fastpath", **fastpath}))
        cache = bench_compile_cache()
        print(json.dumps({"layer": "compile-cache", **cache}))
        assert cache["restart_hit"], cache
        print(json.dumps({"bench_restart_smoke": "PASS"}))
        return

    inproc = bench_inprocess(args.restarts)
    print(json.dumps({"layer": "in-process", **inproc}))
    injob = bench_injob()
    print(json.dumps({"layer": "in-job", **injob}))
    injob_warm = bench_injob(warm_spares=2)
    print(json.dumps({"layer": "in-job-warm", **injob_warm}))
    fastpath = bench_rendezvous_fastpath()
    print(json.dumps({"layer": "rendezvous-fastpath", **fastpath}))
    cache = bench_compile_cache()
    print(json.dumps({"layer": "compile-cache", **cache}))

    speedup = injob["respawn_ms"] / inproc["faulting_rank_ms"]["median"]
    summary = {
        "in_process": inproc,
        "in_job": injob,
        "in_job_warm_spares": injob_warm,
        "rendezvous_fastpath": fastpath,
        "compile_cache": cache,
        "speedup_in_process_vs_in_job": speedup,
        "warm_spare_respawn_speedup": injob["respawn_ms"] / injob_warm["respawn_ms"],
        "warm_vs_in_process_ratio": (
            injob_warm["respawn_ms"] / inproc["faulting_rank_ms"]["median"]
        ),
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({
        "metric": "recovery latency: in-process engine (median, faulting rank) vs in-job respawn",
        "in_process_ms": round(inproc["faulting_rank_ms"]["median"], 1),
        "in_job_ms": round(injob["respawn_ms"], 1),
        "in_job_warm_ms": round(injob_warm["respawn_ms"], 1),
        "speedup": round(speedup, 1),
        "fastpath_rendezvous_speedup": round(fastpath["speedup"], 2),
        "compile_cache_restart_hit": cache["restart_hit"],
    }))


if __name__ == "__main__":
    main()
