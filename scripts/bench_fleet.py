"""Benchmark fleet federation: scrape cost vs job count + crash containment.

The fleet acceptance story, measured: N concurrent 2-rank chaos jobs (each a
real `tpu-ft-launcher --standalone --fleet-dir ...` run on loopback whose
rank 0 faults once in round 0, so every job exercises a restart while being
scraped) registered in one fleet dir, with a fleetd aggregator scraping them.

Two gates:

- **sub-linear scrape cost**: one scrape fans out in parallel, so its wall
  clock tracks the slowest job, not the sum — p95 scrape time at the largest
  fleet must come in well under the linear extrapolation from the smallest
  (`p95_max < p95_min * (N_max/N_min) * SUBLINEAR_FACTOR`).
- **crash containment**: SIGKILL one whole job (launcher + workers, the
  process group) while the scrape loop keeps running; every `/fleet/*`
  endpoint must keep answering 200 with the dead job reported `unreachable`.

The committed run is BENCH_fleet.json, regression-anchored by the
slow-marked ``tests/fleet/test_fleet_perf.py``.

    python scripts/bench_fleet.py [--sizes 2,4,8] [--scrapes 20] [--out BENCH_fleet.json]
    python scripts/bench_fleet.py --smoke
"""

import argparse
import json
import os
import platform
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tpu_resiliency.fleet.aggregator import FleetAggregator  # noqa: E402
from tpu_resiliency.fleet.server import FleetServer  # noqa: E402

#: the sub-linear bar: p95 at the largest fleet vs linear extrapolation from
#: the smallest — 0.75 means "at least 25% better than linear", comfortably
#: cleared by parallel fan-out (near-flat) yet robust to loopback noise
SUBLINEAR_FACTOR = 0.75

FLEET_ENDPOINTS = (
    "/fleet/metrics", "/fleet/goodput", "/fleet/slo", "/fleet/incidents",
    "/fleet/hangz", "/fleet/snapshot",
)

WORKER = """\
import os, sys, time
from tpu_resiliency.utils.events import record

stop = sys.argv[1]
round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
rank = int(os.environ.get("RANK", "0"))
for i in range(5):
    record("inprocess", "iteration_start", iteration=i)
    time.sleep(0.02)
if round_no == 0 and rank == 0:
    sys.exit(3)  # the chaos leg: every job pays one real restart
i = 5
deadline = time.time() + 180
while not os.path.exists(stop) and time.time() < deadline:
    record("inprocess", "iteration_start", iteration=i)
    i += 1
    time.sleep(0.25)
"""


def launch_job(workdir: str, fleet_dir: str, idx: int) -> subprocess.Popen:
    job_dir = os.path.join(workdir, f"job{idx}")
    os.makedirs(job_dir, exist_ok=True)
    worker = os.path.join(workdir, "worker.py")
    # One process group per job so the SIGKILL leg kills launcher AND workers
    # in one shot — the way a node loss would.
    return subprocess.Popen(
        [
            sys.executable, "-m", "tpu_resiliency.launcher.launch",
            "--standalone", "--nproc-per-node", "2", "--max-restarts", "2",
            "--no-ft-monitors", "--rdzv-last-call", "0.2",
            "--monitor-interval", "0.1",
            "--rdzv-id", f"bench-job-{idx}",
            "--fleet-dir", fleet_dir,
            "--events-file", os.path.join(job_dir, "events.jsonl"),
            "--run-dir", os.path.join(job_dir, "run"),
            worker, os.path.join(workdir, "stop"),
        ],
        stdout=open(os.path.join(job_dir, "launcher.log"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        },
    )


def wait_reachable(agg: FleetAggregator, want: int, deadline_s: float = 90.0):
    deadline = time.time() + deadline_s
    ok: list = []
    while time.time() < deadline:
        view = agg.scrape()
        ok = [s for s in view.states if s["reachable"]]
        if len(ok) >= want:
            return view
        time.sleep(0.3)
    raise RuntimeError(
        f"only {len(ok)} of {want} jobs became scrapeable in {deadline_s}s"
    )


def measure_size(agg: FleetAggregator, scrapes: int) -> dict:
    times = []
    jobs = None
    for _ in range(scrapes):
        t0 = time.monotonic()
        view = agg.scrape()
        times.append(time.monotonic() - t0)
        jobs = len(view.states)
    times.sort()
    return {
        "jobs": jobs,
        "scrapes": scrapes,
        "p50_s": round(times[len(times) // 2], 6),
        "p95_s": round(times[min(len(times) - 1, int(len(times) * 0.95))], 6),
        "max_s": round(times[-1], 6),
    }


def run(sizes, scrapes, workdir: str) -> dict:
    fleet_dir = os.path.join(workdir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    with open(os.path.join(workdir, "worker.py"), "w") as f:
        f.write(WORKER)
    agg = FleetAggregator(fleet_dir, timeout=5.0)
    procs: list[subprocess.Popen] = []
    results: list[dict] = []
    kill_report: dict = {}
    try:
        for size in sizes:
            while len(procs) < size:
                procs.append(launch_job(workdir, fleet_dir, len(procs)))
            wait_reachable(agg, size)
            for _ in range(3):  # warmup: compile caches, lazy imports settle
                agg.scrape()
            res = measure_size(agg, scrapes)
            print(f"  {res['jobs']} jobs: p50={res['p50_s'] * 1e3:.1f}ms "
                  f"p95={res['p95_s'] * 1e3:.1f}ms")
            results.append(res)

        # -- crash containment: SIGKILL one job's whole process group while
        # the fleet endpoint keeps serving.
        srv = FleetServer(agg, port=0, scrape_ttl=0.0)
        port = srv.start()
        try:
            victim = procs[0]
            os.killpg(os.getpgid(victim.pid), signal.SIGKILL)
            victim.wait(timeout=30)
            statuses: dict = {}
            rows: dict = {}
            deadline = time.time() + 30
            dead_job = "bench-job-0"
            while time.time() < deadline:
                statuses = {}
                for ep in FLEET_ENDPOINTS:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{ep}", timeout=15
                    ) as r:
                        statuses[ep] = r.status
                doc = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleet/goodput", timeout=15))
                rows = {r["job"]: r["status"] for r in doc["jobs"]}
                if rows.get(dead_job) == "unreachable":
                    break
                time.sleep(0.3)
            kill_report = {
                "victim": dead_job,
                "endpoint_status": statuses,
                "all_200": all(s == 200 for s in statuses.values()),
                "victim_status": rows.get(dead_job),
                "survivors_ok": all(
                    st == "ok" for j, st in rows.items() if j != dead_job
                ),
            }
            print(f"  kill leg: endpoints={sorted(set(statuses.values()))} "
                  f"victim={kill_report['victim_status']}")
        finally:
            srv.stop()
    finally:
        with open(os.path.join(workdir, "stop"), "w"):
            pass
        for p in procs:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass

    lo, hi = results[0], results[-1]
    linear = lo["p95_s"] * (hi["jobs"] / lo["jobs"])
    sublinear = {
        "p95_low_s": lo["p95_s"],
        "p95_high_s": hi["p95_s"],
        "jobs_low": lo["jobs"],
        "jobs_high": hi["jobs"],
        "linear_extrapolation_s": round(linear, 6),
        "factor_vs_linear": round(hi["p95_s"] / linear, 6) if linear else None,
        "bar": SUBLINEAR_FACTOR,
        "ok": hi["p95_s"] < linear * SUBLINEAR_FACTOR,
    }
    return {
        "bench": "fleet_federation",
        "host": platform.node(),
        "python": sys.version.split()[0],
        "config": {"sizes": list(sizes), "scrapes": scrapes,
                   "nproc_per_node": 2},
        "scrape_cost": results,
        "sublinear": sublinear,
        "kill": kill_report,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", default=None,
                    help="comma-separated fleet sizes (default 2,4,8)")
    ap.add_argument("--scrapes", type=int, default=None,
                    help="timed scrapes per size (default 20)")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    ap.add_argument("--smoke", action="store_true",
                    help="small sizes, few scrapes — the CI smoke leg")
    args = ap.parse_args(argv)
    if args.smoke:
        sizes = [2, 4] if args.sizes is None else [
            int(s) for s in args.sizes.split(",")]
        scrapes = args.scrapes or 8
    else:
        sizes = [int(s) for s in (args.sizes or "2,4,8").split(",")]
        scrapes = args.scrapes or 20
    with tempfile.TemporaryDirectory(prefix="bench_fleet_") as workdir:
        print(f"fleet bench: sizes={sizes}, {scrapes} scrapes/size "
              f"({workdir})")
        res = run(sizes, scrapes, workdir)
    print(json.dumps(res, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(res, f, indent=2)
            f.write("\n")
        print(f"wrote {args.out}")
    ok = res["sublinear"]["ok"] and res["kill"].get("all_200") \
        and res["kill"].get("victim_status") == "unreachable"
    if not ok:
        print("FAIL: fleet acceptance gates not met", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
