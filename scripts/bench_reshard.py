"""Benchmark elastic reshard: ranged peer fetch vs full-mirror retrieve.

The scenario is the elastic headline: a 4-rank dp world checkpoints with
layout meta, loses rank 3, and the 3 survivors resume resharded. Two ways to
move the bytes a survivor newly owns:

- **ranged** (`LocalCheckpointManager.load_resharded`): fetch ONLY the byte
  ranges of the source shards the target rank's new blocks intersect, over
  the `PeerExchange.fetch_ranges` wire op (per-range CRCs).
- **full-mirror** (what the pre-reshard code forced): every needed source
  container is retrieved WHOLE from a holder, then sliced locally — the
  shape of `CliqueReplicationStrategy.retrieve`.

Both paths run against the same on-disk root over loopback; the report
records wall time and the peer bytes each moved. The interesting number is
``bytes_ratio`` (ranged / full): the ranged path must move strictly fewer
bytes — at this scenario's geometry roughly half a shard instead of whole
containers — and the committed run is the regression anchor for
``tests/checkpoint/test_reshard_perf.py``.

The committed artifact also carries a ``leg_1g`` block (``--with-1g``): the
same scenario at a 1 GB tree, where fixed costs (collectives, plan build)
vanish into the noise and the speedup is pure serve-path pipelining — the
1 GB speedup must EXCEED the 64 MB one, which is the regression gate that
the overlap keeps scaling with payload instead of being a small-payload
artifact. ``--assert-subsecond`` turns the report into a pass/fail check of
the elastic headline: shrink-to-trainable (the slowest survivor's
``load_resharded`` wall) under one second at the gate payload.

    python scripts/bench_reshard.py [--mb 64] [--with-1g] [--out BENCH_reshard.json]
"""

import argparse
import concurrent.futures as cf
import json
import os
import platform
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tpu_resiliency.checkpoint import reshard as R  # noqa: E402
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm  # noqa: E402
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager  # noqa: E402
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy  # noqa: E402
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict  # noqa: E402
from tpu_resiliency.platform.store import CoordStore, KVServer  # noqa: E402
from tpu_resiliency.utils import events as tpu_events  # noqa: E402

WORLD = [0, 1, 2, 3]
SURVIVORS = [0, 1, 2]


def _layout(mb: int):
    # One dp-sharded tree of ~mb MB total: a handful of [rows, 4096] f32
    # leaves, rows divisible by 4 so the saved world is uniform.
    total = mb << 20
    leaf_bytes = min(total, 16 << 20)
    nleaves = max(1, total // leaf_bytes)
    rows = leaf_bytes // (4096 * 4)
    rows -= rows % 4
    leaves = [R.LeafSpec((rows, 4096), "float32", ("dp",)) for _ in range(nleaves)]
    return R.TreeLayout([("dp", 4)], WORLD, leaves)


def _local_tree(layout, rank):
    tree = {}
    for i, spec in enumerate(layout.leaves):
        shape = layout.box(i, rank).shape
        rng = np.random.default_rng(rank * 1000 + i)
        # Zero-padded keys: pytrees flatten in sorted-key order, which must
        # match the layout's leaf order (save() validates this).
        tree[f"leaf{i:03d}"] = rng.standard_normal(shape).astype(np.float32)
    tree["step"] = 1
    return tree


def _run_world(ranks, fn, timeout=600):
    with cf.ThreadPoolExecutor(max_workers=len(ranks)) as pool:
        return [f.result(timeout=timeout) for f in [pool.submit(fn, r) for r in ranks]]


def bench(mb: int) -> dict:
    layout = _layout(mb)
    srv = KVServer(host="127.0.0.1", port=0)
    root = tempfile.mkdtemp(prefix="bench_reshard.")
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    def save_body(rank):
        comm = StoreComm(mk(), rank, WORLD, timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=2
            )
            mgr = LocalCheckpointManager(root, rank=rank, comm=comm, replication=strat)
            mgr.save(
                1, PyTreeStateDict(_local_tree(layout, rank)),
                is_async=False, layout=layout,
            )
            mgr.close()
        finally:
            ex.close()

    _run_world(WORLD, save_body)

    seen = []
    tpu_events.add_sink(seen.append)

    # -- ranged path -------------------------------------------------------
    def ranged_body(rank):
        comm = StoreComm(mk(), rank, SURVIVORS, timeout=120.0, generation=1)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=2
            )
            mgr = LocalCheckpointManager(root, rank=rank, comm=comm, replication=strat)
            t0 = time.perf_counter()
            hollow, tensors, meta = mgr.load_resharded()
            dt = time.perf_counter() - t0
            mgr.close()
            return dt, sum(t.nbytes for t in tensors)
        finally:
            ex.close()

    ranged = _run_world(SURVIVORS, ranged_body)
    ranged_s = max(dt for dt, _ in ranged)
    ranged_peer = sum(
        e.payload["bytes"] for e in seen
        if e.kind == "reshard_fetch" and e.payload.get("via") == "peer"
    )
    ranged_local = sum(
        e.payload["bytes"] for e in seen
        if e.kind == "reshard_fetch" and e.payload.get("via") == "local"
    )

    # -- full-mirror baseline ---------------------------------------------
    # Same END STATE as the ranged path (the target-local tree assembled in
    # host memory), but bytes move the way pre-reshard recovery forced:
    # every source container a rank cannot serve locally is fetched WHOLE
    # (all leaves, full ranges) from a holder, local sources are read WHOLE
    # off disk, and the target blocks are then sliced out of the complete
    # containers in memory.
    source = layout
    target = source.retarget(SURVIVORS)
    plan = R.build_plan(source, target)

    def full_body(rank):
        comm = StoreComm(mk(), rank, SURVIVORS, timeout=120.0, generation=2)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=2
            )
            mgr = LocalCheckpointManager(root, rank=rank, comm=comm, replication=strat)
            held = {i.owner for i in mgr.local_ids() if i.iteration == 1}
            all_held = comm.all_gather((rank, sorted(held)), tag="bench-held")
            holders = {r: set(h) for r, h in all_held}
            rp = plan.for_rank(rank)
            needed = set()
            for seg in rp.segments:
                if not (set(seg.owners) & held):
                    needed.add(sorted(seg.owners)[0])
            t0 = time.perf_counter()
            moved = 0
            # Whole-container sources: peer mirrors over the wire, held
            # containers off disk (leaf payloads via full-range reads).
            sources: dict[int, list] = {}
            for owner in sorted(needed):
                holder = min(r for r, h in holders.items() if owner in h and r != rank)
                full = [
                    [i, 0, source.local_nbytes(i, owner)]
                    for i in range(len(source.leaves))
                ]
                _, parts = ex.fetch_ranges(
                    holder,
                    {"session": 0, "iteration": 1, "owner": owner, "ranges": full},
                )
                moved += sum(memoryview(p).nbytes for p in parts)
                sources[owner] = parts
            for seg in rp.segments:
                owner = min(set(seg.owners) & held, default=None)
                if owner is not None and owner not in sources:
                    full = [
                        [i, 0, source.local_nbytes(i, owner)]
                        for i in range(len(source.leaves))
                    ]
                    sources[owner] = mgr._read_ranges(1, owner, full)
            # Assemble the same target-local leaves the ranged path built.
            buffers = [
                np.empty(shape, dtype=np.float32)
                for shape in rp.local_shapes
            ]
            flats = [b.reshape(-1).view(np.uint8) for b in buffers]
            for seg in rp.segments:
                owner = min(o for o in seg.owners if o in sources)
                for rg in seg.ranges:
                    leaf_buf = memoryview(sources[owner][seg.leaf])
                    flats[seg.leaf][rg.dst_off : rg.dst_off + rg.nbytes] = (
                        np.frombuffer(
                            leaf_buf[rg.src_off : rg.src_off + rg.nbytes],
                            dtype=np.uint8,
                        )
                    )
            dt = time.perf_counter() - t0
            comm.barrier(tag="bench-full-done")
            mgr.close()
            return dt, moved
        finally:
            ex.close()

    full = _run_world(SURVIVORS, full_body)
    full_s = max(dt for dt, _ in full)
    full_peer = sum(moved for _, moved in full)
    tpu_events.remove_sink(seen.append)

    # Phase decomposition from the SAME event stream, through the same code
    # path tpu-critpath runs for operators (tools/critpath.py) — no more
    # bench-private stopwatch arithmetic.
    from tpu_resiliency.tools.critpath import reshard_decomposition

    phases = reshard_decomposition([e.to_record() for e in seen])

    for s in stores:
        s.close()
    srv.close()
    import shutil

    shutil.rmtree(root, ignore_errors=True)

    return {
        "host": platform.node(),
        "world": len(WORLD),
        "shrink_to": len(SURVIVORS),
        "mb": mb,
        "ranged_s": round(ranged_s, 4),
        "ranged_peer_bytes": ranged_peer,
        "ranged_local_bytes": ranged_local,
        #: tools/critpath.py:reshard_decomposition over the run's events —
        #: plan-build vs ranged-fetch wall split (fetch_s is the serve-side
        #: target ROADMAP item 4 attacks)
        "phases": phases,
        "full_s": round(full_s, 4),
        "full_peer_bytes": full_peer,
        "bytes_ratio": round(ranged_peer / full_peer, 4) if full_peer else None,
        "speedup": round(full_s / ranged_s, 2) if ranged_s else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mb", type=int, default=64, help="total tree size (MB)")
    ap.add_argument("--out", default=None, help="write the JSON report here")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny payload, assert the bytes win, exit 0/1")
    ap.add_argument("--with-1g", action="store_true",
                    help="also run the slow 1 GB leg (leg_1g in the report); "
                    "its speedup must exceed the gate payload's")
    ap.add_argument("--assert-subsecond", action="store_true",
                    help="exit 1 unless shrink-to-trainable (ranged_s) < 1 s")
    args = ap.parse_args(argv)
    mb = 2 if args.smoke else args.mb
    res = bench(mb)
    if args.with_1g:
        res["leg_1g"] = bench(1024)
    print(json.dumps(res, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(res, f, indent=2)
            f.write("\n")
    rc = 0
    if args.smoke:
        ok = (
            res["full_peer_bytes"] > 0
            and res["ranged_peer_bytes"] < res["full_peer_bytes"]
        )
        print(f"bench_reshard smoke: {'PASS' if ok else 'FAIL'}")
        rc = max(rc, 0 if ok else 1)
    if args.assert_subsecond:
        ok = res["ranged_s"] < 1.0
        print(
            f"bench_reshard sub-second resume: shrink-to-trainable "
            f"{res['ranged_s']}s at {mb} MB — {'PASS' if ok else 'FAIL'}"
        )
        rc = max(rc, 0 if ok else 1)
    if args.with_1g:
        ok = (res["leg_1g"]["speedup"] or 0) > (res["speedup"] or 0)
        print(
            f"bench_reshard 1G scaling: speedup {res['leg_1g']['speedup']}x "
            f"@1G vs {res['speedup']}x @{mb}MB — {'PASS' if ok else 'FAIL'}"
        )
        rc = max(rc, 0 if ok else 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
