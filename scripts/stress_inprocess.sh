#!/usr/bin/env bash
# Stress loop for the in-process restart engine's async-exception delivery.
#
# The engine's premise is that injection is safe: a healthy rank must NEVER die
# because a RankShouldRestart landed outside the wrapped fn (the round-2 delivery
# race, VERDICT r2 weak #1). This loop is the regression gate: run the multi-rank
# restart tests N times (default 50) and fail on the first non-green run.
#
#   ./scripts/stress_inprocess.sh [N]
set -u
N="${1:-50}"
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
for i in $(seq 1 "$N"); do
    out=$(timeout 300 python -m pytest tests/inprocess/test_wrap.py -k MultiRank -q 2>&1)
    status=$?
    tail=$(echo "$out" | tail -1)
    echo "run $i/$N: $tail"
    if [ "$status" -ne 0 ]; then
        echo "$out"
        echo "STRESS FAILURE on run $i"
        exit 1
    fi
done
echo "all $N runs green"
