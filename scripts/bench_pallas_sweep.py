"""Sweep the Pallas fused-median kernels vs XLA's sort lowering over (W, R) —
the measured data behind ``scoring_pallas`` auto-selection (VERDICT r3 item 5 /
r4 item 3).

Three kernel formulations are measured: ``loop`` (rank-counting, O(W²)),
``pairwise`` (all-pairs block, O(W²) VMEM-heavy; the product gate caps it at
the measured ``PAIRWISE_MAX_WINDOW`` = 32, but the sweep deliberately probes
up to W=64 so a different device generation that can compile it gets
measured rather than assumed — W>64 is skipped outright for its quadratic
VMEM temporaries), and ``radix`` (bit-select, O(32·W) — the scaling-safe
mode). The JSON tail derives the
auto-select boundary from the measurements:

- ``loop_max_window``: largest W where the loop kernel is the best variant at
  every tested R → export as ``$TPU_RESILIENCY_PALLAS_MAX_WINDOW`` (beyond it
  auto-select runs radix).
- ``pallas_beats_xla_at``: per-W verdict of best-Pallas vs XLA under the
  same noise tolerance as the cap (``TOL``), so the two exports cannot
  contradict each other on a sub-noise tie (the use_pallas gate
  justification).

Run on a real TPU (device-true per-program times via the framework's own
DeviceTimeProfiler; wall clocks lie on remote-dispatch runtimes):

    python scripts/bench_pallas_sweep.py [--ws 32,64,128,256] [--rs 256,1024,4096]
"""

import argparse
import json
import sys

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

S = 64
ITERS = 20

#: Measurement-noise tolerance for BOTH exported decisions: a variant keeps
#: its "win" on a cell unless it is more than 2% slower than the alternative
#: (ties and sub-2% deficits count as wins — deliberately asymmetric toward
#: the Pallas path). On v5e, W=64 reads as an XLA "win" by 0.3-0.8% at small
#: R while loop wins 25% at R=4096 — a sub-noise tie must not flip either
#: export.
TOL = 1.02


def measure(r, w, variant):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.telemetry import scoring
    from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.uniform(0.8, 1.2, (r, S, w)).astype(np.float32))
    counts = jnp.full((r, S), w, jnp.int32)
    ewma = jnp.ones((r,))
    hist = jnp.full((r, S), jnp.inf)

    if variant == "xla":
        def program(d, c, e, h):
            return scoring.score_round(d, c, e, h)
    else:
        from tpu_resiliency.ops.scoring_pallas import fused_median_weights

        mode = variant.removeprefix("pallas-")

        def program(d, c, e, h):
            mw = fused_median_weights(d, c, mode=mode)
            return scoring.score_round(d, c, e, h, medians_and_weights=mw)

    fn = jax.jit(program)
    out = fn(data, counts, ewma, hist)
    jax.block_until_ready(out)
    if jax.default_backend() == "tpu":
        prof = DeviceTimeProfiler()
        with prof:
            for _ in range(ITERS):
                out = fn(data, counts, out.ewma, hist)
            jax.block_until_ready(out)
        for name, st in prof.get_stats().items():
            if "program" in name:
                return st["med"] * 1e3
        raise RuntimeError(f"profiler missed program: {sorted(prof.get_stats())}")
    import time

    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(data, counts, out.ewma, hist)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS * 1e3


VARIANTS = ("pallas-loop", "pallas-pairwise", "pallas-radix", "xla")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ws", default="32,64,128,256")
    ap.add_argument("--rs", default="256,1024,4096")
    args = ap.parse_args()
    ws = [int(x) for x in args.ws.split(",")]
    rs = [int(x) for x in args.rs.split(",")]

    import jax

    from tpu_resiliency.platform.device import apply_platform_env

    apply_platform_env()

    backend = jax.default_backend()
    print(f"backend: {backend} {jax.devices()}", file=sys.stderr)
    results = {}
    loop_best_by_w = {w: True for w in ws}
    pallas_wins_by_w = {w: True for w in ws}
    for r in rs:
        for w in ws:
            row = {}
            for variant in VARIANTS:
                if variant == "pallas-pairwise" and w > 64:
                    continue  # quadratic VMEM temporaries exceed budget
                try:
                    row[variant] = measure(r, w, variant)
                except Exception as e:
                    row[variant] = None
                    print(f"R={r} W={w} {variant}: FAILED {e!r}"[:4000], file=sys.stderr)
            results[f"{r}x{w}"] = row
            # Pairwise never auto-selects, so it votes in neither export —
            # a pairwise-only win would certify a path use_pallas can't run.
            pallas_times = {
                k: v
                for k, v in row.items()
                if k not in ("xla", "pallas-pairwise") and v is not None
            }
            best_pallas = min(pallas_times.values(), default=None)
            # THIS row's verdict; the *_by_w flags separately accumulate the
            # every-R requirement for the exported defaults. Same TOL as the
            # loop cap so the two exports cannot contradict each other on a
            # sub-noise tie.
            row_pallas_wins = (
                best_pallas is not None
                and row.get("xla") is not None
                and best_pallas <= TOL * row["xla"]
            )
            if not row_pallas_wins:
                pallas_wins_by_w[w] = False
            # The cap governs loop-vs-its-auto-alternatives (radix / XLA);
            # pairwise is never auto-selected, so it doesn't vote.
            loop_t = row.get("pallas-loop")
            loop_ok = (
                loop_t is not None
                and (row.get("pallas-radix") is None or loop_t <= TOL * row["pallas-radix"])
                and (row.get("xla") is None or loop_t <= TOL * row["xla"])
            )
            if not loop_ok:
                loop_best_by_w[w] = False
            cells = "  ".join(
                f"{k}={v:.3f}ms" if v is not None else f"{k}=FAIL"
                for k, v in row.items()
            )
            verdict = "pallas" if row_pallas_wins else "xla"
            print(f"R={r:5d} W={w:4d}: {cells}  -> {verdict}")
    # The loop cap must be safe for EVERY rank count: a window qualifies only
    # if the loop kernel was the best variant at every tested R, and only while
    # all smaller tested windows also qualified (one noise win past a loss must
    # not raise the cap).
    loop_max_window = 0
    for w in sorted(ws):
        if not loop_best_by_w[w]:
            break
        loop_max_window = w
    print(
        json.dumps(
            {
                "backend": backend,
                "signals": S,
                "results_ms": results,
                "loop_max_window": loop_max_window,
                "loop_tolerance": TOL,
                "pallas_beats_xla_at": {
                    str(w): pallas_wins_by_w[w] for w in sorted(ws)
                },
                "export": f"TPU_RESILIENCY_PALLAS_MAX_WINDOW={loop_max_window}",
                # Stable schema with the merge flow that annotates a wedged
                # run's artifact (BASELINE.md references these fields).
                "carried_cells": [],
                "note": "",
            }
        )
    )


if __name__ == "__main__":
    main()
