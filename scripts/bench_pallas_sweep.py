"""Sweep the Pallas fused-median kernel vs XLA's sort lowering over (W, R) —
the measured crossover behind ``scoring_pallas.pallas_supported``'s window gate
(VERDICT r3 item 5).

Run on a real TPU (device-true per-program times via the framework's own
DeviceTimeProfiler; wall clocks lie on remote-dispatch runtimes):

    python scripts/bench_pallas_sweep.py [--ws 32,64,128,256] [--rs 256,1024,4096]

Prints one table row per (R, W) with loop-mode Pallas, pairwise Pallas (W<=64;
its [RT,S,W,W] temporaries exceed VMEM beyond that), and XLA times, plus a final
JSON line with the measured max winning window to export as
``$TPU_RESILIENCY_PALLAS_MAX_WINDOW``.
"""

import argparse
import json
import sys

S = 64
ITERS = 20


def measure(r, w, variant):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.telemetry import scoring
    from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.uniform(0.8, 1.2, (r, S, w)).astype(np.float32))
    counts = jnp.full((r, S), w, jnp.int32)
    ewma = jnp.ones((r,))
    hist = jnp.full((r, S), jnp.inf)

    if variant == "xla":
        def program(d, c, e, h):
            return scoring.score_round(d, c, e, h)
    else:
        from tpu_resiliency.ops.scoring_pallas import fused_median_weights

        mode = "loop" if variant == "pallas" else "pairwise"

        def program(d, c, e, h):
            mw = fused_median_weights(d, c, mode=mode)
            return scoring.score_round(d, c, e, h, medians_and_weights=mw)

    fn = jax.jit(program)
    out = fn(data, counts, ewma, hist)
    jax.block_until_ready(out)
    if jax.default_backend() == "tpu":
        prof = DeviceTimeProfiler()
        with prof:
            for _ in range(ITERS):
                out = fn(data, counts, out.ewma, hist)
            jax.block_until_ready(out)
        for name, st in prof.get_stats().items():
            if "program" in name:
                return st["med"] * 1e3
        raise RuntimeError(f"profiler missed program: {sorted(prof.get_stats())}")
    import time

    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(data, counts, out.ewma, hist)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ws", default="32,64,128,256")
    ap.add_argument("--rs", default="256,1024,4096")
    args = ap.parse_args()
    ws = [int(x) for x in args.ws.split(",")]
    rs = [int(x) for x in args.rs.split(",")]

    import jax

    from tpu_resiliency.platform.device import apply_platform_env

    apply_platform_env()

    backend = jax.default_backend()
    print(f"backend: {backend} {jax.devices()}", file=sys.stderr)
    results = {}
    win_by_w = {w: True for w in ws}
    for r in rs:
        for w in ws:
            row = {}
            for variant in ("pallas", "pallas-pairwise", "xla"):
                if variant == "pallas-pairwise" and w > 64:
                    continue  # quadratic VMEM temporaries exceed budget
                try:
                    row[variant] = measure(r, w, variant)
                except Exception as e:
                    row[variant] = None
                    print(f"R={r} W={w} {variant}: FAILED {e!r}"[:200], file=sys.stderr)
            results[f"{r}x{w}"] = row
            best_pallas = min(
                (v for k, v in row.items() if k != "xla" and v is not None),
                default=None,
            )
            verdict = (
                "pallas" if best_pallas is not None and row.get("xla") is not None
                and best_pallas < row["xla"] else "xla"
            )
            if verdict != "pallas":
                win_by_w[w] = False
            cells = "  ".join(
                f"{k}={v:.3f}ms" if v is not None else f"{k}=FAIL"
                for k, v in row.items()
            )
            print(f"R={r:5d} W={w:4d}: {cells}  -> {verdict}")
    # The cap must be safe for EVERY rank count: a window qualifies only if
    # Pallas won at every tested R, and only while all smaller tested windows
    # also qualified (one noise win past a loss must not raise the cap).
    max_winning_w = 0
    for w in sorted(ws):
        if not win_by_w[w]:
            break
        max_winning_w = w
    print(
        json.dumps(
            {
                "backend": backend,
                "signals": S,
                "results_ms": results,
                "max_winning_window": max_winning_w,
                "export": f"TPU_RESILIENCY_PALLAS_MAX_WINDOW={max_winning_w}",
            }
        )
    )


if __name__ == "__main__":
    main()
