"""Op-storm benchmark for the coordination store: the "before" picture.

ROADMAP item 2 (shard the store, tree the collectives) will be judged against
a latency curve — this harness records it. N concurrent clients on loopback
hammer one :class:`KVServer` with the mixed small-op workload the launcher
actually generates (set/get/add/touch + a periodic prefix scan), and the
report is client-observed p50/p95 latency and aggregate throughput per
concurrency level, plus the server's OWN ``store_stats`` view of the same
storm (handle vs queue-wait split — the number that says whether the loop or
the wire is the bottleneck).

The second leg is the **telemetry overhead gate**: the same storm against a
``stats_enabled=False`` control server. Per-op accounting must cost <5% of
client-observed p50 (the knob defaults ON, so the tax is paid by every job —
``tests/platform/test_store_perf.py`` enforces the gate as a slow-marked
test).

Usage::

    python scripts/bench_store.py [--ops N] [--out BENCH_store_baseline.json]
    python scripts/bench_store.py --smoke     # CI: tiny storm, sanity asserts
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import platform
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tpu_resiliency.platform.store import KVClient, KVServer  # noqa: E402

#: concurrency levels of the committed baseline curve
LEVELS = (1, 4, 16, 64)


def storm_client(port: int, client_id: int, ops: int, q) -> None:
    """One client's slice of the storm: the launcher-shaped small-op mix,
    per-op latency sampled client-side (the operator-visible number)."""
    c = KVClient("127.0.0.1", port, timeout=30.0)
    lat: list[float] = []
    try:
        for i in range(ops):
            kind = i % 8
            key = f"storm/c{client_id}/k{i % 16}"
            t0 = time.perf_counter()
            if kind < 3:
                c.set(key, i)
            elif kind < 6:
                c.try_get(key)
            elif kind == 6:
                c.add(f"storm/c{client_id}/ctr", 1)
            else:
                c.touch(f"storm/hb/c{client_id}")
            lat.append(time.perf_counter() - t0)
            if i % 64 == 63:
                t0 = time.perf_counter()
                c.prefix_get(f"storm/c{client_id}/")
                lat.append(time.perf_counter() - t0)
    finally:
        c.close()
    q.put((client_id, lat))


def run_storm(port: int, clients: int, ops_per_client: int) -> dict:
    """Storm with client PROCESSES — the deployment shape (workers are
    separate processes), and the measurement shape: in-process client threads
    would share the server loop's GIL and misattribute their own framing cost
    to server latency."""
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=storm_client, args=(port, i, ops_per_client, q))
        for i in range(clients)
    ]
    t_start = time.perf_counter()
    for p in procs:
        p.start()
    lats: list[float] = []
    for _ in range(clients):
        _, lat = q.get(timeout=300)
        lats.extend(lat)
    wall = time.perf_counter() - t_start
    for p in procs:
        p.join(20.0)
        if p.is_alive():
            p.terminate()
    lats.sort()

    def qtile(p: float) -> float:
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    return {
        "clients": clients,
        "ops": len(lats),
        "p50_us": round(qtile(0.50) * 1e6, 2),
        "p95_us": round(qtile(0.95) * 1e6, 2),
        "p99_us": round(qtile(0.99) * 1e6, 2),
        "ops_per_s": round(len(lats) / wall, 1),
        "wall_s": round(wall, 3),
    }


def bench_levels(levels=LEVELS, ops_per_client: int = 1500) -> dict:
    """The latency-vs-concurrency curve, one server for the whole sweep (the
    production shape: one store outlives every client), plus the server's own
    store_stats account of it."""
    srv = KVServer(host="127.0.0.1", port=0, stats_interval=3600.0)
    try:
        rows = [run_storm(srv.port, n, ops_per_client) for n in levels]
        probe = KVClient("127.0.0.1", srv.port)
        try:
            stats = probe.store_stats()
        finally:
            probe.close()
        # Trim the per-op table to the storm's hot ops (the committed JSON
        # stays reviewable).
        stats["ops"] = {
            op: row for op, row in (stats.get("ops") or {}).items()
            if row.get("count", 0) >= len(levels)
        }
        return {"levels": rows, "store_stats": stats}
    finally:
        srv.close()


def bench_overhead(clients: int = 1, ops_per_client: int = 1500,
                   trials: int = 9) -> dict:
    """Client-observed p50 with per-op telemetry on vs off: N interleaved
    trials per mode (on/off alternating, fresh server each — background-load
    spikes hit both arms), compared by MEDIAN. One client on purpose — no
    queueing amplification, so the delta is the collector's own service-time
    tax, the number the <5% gate is about."""
    import statistics

    p50 = {True: [], False: []}
    for _ in range(trials):
        for enabled in (True, False):
            srv = KVServer(
                host="127.0.0.1", port=0,
                stats_enabled=enabled, stats_interval=3600.0,
            )
            try:
                p50[enabled].append(
                    run_storm(srv.port, clients, ops_per_client)["p50_us"]
                )
            finally:
                srv.close()
    on = statistics.median(p50[True])
    off = statistics.median(p50[False])
    return {
        "clients": clients,
        "trials": trials,
        "stats_on_p50_us": round(on, 2),
        "stats_off_p50_us": round(off, 2),
        "overhead_frac": round(on / off - 1.0, 4) if off else None,
        "p50_us_all": {"on": p50[True], "off": p50[False]},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ops", type=int, default=1500,
                    help="ops per client per level")
    ap.add_argument(
        "--out", default=os.path.join(REPO_ROOT, "BENCH_store_baseline.json")
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny storm asserting the telemetry answers (op counts, wait/"
        "handle split, hot prefixes) without writing the committed file",
    )
    args = ap.parse_args(argv)

    if args.smoke:
        res = bench_levels(levels=(2,), ops_per_client=200)
        print(json.dumps({"layer": "store-storm", **res["levels"][0]}))
        stats = res["store_stats"]
        ok = (
            stats.get("enabled") is True
            and stats.get("ops", {}).get("set", {}).get("count", 0) > 0
            and stats["ops"]["set"]["handle"]["p50_us"] > 0
            and stats["ops"]["set"]["wait"]["count"] > 0
            and any(
                r["prefix"].startswith("storm/")
                for r in stats.get("hot_prefixes", [])
            )
            and stats.get("bytes", {}).get("in", 0) > 0
        )
        print(json.dumps({"bench_store_smoke": "PASS" if ok else "FAIL",
                          "stats_enabled": stats.get("enabled")}))
        return 0 if ok else 1

    curve = bench_levels(levels=LEVELS, ops_per_client=args.ops)
    for row in curve["levels"]:
        print(json.dumps({"layer": "store-storm", **row}))
    overhead = bench_overhead()
    print(json.dumps({"layer": "telemetry-overhead", **overhead}))
    summary = {
        "host": platform.node(),
        "cpus": os.cpu_count(),
        **curve,
        "telemetry_overhead": overhead,
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "metric": "flat-store op latency vs concurrency (loopback, "
                  "client-observed)",
        "p50_us_by_clients": {
            str(r["clients"]): r["p50_us"] for r in curve["levels"]
        },
        "p95_us_by_clients": {
            str(r["clients"]): r["p95_us"] for r in curve["levels"]
        },
        "telemetry_overhead_frac": overhead["overhead_frac"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
