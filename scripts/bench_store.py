"""Op-storm benchmarks for the coordination store: before AND after.

**Baseline leg** (default): N concurrent clients on loopback hammer one
:class:`KVServer` with the mixed small-op workload the launcher actually
generates (set/get/add/touch + a periodic prefix scan); the report is
client-observed p50/p95 latency and aggregate throughput per concurrency
level, plus the server's OWN ``store_stats`` view of the same storm (handle
vs queue-wait split). This is the committed ``BENCH_store_baseline.json``
"before" curve ROADMAP item 2 is judged against.

**Telemetry overhead leg**: the same storm against a ``stats_enabled=False``
control server. Per-op accounting must cost <5% of client-observed p50
(``tests/platform/test_store_perf.py`` enforces the gate).

**Scale leg** (``--ranks N``): the "after" picture — a simulated N-rank
rendezvous + barrier storm + metrics-push storm against a **sharded clique**
of ``--shards`` KVServer *processes*, driven by ``--procs`` light loopback
worker processes each multiplexing a contiguous slice of ranks. The tree
barrier executes level-stepped (deepest level first, an mp barrier between
levels), which is DAG-faithful: op counts, key layout, and shard routing are
exactly the deployment protocol's — only the park-and-wake idling is elided,
so the measured figures are store service times, the quantity the baseline
curve also measures. The report: per-op p50/p95 across the storm (the
apples-to-apples number vs the baseline's 64-client point), per-shard op
totals from the aggregated ``store_stats`` (how evenly the hash spreads the
storm), and a flat-vs-tree comparison table with analytic critical-path hop
counts (``treecomm.flat_hops``/``tree_hops``) plus measured per-rank op
counts and wall clocks. Committed as ``BENCH_store_scale.json``.

Usage::

    python scripts/bench_store.py [--ops N] [--out BENCH_store_baseline.json]
    python scripts/bench_store.py --ranks 4096 --shards 4   # scale storm
    python scripts/bench_store.py --smoke     # CI: tiny storm, sanity asserts
    python scripts/bench_store.py --smoke --ranks 128 --shards 2  # + scale leg
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import platform
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tpu_resiliency.platform.store import KVClient, KVServer  # noqa: E402

#: concurrency levels of the committed baseline curve
LEVELS = (1, 4, 16, 64)


def storm_client(port: int, client_id: int, ops: int, q) -> None:
    """One client's slice of the storm: the launcher-shaped small-op mix,
    per-op latency sampled client-side (the operator-visible number)."""
    c = KVClient("127.0.0.1", port, timeout=30.0)
    lat: list[float] = []
    try:
        for i in range(ops):
            kind = i % 8
            key = f"storm/c{client_id}/k{i % 16}"
            t0 = time.perf_counter()
            if kind < 3:
                c.set(key, i)
            elif kind < 6:
                c.try_get(key)
            elif kind == 6:
                c.add(f"storm/c{client_id}/ctr", 1)
            else:
                c.touch(f"storm/hb/c{client_id}")
            lat.append(time.perf_counter() - t0)
            if i % 64 == 63:
                t0 = time.perf_counter()
                c.prefix_get(f"storm/c{client_id}/")
                lat.append(time.perf_counter() - t0)
    finally:
        c.close()
    q.put((client_id, lat))


def run_storm(port: int, clients: int, ops_per_client: int) -> dict:
    """Storm with client PROCESSES — the deployment shape (workers are
    separate processes), and the measurement shape: in-process client threads
    would share the server loop's GIL and misattribute their own framing cost
    to server latency."""
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=storm_client, args=(port, i, ops_per_client, q))
        for i in range(clients)
    ]
    t_start = time.perf_counter()
    for p in procs:
        p.start()
    lats: list[float] = []
    for _ in range(clients):
        _, lat = q.get(timeout=300)
        lats.extend(lat)
    wall = time.perf_counter() - t_start
    for p in procs:
        p.join(20.0)
        if p.is_alive():
            p.terminate()
    lats.sort()

    def qtile(p: float) -> float:
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    return {
        "clients": clients,
        "ops": len(lats),
        "p50_us": round(qtile(0.50) * 1e6, 2),
        "p95_us": round(qtile(0.95) * 1e6, 2),
        "p99_us": round(qtile(0.99) * 1e6, 2),
        "ops_per_s": round(len(lats) / wall, 1),
        "wall_s": round(wall, 3),
    }


def bench_levels(levels=LEVELS, ops_per_client: int = 1500) -> dict:
    """The latency-vs-concurrency curve, one server for the whole sweep (the
    production shape: one store outlives every client), plus the server's own
    store_stats account of it."""
    srv = KVServer(host="127.0.0.1", port=0, stats_interval=3600.0)
    try:
        rows = [run_storm(srv.port, n, ops_per_client) for n in levels]
        probe = KVClient("127.0.0.1", srv.port)
        try:
            stats = probe.store_stats()
        finally:
            probe.close()
        # Trim the per-op table to the storm's hot ops (the committed JSON
        # stays reviewable).
        stats["ops"] = {
            op: row for op, row in (stats.get("ops") or {}).items()
            if row.get("count", 0) >= len(levels)
        }
        return {"levels": rows, "store_stats": stats}
    finally:
        srv.close()


def bench_overhead(clients: int = 1, ops_per_client: int = 1500,
                   trials: int = 9) -> dict:
    """Client-observed p50 with per-op telemetry on vs off: N interleaved
    trials per mode (on/off alternating, fresh server each — background-load
    spikes hit both arms), compared by MEDIAN. One client on purpose — no
    queueing amplification, so the delta is the collector's own service-time
    tax, the number the <5% gate is about."""
    import statistics

    p50 = {True: [], False: []}
    for _ in range(trials):
        for enabled in (True, False):
            srv = KVServer(
                host="127.0.0.1", port=0,
                stats_enabled=enabled, stats_interval=3600.0,
            )
            try:
                p50[enabled].append(
                    run_storm(srv.port, clients, ops_per_client)["p50_us"]
                )
            finally:
                srv.close()
    on = statistics.median(p50[True])
    off = statistics.median(p50[False])
    return {
        "clients": clients,
        "trials": trials,
        "stats_on_p50_us": round(on, 2),
        "stats_off_p50_us": round(off, 2),
        "overhead_frac": round(on / off - 1.0, 4) if off else None,
        "p50_us_all": {"on": p50[True], "off": p50[False]},
    }


# -- scale leg: sharded clique + tree collectives ---------------------------


def _quantiles(lats: list) -> dict:
    lats = sorted(lats)

    def q(p: float) -> float:
        return lats[min(len(lats) - 1, int(p * len(lats)))] if lats else 0.0

    return {
        "ops": len(lats),
        "p50_us": round(q(0.50) * 1e6, 2),
        "p95_us": round(q(0.95) * 1e6, 2),
        "p99_us": round(q(0.99) * 1e6, 2),
    }


def _storm_worker(spec: str, proc_id: int, ranks: range, world: int,
                  fanout: int, rounds: int, depth: int, lvl_barrier, q) -> None:
    """One light loopback process multiplexing ``ranks``: per round, the
    rendezvous write burst, the level-stepped tree barrier (exact deployment
    key layout/op counts — see module doc), and the metrics-push burst.
    Reports (proc_id, per-op latencies, per-rank op count)."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints
    from tpu_resiliency.platform.treecomm import children, tree_depth

    c = ShardedKVClient(parse_endpoints(spec), timeout=60.0)
    lat: list[float] = []
    ops_by_rank = dict.fromkeys(ranks, 0)

    def op(rank, fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        lat.append(time.perf_counter() - t0)
        ops_by_rank[rank] += 1
        return out

    def depth_of(i: int) -> int:
        d = 0
        while i > 0:
            i = (i - 1) // fanout
            d += 1
        return d

    try:
        for r in range(1, rounds + 1):
            # Phase 1: rendezvous registration burst (keyed writes, scattered
            # across shards by hash — the round-open census shape).
            for rank in ranks:
                op(rank, c.set, f"rdzv/r{r}/{rank}", rank)
            lvl_barrier.wait()
            # Phase 2: tree barrier, level-stepped. Up: deepest level first,
            # so every child's arrival key is committed before its parent
            # reads it (the parked wait of the live protocol, minus idling).
            for lvl in range(depth, -1, -1):
                for rank in ranks:
                    if depth_of(rank) != lvl:
                        continue
                    for ch in children(rank, world, fanout):
                        got = op(rank, c.get, f"bar/u/{ch}", 30.0)
                        assert got == r, (ch, got, r)
                    if rank != 0:
                        op(rank, c.set, f"bar/u/{rank}", r)
                lvl_barrier.wait()
            # Down: release propagates root→leaves on per-child keys.
            for lvl in range(0, depth + 1):
                for rank in ranks:
                    if depth_of(rank) != lvl:
                        continue
                    if rank != 0:
                        got = op(rank, c.get, f"bar/d/{rank}", 30.0)
                        assert got == r, (rank, got, r)
                    for ch in children(rank, world, fanout):
                        op(rank, c.set, f"bar/d/{ch}", r)
                lvl_barrier.wait()
            # Phase 3: metrics-push burst (heartbeat touch + snapshot set —
            # the per-tick publisher shape).
            for rank in ranks:
                op(rank, c.touch, f"mhb/{rank}")
                op(rank, c.set, f"jobmetrics/{rank}", {"rank": rank, "round": r})
            lvl_barrier.wait()
    finally:
        c.close()
    q.put((proc_id, lat, max(ops_by_rank.values()) if ops_by_rank else 0))


def bench_scale(ranks: int = 4096, shards: int = 4, procs: int = 16,
                rounds: int = 3, fanout: int = 8) -> dict:
    """The simulated N-rank storm against a spawned shard clique."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, SpawnedClique
    from tpu_resiliency.platform.treecomm import flat_hops, tree_depth, tree_hops

    procs = min(procs, ranks)
    clique = SpawnedClique(shards)
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    depth = tree_depth(ranks, fanout)
    lvl_barrier = ctx.Barrier(procs)
    slices = []
    per = ranks // procs
    extra = ranks % procs
    lo = 0
    for i in range(procs):
        hi = lo + per + (1 if i < extra else 0)
        slices.append(range(lo, hi))
        lo = hi
    try:
        workers = [
            ctx.Process(
                target=_storm_worker,
                args=(clique.spec, i, slices[i], ranks, fanout, rounds,
                      depth, lvl_barrier, q),
            )
            for i in range(procs)
        ]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        lats: list[float] = []
        max_rank_ops = 0
        for _ in range(procs):
            _, lat, rank_ops = q.get(timeout=600)
            lats.extend(lat)
            max_rank_ops = max(max_rank_ops, rank_ops)
        wall = time.perf_counter() - t0
        for w in workers:
            w.join(30.0)
            if w.is_alive():
                w.terminate()
        probe = ShardedKVClient(clique.endpoints)
        try:
            stats = probe.store_stats()
        finally:
            probe.close()
    finally:
        clique.close()
    shard_ops = [s["ops_total"] for s in stats.get("shards", [])]
    total_shard_ops = sum(shard_ops) or 1
    return {
        "ranks": ranks,
        "shards": shards,
        "procs": procs,
        "rounds": rounds,
        "fanout": fanout,
        **_quantiles(lats),
        "ops_per_s": round(len(lats) / wall, 1) if wall else 0.0,
        "wall_s": round(wall, 3),
        "max_ops_per_rank_per_round": round(max_rank_ops / rounds, 1),
        "hops": {
            "flat": flat_hops(ranks),
            "tree": tree_hops(ranks, fanout),
            "win": round(flat_hops(ranks) / tree_hops(ranks, fanout), 1),
        },
        "shard_balance": {
            "backend": stats.get("backend"),
            "per_shard_ops": shard_ops,
            # 1/shards is perfect balance; 1.0 means one loop served it all.
            "busiest_shard_frac": round(max(shard_ops) / total_shard_ops, 3)
            if shard_ops else 1.0,
        },
    }


def bench_tree_vs_flat(sizes=(64, 256, 1024), fanout: int = 8,
                       shards: int = 4, procs: int = 8) -> list[dict]:
    """Flat vs tree collective round at each world size, same clique: wall
    clock, per-rank op ceiling, and the analytic critical-path hop counts
    the ≥4×-at-256 acceptance gate reads. The flat leg reproduces today's
    ``StoreComm.all_gather`` op sequence (set + entry barrier + prefix_get +
    exit barrier); the tree leg is the level-stepped tree gather."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, SpawnedClique
    from tpu_resiliency.platform.treecomm import flat_hops, tree_depth, tree_hops

    out = []
    clique = SpawnedClique(shards)
    ctx = mp.get_context("fork")
    try:
        for world in sizes:
            nproc = min(procs, world)
            q = ctx.Queue()
            lvl_barrier = ctx.Barrier(nproc)
            per = world // nproc
            extra = world % nproc
            slices, lo = [], 0
            for i in range(nproc):
                hi = lo + per + (1 if i < extra else 0)
                slices.append(range(lo, hi))
                lo = hi

            def run(target):
                workers = [
                    ctx.Process(
                        target=target,
                        args=(clique.spec, i, slices[i], world, fanout,
                              lvl_barrier, q),
                    )
                    for i in range(nproc)
                ]
                t0 = time.perf_counter()
                for w in workers:
                    w.start()
                lats, rank_ops = [], 0
                for _ in range(nproc):
                    _, lat, ro = q.get(timeout=600)
                    lats.extend(lat)
                    rank_ops = max(rank_ops, ro)
                wall = time.perf_counter() - t0
                for w in workers:
                    w.join(30.0)
                return wall, lats, rank_ops

            flat_wall, flat_lats, flat_rank_ops = run(_flat_gather_worker)
            tree_wall, tree_lats, tree_rank_ops = run(_tree_gather_worker)
            out.append({
                "world": world,
                "flat": {"wall_s": round(flat_wall, 3),
                         "ops_per_rank": flat_rank_ops,
                         "hops": flat_hops(world), **_quantiles(flat_lats)},
                "tree": {"wall_s": round(tree_wall, 3),
                         "ops_per_rank": tree_rank_ops,
                         "hops": tree_hops(world, fanout),
                         "depth": tree_depth(world, fanout),
                         **_quantiles(tree_lats)},
                "hop_win": round(flat_hops(world) / tree_hops(world, fanout), 1),
            })
    finally:
        clique.close()
    return out


def _flat_gather_worker(spec, proc_id, ranks, world, fanout, lvl_barrier, q):
    """Today's flat all_gather shape: value set, entry barrier (non-blocking
    registration — the level-stepped stand-in for the parked join), one
    whole-world prefix_get per rank, exit barrier."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints

    c = ShardedKVClient(parse_endpoints(spec), timeout=60.0)
    lat, ops = [], dict.fromkeys(ranks, 0)

    def op(rank, fn, *a, **kw):
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        lat.append(time.perf_counter() - t0)
        ops[rank] += 1
        return out

    try:
        for rank in ranks:
            op(rank, c.set, f"fg{world}/v/{rank}", rank)
            op(rank, c.barrier_join, f"fg{world}/b0", rank, world, 30.0, False)
        lvl_barrier.wait()
        for rank in ranks:
            vals = op(rank, c.prefix_get, f"fg{world}/v/")
            assert len(vals) == world, (rank, len(vals))
            op(rank, c.barrier_join, f"fg{world}/b1", rank, world, 30.0, False)
        lvl_barrier.wait()
    finally:
        c.close()
    q.put((proc_id, lat, max(ops.values()) if ops else 0))


def _tree_gather_worker(spec, proc_id, ranks, world, fanout, lvl_barrier, q):
    """The tree all_gather DAG, level-stepped: fan-in merged dicts up,
    result fan-out down per-child keys, ack fan-in, root GC."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints
    from tpu_resiliency.platform.treecomm import children, tree_depth

    c = ShardedKVClient(parse_endpoints(spec), timeout=60.0)
    lat, ops = [], dict.fromkeys(ranks, 0)
    depth = tree_depth(world, fanout)

    def op(rank, fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        lat.append(time.perf_counter() - t0)
        ops[rank] += 1
        return out

    def depth_of(i):
        d = 0
        while i > 0:
            i = (i - 1) // fanout
            d += 1
        return d

    try:
        for lvl in range(depth, -1, -1):  # fan-in
            for rank in ranks:
                if depth_of(rank) != lvl:
                    continue
                merged = {rank: rank}
                for ch in children(rank, world, fanout):
                    merged.update(op(rank, c.get, f"tg{world}/v/{ch}", 30.0))
                if rank == 0:
                    assert len(merged) == world, len(merged)
                    for ch in children(rank, world, fanout):
                        op(rank, c.set, f"tg{world}/res/{ch}", merged)
                else:
                    op(rank, c.set, f"tg{world}/v/{rank}", merged)
            lvl_barrier.wait()
        for lvl in range(1, depth + 1):  # result fan-out
            for rank in ranks:
                if depth_of(rank) != lvl:
                    continue
                res = op(rank, c.get, f"tg{world}/res/{rank}", 30.0)
                assert len(res) == world, (rank, len(res))
                for ch in children(rank, world, fanout):
                    op(rank, c.set, f"tg{world}/res/{ch}", res)
            lvl_barrier.wait()
        for lvl in range(depth, 0, -1):  # ack fan-in
            for rank in ranks:
                if depth_of(rank) != lvl:
                    continue
                for ch in children(rank, world, fanout):
                    op(rank, c.get, f"tg{world}/a/{ch}", 30.0)
                op(rank, c.set, f"tg{world}/a/{rank}", 1)
            lvl_barrier.wait()
        for rank in ranks:
            if rank == 0:
                for ch in children(0, world, fanout):
                    op(rank, c.get, f"tg{world}/a/{ch}", 30.0)
                op(rank, c.prefix_clear, f"tg{world}/")
        lvl_barrier.wait()
    finally:
        c.close()
    q.put((proc_id, lat, max(ops.values()) if ops else 0))


def _failover_storm_worker(spec, client_id, ops, q):
    """One replicated client's slice of the failover storm: the same
    launcher-shaped mix as :func:`storm_client`, but through a replicating
    ``ShardedKVClient`` (every write double-writes to the successor). The
    untimed warmup trips this process's circuit breaker for any dead shard,
    so the timed ops measure STEADY-STATE failover routing — the transient
    trip cost is the chaos scenario's business, not this gate's."""
    from tpu_resiliency.exceptions import StoreError
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints

    c = ShardedKVClient(
        parse_endpoints(spec), timeout=30.0, connect_retries=2,
        retry_budget=0.5, replicate=True,
    )
    lat: list[float] = []
    try:
        for i in range(24):
            try:
                c.set(f"fstorm/c{client_id}/warm{i % 4}", i)
                c.try_get(f"fstorm/c{client_id}/warm{i % 4}")
            except StoreError:
                pass
        for i in range(ops):
            kind = i % 8
            key = f"fstorm/c{client_id}/k{i % 16}"
            t0 = time.perf_counter()
            if kind < 3:
                c.set(key, i)
            elif kind < 6:
                c.try_get(key)
            elif kind == 6:
                c.add(f"fstorm/c{client_id}/ctr", 1)
            else:
                c.touch(f"fstorm/hb/c{client_id}")
            lat.append(time.perf_counter() - t0)
    finally:
        c.close()
    q.put((client_id, lat))


def bench_failover_storm(clients: int = 8, ops_per_client: int = 800,
                         shards: int = 3) -> dict:
    """Storm-under-failover: the same replicated storm healthy, then again
    with one shard SIGKILLed (clients route its keyspace to the successor
    replica). The committed ``p95_ratio`` is THE degraded-operation
    acceptance number: failover must cost ≤2× the healthy p95
    (``tests/platform/test_store_perf.py`` pins it)."""
    from tpu_resiliency.platform.shardstore import SpawnedClique

    clique = SpawnedClique(shards)
    ctx = mp.get_context("fork")

    def leg() -> dict:
        q = ctx.Queue()
        procs = [
            ctx.Process(target=_failover_storm_worker,
                        args=(clique.spec, i, ops_per_client, q))
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for p in procs:
            p.start()
        lats: list[float] = []
        for _ in range(clients):
            _, lat = q.get(timeout=300)
            lats.extend(lat)
        wall = time.perf_counter() - t0
        for p in procs:
            p.join(20.0)
            if p.is_alive():
                p.terminate()
        return {
            "ops": len(lats), "wall_s": round(wall, 3),
            "ops_per_s": round(len(lats) / wall, 1), **_quantiles(lats),
        }

    victim = shards // 2
    try:
        healthy = leg()
        clique.procs[victim].kill()
        time.sleep(0.2)
        degraded = leg()
    finally:
        clique.close()
    return {
        "clients": clients, "shards": shards, "victim_shard": victim,
        "healthy": healthy, "degraded": degraded,
        "p95_ratio": round(degraded["p95_us"] / healthy["p95_us"], 3)
        if healthy["p95_us"] else None,
    }


def _flat_join_worker(spec, proc_id, ranks, q):
    """The flat rendezvous join ladder: every rank CAS-appends itself to the
    ONE state key — N contended read-modify-writes against a single shard,
    each carrying the whole O(N) participant list back and forth."""
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints

    c = ShardedKVClient(parse_endpoints(spec), timeout=60.0)
    lat: list[float] = []

    def op(fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        lat.append(time.perf_counter() - t0)
        return out

    try:
        for rank in ranks:
            while True:
                cur = op(c.try_get, "rl/flat/state")
                nxt = (cur or []) + [rank]
                ok, _ = op(c.compare_set, "rl/flat/state", cur, nxt)
                if ok:
                    break
                time.sleep(0.001)  # the real ladder's contention backoff
    finally:
        c.close()
    q.put((proc_id, lat))


def _scatter_join_worker(spec, proc_id, ranks, world, lvl_barrier, q):
    """The tree-laddered join: every rank ONE hash-scattered edge write
    (``treecomm.scatter_register``), then the leader folds the whole
    registration set with a shard-parallel prefix scan and ONE state write —
    O(N) ops spread over every shard with O(1) payloads, vs the flat arm's
    O(N) contended round trips on one shard with O(N) payloads."""
    from tpu_resiliency.platform import treecomm
    from tpu_resiliency.platform.shardstore import ShardedKVClient, parse_endpoints

    c = ShardedKVClient(parse_endpoints(spec), timeout=60.0)
    lat: list[float] = []

    def op(fn, *a):
        t0 = time.perf_counter()
        out = fn(*a)
        lat.append(time.perf_counter() - t0)
        return out

    try:
        for rank in ranks:
            op(treecomm.scatter_register, c, "rl/join", f"n{rank}")
        lvl_barrier.wait()
        if proc_id == 0:
            regs = op(treecomm.scatter_collect, c, "rl/join")
            assert len(regs) == world, (len(regs), world)
            op(c.set, "rl/scatter/state",
               {"round": 0, "parts": len(regs)})
            op(treecomm.scatter_clear, c, "rl/join")
        lvl_barrier.wait()
    finally:
        c.close()
    q.put((proc_id, lat))


def bench_rendezvous_ladder(world: int = 4096, shards: int = 4,
                            procs: int = 16) -> dict:
    """Full rendezvous join round, flat vs tree-laddered, same clique.
    The committed ``wall_win`` (flat wall / scattered wall) is the
    acceptance number: the scattered ladder must beat the flat baseline at
    4096 ranks (``tests/platform/test_store_perf.py`` pins it)."""
    from tpu_resiliency.platform.shardstore import SpawnedClique

    clique = SpawnedClique(shards)
    ctx = mp.get_context("fork")
    nproc = min(procs, world)
    per, extra = world // nproc, world % nproc
    slices, lo = [], 0
    for i in range(nproc):
        hi = lo + per + (1 if i < extra else 0)
        slices.append(range(lo, hi))
        lo = hi

    def run(target, with_barrier: bool) -> tuple[float, list]:
        q = ctx.Queue()
        lvl_barrier = ctx.Barrier(nproc)
        workers = [
            ctx.Process(
                target=target,
                args=(clique.spec, i, slices[i], world, lvl_barrier, q)
                if with_barrier else (clique.spec, i, slices[i], q),
            )
            for i in range(nproc)
        ]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        lats: list[float] = []
        for _ in range(nproc):
            _, lat = q.get(timeout=600)
            lats.extend(lat)
        wall = time.perf_counter() - t0
        for w in workers:
            w.join(30.0)
        return wall, lats

    try:
        flat_wall, flat_lats = run(_flat_join_worker, with_barrier=False)
        scatter_wall, scatter_lats = run(_scatter_join_worker, with_barrier=True)
    finally:
        clique.close()
    return {
        "world": world, "shards": shards, "procs": nproc,
        "flat": {"wall_s": round(flat_wall, 3), "ops": len(flat_lats),
                 **_quantiles(flat_lats)},
        "scattered": {"wall_s": round(scatter_wall, 3),
                      "ops": len(scatter_lats), **_quantiles(scatter_lats)},
        "wall_win": round(flat_wall / scatter_wall, 2) if scatter_wall else None,
    }


def bench_scale_report(ranks: int, shards: int, procs: int, rounds: int,
                       fanout: int, compare_sizes) -> dict:
    """The full scale leg + the committed baseline replayed side-by-side."""
    storm = bench_scale(ranks=ranks, shards=shards, procs=procs,
                        rounds=rounds, fanout=fanout)
    compare = bench_tree_vs_flat(
        sizes=tuple(s for s in compare_sizes if s <= ranks) or (ranks,),
        fanout=fanout, shards=shards,
        procs=min(procs, 8),
    )
    report = {
        "host": platform.node(),
        "cpus": os.cpu_count(),
        "storm": storm,
        "tree_vs_flat": compare,
        # Degraded-operation leg: the replicated storm with one shard
        # SIGKILLed vs healthy. p95_ratio ≤ 2.0 is the committed gate.
        "failover": bench_failover_storm(shards=shards),
        # Tree-laddered rendezvous join round vs the flat CAS ladder at the
        # storm's rank count. wall_win > 1.0 is the committed gate.
        "rendezvous_ladder": bench_rendezvous_ladder(
            world=ranks, shards=shards, procs=procs,
        ),
    }
    base_path = os.path.join(REPO_ROOT, "BENCH_store_baseline.json")
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        levels = base.get("levels") or []
        report["baseline"] = {
            "p50_us_by_clients": {str(r["clients"]): r["p50_us"] for r in levels},
            "p95_us_by_clients": {str(r["clients"]): r["p95_us"] for r in levels},
        }
        b64 = next((r for r in levels if r.get("clients") == 64), None)
        if b64:
            # THE acceptance ratio: per-op p95 under the N-rank sharded storm
            # vs the flat server's 64-client point. <2.0 = the curve held.
            report["p95_vs_baseline64"] = round(
                storm["p95_us"] / b64["p95_us"], 3
            ) if b64["p95_us"] else None
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ops", type=int, default=1500,
                    help="ops per client per level")
    ap.add_argument(
        "--out", default=os.path.join(REPO_ROOT, "BENCH_store_baseline.json")
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny storm asserting the telemetry answers (op counts, wait/"
        "handle split, hot prefixes) without writing the committed file; "
        "with --ranks, also a reduced sharded scale storm with its own "
        "sanity asserts",
    )
    ap.add_argument(
        "--ranks", type=int, default=0,
        help="run the SCALE leg: simulated N-rank rendezvous + tree-barrier "
        "+ metrics-push storm over a sharded clique; writes "
        "BENCH_store_scale.json (unless --smoke)",
    )
    ap.add_argument("--shards", type=int, default=4,
                    help="store clique size for the scale leg")
    ap.add_argument("--procs", type=int, default=16,
                    help="worker processes multiplexing the simulated ranks")
    ap.add_argument("--rounds", type=int, default=3,
                    help="storm rounds (rendezvous+barrier+metrics each)")
    ap.add_argument("--fanout", type=int, default=8, help="tree arity")
    ap.add_argument(
        "--scale-out",
        default=os.path.join(REPO_ROOT, "BENCH_store_scale.json"),
        help="output for the scale leg's committed report",
    )
    args = ap.parse_args(argv)

    if args.smoke:
        res = bench_levels(levels=(2,), ops_per_client=200)
        print(json.dumps({"layer": "store-storm", **res["levels"][0]}))
        stats = res["store_stats"]
        ok = (
            stats.get("enabled") is True
            and stats.get("backend") == "epoll"
            and stats.get("ops", {}).get("set", {}).get("count", 0) > 0
            and stats["ops"]["set"]["handle"]["p50_us"] > 0
            and stats["ops"]["set"]["wait"]["count"] > 0
            and any(
                r["prefix"].startswith("storm/")
                for r in stats.get("hot_prefixes", [])
            )
            and stats.get("bytes", {}).get("in", 0) > 0
        )
        print(json.dumps({"bench_store_smoke": "PASS" if ok else "FAIL",
                          "stats_enabled": stats.get("enabled"),
                          "backend": stats.get("backend")}))
        if ok and args.ranks:
            # Reduced sharded storm: the scale plumbing end to end (clique
            # spawn, hash fan-out, tree DAG, aggregated per-shard stats).
            storm = bench_scale(
                ranks=args.ranks, shards=args.shards,
                procs=min(args.procs, 4), rounds=1, fanout=args.fanout,
            )
            bal = storm["shard_balance"]
            scale_ok = (
                storm["p95_us"] > 0
                and storm["hops"]["tree"] < storm["hops"]["flat"]
                and bal["backend"] == "epoll"
                and len(bal["per_shard_ops"]) == args.shards
                and sum(bal["per_shard_ops"]) > 0
                and bal["busiest_shard_frac"] < 1.0
            )
            print(json.dumps({
                "layer": "store-scale-storm", "ranks": storm["ranks"],
                "shards": storm["shards"], "p95_us": storm["p95_us"],
                "hop_win": storm["hops"]["win"],
                "busiest_shard_frac": bal["busiest_shard_frac"],
            }))
            print(json.dumps(
                {"bench_store_scale_smoke": "PASS" if scale_ok else "FAIL"}
            ))
            ok = ok and scale_ok
            # Reduced failover + rendezvous-ladder legs: the HA plumbing end
            # to end (replicated double-writes, SIGKILL, breaker-routed
            # successor reads, scattered join fold) with sanity asserts.
            fo = bench_failover_storm(
                clients=2, ops_per_client=120, shards=min(args.shards, 3) or 2,
            )
            rl = bench_rendezvous_ladder(
                world=min(args.ranks, 128), shards=args.shards, procs=4,
            )
            ha_ok = (
                fo["healthy"]["p95_us"] > 0
                and fo["degraded"]["p95_us"] > 0
                and fo["degraded"]["ops"] == fo["healthy"]["ops"]
                and rl["flat"]["wall_s"] > 0
                and rl["scattered"]["wall_s"] > 0
            )
            print(json.dumps({
                "layer": "store-failover-storm",
                "p95_ratio": fo["p95_ratio"],
                "ladder_wall_win": rl["wall_win"],
            }))
            print(json.dumps(
                {"bench_store_failover_smoke": "PASS" if ha_ok else "FAIL"}
            ))
            ok = ok and ha_ok
        return 0 if ok else 1

    if args.ranks:
        report = bench_scale_report(
            ranks=args.ranks, shards=args.shards, procs=args.procs,
            rounds=args.rounds, fanout=args.fanout,
            compare_sizes=(64, 256, 1024),
        )
        with open(args.scale_out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(json.dumps({
            "metric": "sharded-store scale storm (simulated ranks, loopback)",
            "ranks": report["storm"]["ranks"],
            "shards": report["storm"]["shards"],
            "p50_us": report["storm"]["p50_us"],
            "p95_us": report["storm"]["p95_us"],
            "p95_vs_baseline64": report.get("p95_vs_baseline64"),
            "busiest_shard_frac":
                report["storm"]["shard_balance"]["busiest_shard_frac"],
            "hop_win_at": {
                str(row["world"]): row["hop_win"]
                for row in report["tree_vs_flat"]
            },
        }))
        return 0

    curve = bench_levels(levels=LEVELS, ops_per_client=args.ops)
    for row in curve["levels"]:
        print(json.dumps({"layer": "store-storm", **row}))
    overhead = bench_overhead()
    print(json.dumps({"layer": "telemetry-overhead", **overhead}))
    summary = {
        "host": platform.node(),
        "cpus": os.cpu_count(),
        **curve,
        "telemetry_overhead": overhead,
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "metric": "flat-store op latency vs concurrency (loopback, "
                  "client-observed)",
        "p50_us_by_clients": {
            str(r["clients"]): r["p50_us"] for r in curve["levels"]
        },
        "p95_us_by_clients": {
            str(r["clients"]): r["p95_us"] for r in curve["levels"]
        },
        "telemetry_overhead_frac": overhead["overhead_frac"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
