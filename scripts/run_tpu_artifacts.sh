#!/usr/bin/env bash
# One command for everything that needs a LIVE TPU — run the moment the tunnel
# recovers (rounds 3 AND 4 never saw it up; see BASELINE.md "Pallas window
# gate" + VERDICT r4 items 1/2/8):
#
#   ./scripts/run_tpu_artifacts.sh
#
# Produces, in repo root:
#   BENCH_tpu.json            - bench.py headline line (backend must say "tpu")
#   BENCH_pallas_sweep.json   - W/R table over loop/pairwise/radix vs XLA:
#                               loop_max_window -> $TPU_RESILIENCY_PALLAS_MAX_WINDOW,
#                               pallas_beats_xla_at -> whether to flip
#                               $TPU_RESILIENCY_PALLAS_RADIX / use_pallas defaults
#   BENCH_model.json          - flagship train-step tokens/s + MFU denominator
#   EXAMPLES_tpu.log          - every example run once on the real chip
set -u
cd "$(dirname "$0")/.."
probe() { timeout 240 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu', d; print('TPU OK', d)"; }
echo "== probing TPU"
probe || { echo "TPU unreachable; not falling back to CPU for these artifacts"; exit 1; }
echo "== bench.py (headline)"
timeout 3600 python bench.py > BENCH_tpu.json 2> bench_tpu.log && tail -1 BENCH_tpu.json
echo "== pallas sweep"
timeout 3600 python scripts/bench_pallas_sweep.py 2> sweep_tpu.log | tee /dev/stderr | tail -1 > BENCH_pallas_sweep.json
echo "== model denominator"
timeout 3600 python scripts/bench_model.py 2> model_tpu.log | tail -1 > BENCH_model.json && cat BENCH_model.json
echo "== examples on the real chip (closing the 'works on the actual device?' gap)"
: > EXAMPLES_tpu.log
run_example() {
  name="$1"; shift
  if timeout 600 "$@" >> EXAMPLES_tpu.log 2>&1; then
    echo "EXAMPLE OK: $name" | tee -a EXAMPLES_tpu.log
  else
    echo "EXAMPLE FAILED: $name (rc=$?)" | tee -a EXAMPLES_tpu.log
  fi
}
# Single-process examples run against the device directly (--tpu / platform
# env); multi-process examples MUST force JAX_PLATFORMS=cpu for their ranks:
# the ambient environment pins JAX_PLATFORMS to the device platform, the
# single-tenant tunnel cannot host N concurrent jax clients, and workers that
# inherit the device platform wedge at backend init until the monitor's hard
# timeout kills them. CPU ranks still prove the user-facing surface executes
# in this environment. PYTHONPATH covers spawned workers, which don't inherit
# the parent's sys.path bootstrap.
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
run_example moe_pipeline_TPU    python examples/moe_pipeline_training.py --tpu
# mesh_telemetry is a jax.distributed multi-process example: launcher-driven
# with a coordinator port, per its docstring (the script itself forces the CPU
# simulation for its workers unless TPU_MESH_EXAMPLE_PLATFORM overrides).
# Allocated-then-released just before use: the reuse window spans only the
# launcher's bring-up (a couple of ephemeral binds vs the ~28k-port range);
# a collision merely fails this one example line, visibly, on a rerunnable
# script — accepted over plumbing the port through the launcher store.
COORD_PORT=$(python -c "import socket;s=socket.socket();s.bind(('127.0.0.1',0));print(s.getsockname()[1]);s.close()")
run_example mesh_telemetry      python -m tpu_resiliency.launcher.launch \
  --nproc-per-node 2 --no-ft-monitors \
  --rdzv-endpoint 127.0.0.1:0 --rdzv-last-call 0.2 --monitor-interval 0.1 \
  examples/mesh_telemetry_training.py --coord-port "$COORD_PORT" --steps 150
run_example inprocess_restart   env JAX_PLATFORMS=cpu python examples/inprocess_restart_train.py --world 2 --steps 8 --ckpt-every 2 --kill-rank 1 --kill-step 4 --step-time 0.05
run_example preemption          env JAX_PLATFORMS=cpu python examples/preemption_train.py --world 2
# The last two are launcher-driven by design (their docstrings); bare
# invocation has no monitor sockets and no in-job restart layer.
run_example layered_restart     env JAX_PLATFORMS=cpu python -m tpu_resiliency.launcher.launch \
  --nproc-per-node 2 --max-restarts 2 --no-ft-monitors \
  --rdzv-endpoint 127.0.0.1:0 --rdzv-last-call 0.2 --monitor-interval 0.1 \
  examples/layered_restart.py --steps 20
run_example resilient_training  env JAX_PLATFORMS=cpu python -m tpu_resiliency.launcher.launch \
  --nproc-per-node 1 --max-restarts 2 \
  --rdzv-endpoint 127.0.0.1:0 --rdzv-last-call 0.2 --monitor-interval 0.1 \
  --ft-param-initial_rank_heartbeat_timeout 60 \
  --ft-param-rank_heartbeat_timeout 60 \
  examples/resilient_training.py --ckpt-dir "$(mktemp -d)"
echo "== done. Sweep exports are already encoded in-tree (DEFAULT_MAX_WINDOW=128 measured)."
echo "== Remaining decision: if this run's sweep shows pallas-radix compiling at W=256"
echo "== (VMEM tile shrink fix) AND beating xla there, flip DEFAULT_RADIX_AUTO in"
echo "== ops/scoring_pallas.py from the artifact; otherwise leave it off (measured-losing)."
