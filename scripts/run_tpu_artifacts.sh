#!/usr/bin/env bash
# One command for everything that needs a LIVE TPU — run the moment the tunnel
# recovers (round-4 builder session never saw it up; see BASELINE.md "Pallas
# window gate" + VERDICT r3 item 1):
#
#   ./scripts/run_tpu_artifacts.sh
#
# Produces, in repo root:
#   BENCH_tpu.json            - bench.py headline line (backend must say "tpu")
#   BENCH_pallas_sweep.json   - W/R crossover table + TPU_RESILIENCY_PALLAS_MAX_WINDOW export
#   BENCH_model.json          - flagship train-step tokens/s + MFU denominator
set -u
cd "$(dirname "$0")/.."
probe() { timeout 240 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu', d; print('TPU OK', d)"; }
echo "== probing TPU"
probe || { echo "TPU unreachable; not falling back to CPU for these artifacts"; exit 1; }
echo "== bench.py (headline)"
timeout 3600 python bench.py > BENCH_tpu.json 2> bench_tpu.log && tail -1 BENCH_tpu.json
echo "== pallas sweep"
timeout 3600 python scripts/bench_pallas_sweep.py 2> sweep_tpu.log | tee /dev/stderr | tail -1 > BENCH_pallas_sweep.json
echo "== model denominator"
timeout 3600 python scripts/bench_model.py 2> model_tpu.log | tail -1 > BENCH_model.json && cat BENCH_model.json
echo "== done; encode the sweep's TPU_RESILIENCY_PALLAS_MAX_WINDOW export in BASELINE.md"
