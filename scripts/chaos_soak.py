"""Chaos soak: drive the coordination and storage planes through seeded fault plans.

Each scenario asserts the job converges to a CORRECT final state
despite injected faults (`tpu_resiliency/platform/chaos.py`):

- **store**: N client threads hammer one ``KVServer`` (sets, shared counter
  adds, reentrant barriers) while resets/truncations/EOF-on-accept hit the
  channel. Convergence = every key present, the counter EXACT (at-most-once
  adds under retry — the req_id dedup), barriers released the right number of
  times.
- **replication**: a 3-clique ``replicate()`` + ``retrieve()`` round under p2p
  faults. Convergence = every surviving mirror and every routed shard is
  byte-identical to the payload its owner saved.
- **disk**: two ranks save two replicated checkpoint iterations while a seeded
  ``disk.write.bitflip`` plan corrupts one rank's newest shard at write time;
  ``LocalCheckpointManager.load()`` must climb the recovery ladder. With only
  the rank's own copy corrupt: quarantine → peer retrieve → byte-identical
  tree, no exception. With the clique mirror ALSO corrupt (``--fallback``
  variant): every rank agrees on and loads the older iteration. Both variants
  assert ``ckpt_quarantined`` events and ``tpu_ckpt_integrity_failures_total``
  in the aggregated metrics.
- **coding**: the byte-economy campaign — a 4-rank erasure clique saves under
  network pressure, then a victim death + a holder death + a seeded parity
  bitflip force the recovery ladder to ATTEMPT reconstruction, fail CLOSED
  (never a false-positive container), and agree the keyframe fallback, which
  reconstructs byte-identically; a 2-rank delta chain then breaks its base
  and must drop exactly one mirror (``ckpt_delta_applied{broken}``) while
  saves/loads stay healthy. The full seeded fault-identity tuple reproduces.
- **elastic**: the shrink-and-continue chain — a 4-rank dp world checkpoints
  with layout meta, the seed-chosen victim is preempted (disk gone), the
  survivors resume resharded (``load_resharded``) and save at the shrunken
  layout, then the victim returns wiped and the wide world reshards back up.
  Convergence = every resumed world byte-identical, the shrink's peer traffic
  strictly less than whole mirrors, ``tpu_reshard_*`` metrics aggregate.
- **cold-start**: checkpoints that outlive the job — a 3-rank job archives two
  keyframes to the durable cold tier (``checkpoint/coldtier.py``), then its
  ENTIRE process tree is SIGKILLed mid-training. A fresh 2-rank world with an
  EMPTY workdir resumes from the cold tier alone, byte-identical. The seeded
  bitflip variant corrupts one archived payload byte (victim owner + offset
  derived from the seed): the fresh world refuses it fail-closed
  (``coldtier_fetch{outcome="corrupt"}``) and the group agrees to climb to the
  next-older covered iteration. Outcome tuple reproduces run-to-run per seed.
- **launcher**: the real ``tpu-ft-launcher`` restart chain (worker fails round
  0, succeeds round 1) with FT monitors on, under env-propagated chaos hitting
  the store AND ipc channels. Convergence = exit 0 + the events file shows at
  least one reset and one truncation injected per channel.
- **mixed**: the multi-fault campaign — an injected straggler driving the
  policy → remediation loop, a store reset, and a disk bitflip landing during
  an active save — with the incident plane watching. Convergence = recovery
  byte-identical, every incident artifact carries the detect→decide→act→
  recover chain and renders through ``incident_report``, the
  ``tpu_incident_*`` / ``tpu_remediation_actions_total`` metrics aggregate
  from the events stream, and the goodput ledger charges the campaign's
  open→close windows to the ``incident`` phase.
- **hang**: the forensics chain — a seed-chosen rank wedges in a GIL-holding
  sleep while its peer blocks in a barrier it never reaches. Convergence =
  ``/hangz`` names the victim mid-stall (census saved to ``hangz.json``),
  ``hang_detected`` carries the location beacon, the victim captured a
  ``stack_dump``, the ``hang_census`` implicates it, and the job restarts to
  a successful round — with an identical forensics schedule across the two
  per-seed runs.
- **autoscale**: the detect→decide→act acceptance — fluctuating capacity
  (a preemption notice that rescinds, then one that doesn't) + a seeded
  straggler + a disk bitflip, run through the goodput-optimal
  ``AutoscaleController`` (act mode) and through a no-controller baseline
  with today's hard-coded reactions. Convergence = the controlled arm's
  measured goodput ratio STRICTLY beats the baseline of the same seed, the
  (decision, action, victim) schedule reproduces across two controlled
  runs, every ``autoscale_decision`` pairs with an ``autoscale_outcome``
  carrying predicted AND realized deltas, and the ``tpu_autoscale_*``
  metrics aggregate.

Every in-process scenario runs TWICE with the same seed and asserts the two
injection schedules are identical — the reproducibility contract: a failure
seen once is a failure you can replay.

    python scripts/chaos_soak.py --smoke            # fast fixed-seed pass (CI)
    python scripts/chaos_soak.py --seed 7           # one full seeded pass
    python scripts/chaos_soak.py --soak-runs 10     # randomized soak

Exit 0 iff every scenario converged.
"""

import argparse
import concurrent.futures as cf
import json
import os
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm  # noqa: E402
from tpu_resiliency.checkpoint.replication import (  # noqa: E402
    CliqueReplicationStrategy,
)
from tpu_resiliency.platform import chaos  # noqa: E402
from tpu_resiliency.platform.store import CoordStore, KVServer  # noqa: E402
from tpu_resiliency.utils.events import read_events  # noqa: E402


def _assert_byteflow_accounts(seen, min_frac: float = 0.95) -> None:
    """The byte-flow acceptance gate: the ledger (``utils/byteflow.py``) must
    attribute ≥95% of every byte this scenario moved to a purpose, and the
    residue must surface as a metric through the same events→metrics path
    everything else uses. Runs inside the chaos scenarios so every smoke and
    e2e repro inherits the gate."""
    from tpu_resiliency.utils.byteflow import ByteFlowLedger
    from tpu_resiliency.utils.metrics import aggregate as _aggregate

    ledger = ByteFlowLedger()
    ledger.observe_many(e.to_record() for e in seen)
    bf = ledger.summary()
    assert bf["total_bytes"] > 0, "scenario moved no accountable bytes"
    assert bf["accounted_frac"] >= min_frac, (
        f"byte-flow ledger attributed only "
        f"{100 * bf['accounted_frac']:.1f}% of {bf['total_bytes']} bytes "
        f"(residue {bf['residue_bytes']}): {bf['families']}"
    )
    pub: list = []
    ledger.publish(lambda source, kind, **p: pub.append({"kind": kind, **p}))
    prom = _aggregate(pub).to_prometheus()
    assert "tpu_byteflow_bytes_total" in prom, prom[:2000]
    assert "tpu_byteflow_accounted_ratio" in prom, prom[:2000]


# -- scenario: coordination store -------------------------------------------

STORE_SPEC = (
    "{seed}:store.send.reset@at=4;store.send.truncate@at=11;"
    "store.recv.reset@at=9;store.recv.truncate@at=20;store.accept.eof@at=2"
)


def scenario_store(seed: int, clients: int = 3, keys: int = 8, rounds: int = 3,
                   spec: str | None = None):
    """Returns the injection schedule; raises on any divergence."""
    plan = chaos.ChaosPlan.parse(spec or STORE_SPEC.format(seed=seed))
    chaos.install_plan(plan)
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []
    try:
        def body(cid: int):
            st = CoordStore("127.0.0.1", srv.port, timeout=30.0)
            stores.append(st)
            for r in range(rounds):
                for k in range(keys):
                    st.set(f"c{cid}/k{k}", (cid, r, k))
                st.add("counter", 1)
                st.barrier(f"round", cid, clients, timeout=30.0)

        with cf.ThreadPoolExecutor(max_workers=clients) as pool:
            for f in [pool.submit(body, c) for c in range(clients)]:
                f.result(timeout=120)

        probe = CoordStore("127.0.0.1", srv.port, timeout=10.0)
        stores.append(probe)
        counter = probe.get("counter", timeout=5.0)
        assert counter == clients * rounds, (
            f"counter diverged: {counter} != {clients * rounds} "
            f"(a retried add double- or under-applied)"
        )
        data = probe.prefix_get("")
        for cid in range(clients):
            for k in range(keys):
                key = f"c{cid}/k{k}"
                assert data.get(key) == (cid, rounds - 1, k), (key, data.get(key))
        status = probe.barrier_status("round")
        assert status["generation"] == rounds, status
    finally:
        chaos.clear_plan()
        for s in stores:
            s.close()
        srv.close()
    return plan.schedule()


# -- scenario: sharded clique + tree collectives ----------------------------

#: Faults timed to land MID-tree-gather and MID-shard-fanout: the first
#: resets hit while edge values are flowing up the tree, the truncations
#:  while the prefix fan-out reads every shard. Every op on these paths is
#: idempotent (set/get/prefix_get) or req_id-deduped (barrier arrivals), so
#: the client's reconnect-retry ladder must absorb all of it byte-identically.
STORE_SCALE_SPEC = (
    "{seed}:store.send.reset@at=5;store.send.truncate@at=13;"
    "store.recv.reset@at=8;store.recv.truncate@at=21;store.accept.eof@at=3;"
    "store.send.reset@at=34;store.recv.truncate@at=55"
)


def scenario_store_scale(seed: int, world: int = 9, shards: int = 2,
                         rounds: int = 2, spec: str | None = None):
    """Tree collectives over a sharded store clique under seeded faults.

    ``world`` member threads run ``StoreComm`` with the TREE paths forced on
    (fanout 2 → a 3-level tree at world 9) over a ``shards``-wide
    ``LocalClique``; per round every member all_gathers a distinct payload,
    crosses a tree barrier, and the leader does a shard-fanout ``prefix_get``
    census. Convergence: every member's every gather is byte-identical to the
    expected list (same values, same order — the flat contract), the census
    sees every member's key across all shards, and two runs of one seed
    produce the identical injection schedule AND identical gathered bytes.
    Returns ``(schedule, gathered_digest)``.
    """
    import hashlib
    import pickle

    from tpu_resiliency.platform.shardstore import LocalClique

    plan = chaos.ChaosPlan.parse(spec or STORE_SCALE_SPEC.format(seed=seed))
    chaos.install_plan(plan)
    clique = LocalClique(shards)
    stores = []
    results: dict[int, list] = {}
    try:
        def body(rank: int):
            st = clique.client(prefix="soak/")
            stores.append(st)
            comm = StoreComm(
                st, rank, list(range(world)), timeout=60.0,
                tree_fanout=2, tree_min_world=2,  # force the tree shape
            )
            gathered = []
            for r in range(rounds):
                st.set(f"census/{rank}/r{r}", (rank, r))
                gathered.append(comm.all_gather((rank, r, b"x" * (rank + 1)),
                                                tag="ag"))
                comm.barrier("bar", timeout=60.0)
                if comm.is_leader:
                    # Peers may already be writing round r+1 keys (the
                    # barrier releases them forward), so assert the fan-out
                    # found EVERY key owed so far, not an exact count.
                    census = st.prefix_get("census/")
                    owed = {
                        f"census/{k}/r{j}"
                        for k in range(world) for j in range(r + 1)
                    }
                    assert owed <= set(census), (
                        f"shard-fanout census lost keys: "
                        f"{sorted(owed - set(census))}"
                    )
            results[rank] = gathered

        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(body, rank) for rank in range(world)]:
                f.result(timeout=180)

        for r in range(rounds):
            expect = [(peer, r, b"x" * (peer + 1)) for peer in range(world)]
            for rank in range(world):
                assert results[rank][r] == expect, (
                    f"tree gather diverged at rank {rank} round {r}: "
                    f"{results[rank][r]!r}"
                )
        digest = hashlib.sha256(
            pickle.dumps([results[rank] for rank in range(world)])
        ).hexdigest()
    finally:
        chaos.clear_plan()
        for s in stores:
            s.close()
        clique.close()
    return plan.schedule(), digest


# -- scenario: replicated-clique failover (SIGKILL a shard) ------------------


def scenario_store_failover(seed: int, world: int = 4, shards: int = 3,
                            rounds: int = 6):
    """SIGKILL one shard of a successor-replicated clique mid-barrier-storm,
    then again mid-rendezvous; every store guarantee must survive failover.

    Leg 1 (barrier storm): ``world`` replicated clients run ``rounds`` of
    set + deduped ``add`` + a fresh named barrier per round over a
    ``shards``-wide :class:`SpawnedClique`. The victim is the shard that
    OWNS the seed-chosen mid-storm barrier, SIGKILLed by worker 0 right
    before its own join — the other workers are already parked on the dying
    primary, so their joins must fail over to the successor's mirrored
    arrival ledger. Every barrier must still open exactly once per joiner
    (no double-fires: each client returns from exactly one blocking join),
    the counter must be EXACT (at-most-once dedup composed with the
    double-write), and the final keyspace complete via dead-shard
    absorption on the fan-out read.

    Leg 2 (rendezvous): ``world`` nodes run a store rendezvous over a fresh
    replicated clique with the seeded victim killed while joins are in
    flight; all nodes must land in one round with unique contiguous ranks.

    Returns ``(kill_round, victims, counter, kv_digest, rdzv_outcome)`` —
    all deterministic per seed; the caller runs the scenario twice and
    compares.
    """
    import hashlib
    import pickle
    import random

    from tpu_resiliency.launcher.rendezvous import (
        RendezvousSettings,
        StoreRendezvous,
    )
    from tpu_resiliency.platform import store as store_mod
    from tpu_resiliency.platform.shardstore import SpawnedClique, shard_of
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils.metrics import aggregate

    rng = random.Random(seed)
    kill_round = rng.randrange(1, rounds - 1)
    # The victim is the shard the mid-storm barrier hashes to, so the
    # parked-join failover path is exercised on EVERY seed (which shard that
    # is still varies with the seeded round choice).
    victim_storm = shard_of(f"fo/storm-{kill_round}", shards)
    victim_rdzv = rng.randrange(shards)
    seen: list = []
    tpu_events.add_sink(seen.append)

    clique = SpawnedClique(shards)
    stores: list = []
    try:
        def body(w: int):
            st = clique.client(prefix="fo/", timeout=60.0,
                               connect_retries=3, retry_budget=1.0,
                               replicate=True)
            stores.append(st)
            for r in range(rounds):
                st.set(f"w{w}/k{r}", (w, r))
                st.add("counter", 1)
                if w == 0 and r == kill_round:
                    # Give peers time to park on this round's barrier, then
                    # SIGKILL its owning shard mid-round.
                    time.sleep(0.3)
                    clique.procs[victim_storm].kill()
                st.barrier(f"storm-{r}", w, world, timeout=120.0)

        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(body, w) for w in range(world)]:
                f.result(timeout=240)

        probe = clique.client(prefix="fo/", timeout=60.0,
                              connect_retries=3, retry_budget=1.0,
                              replicate=True)
        stores.append(probe)
        counter = probe.get("counter", timeout=10.0)
        assert counter == world * rounds, (
            f"counter diverged through failover: {counter} != {world * rounds}"
            f" (a failed-over add double- or under-applied)"
        )
        data = probe.prefix_get("")
        for w in range(world):
            for r in range(rounds):
                assert data.get(f"w{w}/k{r}") == (w, r), (
                    f"key w{w}/k{r} lost through failover: "
                    f"{data.get(f'w{w}/k{r}')!r}"
                )
        kv_digest = hashlib.sha256(
            pickle.dumps(sorted(
                (k, v) for k, v in data.items() if k != "counter"
            ))
        ).hexdigest()
        fo = [e for e in seen if e.kind == "store_failover"]
        assert fo, "SIGKILLed shard produced no store_failover events"
        outcomes = {e.payload.get("outcome") for e in fo}
        assert "barrier" in outcomes, (
            f"parked joins on the dead barrier shard never failed over "
            f"(outcomes {sorted(outcomes)})"
        )
        prom = aggregate(
            [{"kind": e.kind, **e.payload} for e in seen]
        ).to_prometheus()
        assert "tpu_store_failover_total" in prom, prom[:2000]
    finally:
        for s in stores:
            try:
                s.close()
            except Exception:
                pass
        for h, p in clique.endpoints:
            store_mod._breaker_clear(h, p)
        clique.close()

    # -- leg 2: SIGKILL mid-rendezvous --------------------------------------
    clique2 = SpawnedClique(shards)
    stores2: list = []
    outs: dict = {}
    try:
        def join(i: int):
            st = clique2.client(prefix="rdzv/", timeout=60.0,
                                connect_retries=3, retry_budget=1.0,
                                replicate=True)
            stores2.append(st)
            rdzv = StoreRendezvous(st, f"n{i}", RendezvousSettings(
                min_nodes=world, max_nodes=world, join_timeout=120.0,
                last_call_timeout=0.3, keep_alive_interval=0.1,
                keep_alive_timeout=5.0, poll_interval=0.05,
            ))
            outs[f"n{i}"] = rdzv.next_round()
            rdzv.stop_keepalive()

        threads = [threading.Thread(target=join, args=(i,))
                   for i in range(world)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        time.sleep(0.1)  # joins in flight
        clique2.procs[victim_rdzv].kill()
        for t in threads:
            t.join(180.0)
        assert len(outs) == world, (
            f"rendezvous lost nodes through failover: {sorted(outs)}"
        )
        rounds_seen = sorted({o.round for o in outs.values()})
        assert len(rounds_seen) == 1, f"split-brain rounds: {rounds_seen}"
        assert not any(o.is_spare for o in outs.values())
        ranks = sorted(o.node_rank for o in outs.values())
        assert ranks == list(range(world)), (
            f"failover broke rank assignment: {ranks}"
        )
        rdzv_outcome = (sorted(outs), world, rounds_seen)
    finally:
        for s in stores2:
            try:
                s.close()
            except Exception:
                pass
        for h, p in clique2.endpoints:
            store_mod._breaker_clear(h, p)
        clique2.close()
        tpu_events.remove_sink(seen.append)
    return (kill_round, (victim_storm, victim_rdzv), counter, kv_digest,
            rdzv_outcome)


# -- scenario: clique replication -------------------------------------------

#: Send-side faults are retried by the sender and MUST converge; a recv-side
#: payload truncation is silent loss from the sender's view (it already
#: completed) and legitimately degrades the peer instead — that path is
#: covered by tests/checkpoint/test_replication_chaos.py, not this
#: convergence scenario.
REPL_SPEC = (
    "{seed}:p2p.send.reset@at=2;p2p.send.truncate@at=7;p2p.connect.reset@at=5"
)


def scenario_replication(seed: int, world: int = 3, mb: int = 1,
                         spec: str | None = None):
    plan = chaos.ChaosPlan.parse(spec or REPL_SPEC.format(seed=seed))
    chaos.install_plan(plan)
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []
    payloads = {
        r: bytes(bytearray((r * 7 + i) % 251 for i in range(mb << 20)))
        for r in range(world)
    }
    try:
        def mk():
            s = CoordStore("127.0.0.1", srv.port, timeout=60.0)
            stores.append(s)
            return s

        def body(rank: int):
            comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0)
            ex = PeerExchange(mk(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=world
                )
                held = strat.replicate(payloads[rank])
                assert not strat.last_degraded, (
                    f"rank {rank}: peers {strat.last_degraded} degraded — "
                    f"retries should have absorbed this plan's faults"
                )
                for owner, blob in held.items():
                    assert bytes(blob) == payloads[owner], (
                        f"rank {rank}: mirror of {owner} not byte-identical"
                    )
                # Retrieval: rank 0 pretends it lost its own shard; a clique
                # holder must route it back intact.
                needed = 0 if rank == 0 else None
                held_owners = set(held) - ({0} if rank == 0 else set())
                blob = strat.retrieve(
                    needed, held_owners, get_blob=lambda o: bytes(held[o])
                )
                if rank == 0:
                    assert blob is not None and bytes(blob) == payloads[0], (
                        "retrieved shard not byte-identical"
                    )
                return set(held)
            finally:
                ex.close()

        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            helds = [
                f.result(timeout=180)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
        for rank, held in enumerate(helds):
            assert held == set(range(world)), (rank, held)
    finally:
        chaos.clear_plan()
        for s in stores:
            s.close()
        srv.close()
    return plan.schedule()


# -- scenario: disk integrity + recovery ladder ------------------------------

#: Corrupt rank 0's OWN copy of its iteration-2 shard at write time; the
#: clique mirror in r1's dir (same filename, different holder dir) stays
#: intact, so load() must recover via peer retrieve.
DISK_SPEC_OWN = "{seed}:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"
#: Corrupt BOTH copies (own shard and the r1-held mirror): the only rung left
#: is the group-agreed fallback to iteration 1.
DISK_SPEC_BOTH = (
    DISK_SPEC_OWN + ";disk.write.bitflip@peer=r1/iter_0000002_0_local.ckpt"
)


def scenario_disk(seed: int, fallback: bool = False, spec: str | None = None):
    """Seeded disk corruption of rank 0's newest shard under real saves, then
    a collective ``load()`` exercising the recovery ladder end to end.
    Returns the injection schedule; raises on any divergence from the
    expected recovery (byte-identical peer retrieve, or group-agreed
    fallback when the replica is corrupt too)."""
    import shutil
    import numpy as np

    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils.metrics import aggregate

    world = 2
    plan = chaos.ChaosPlan.parse(
        spec or (DISK_SPEC_BOTH if fallback else DISK_SPEC_OWN).format(seed=seed)
    )
    chaos.install_plan(plan)
    seen: list = []
    tpu_events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0)
    root = tempfile.mkdtemp(prefix="chaos_disk.")
    stores: list = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=30.0)
        stores.append(s)
        return s

    def tree(rank: int, it: int):
        return {"w": np.full((2048,), rank * 10.0 + it, np.float32), "step": it}

    def body(rank: int, gen: int, do_save: bool):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0,
                         generation=gen)
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            mgr = LocalCheckpointManager(
                root, rank=rank, comm=comm, replication=strat, keep=2
            )
            if do_save:
                # Materialized saves: deterministic per-file write sequences,
                # which is what makes the injection schedule reproducible.
                mgr.save(1, PyTreeStateDict(tree(rank, 1)), is_async=False)
                mgr.save(2, PyTreeStateDict(tree(rank, 2)), is_async=False)
            it_loaded, tensors = None, None
            if not do_save:
                hollow, tensors, meta = mgr.load()
                it_loaded = meta["iteration"]
                tensors = np.asarray(tensors[0]).copy()
            mgr.close()
            return it_loaded, tensors
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(body, r, 0, True) for r in range(world)]:
                f.result(timeout=120)
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            loaded = [
                f.result(timeout=120)
                for f in [pool.submit(body, r, 1, False) for r in range(world)]
            ]
        want_iter = 1 if fallback else 2
        for rank, (it, w) in enumerate(loaded):
            assert it == want_iter, (
                f"rank {rank} resumed from iteration {it}, wanted {want_iter} "
                f"(ladder {'fallback' if fallback else 'peer retrieve'} failed)"
            )
            expect = np.full((2048,), rank * 10.0 + want_iter, np.float32)
            assert np.array_equal(w, expect), (
                f"rank {rank}: recovered tree not byte-identical @ iter {it}"
            )
        quarantined = [e for e in seen if e.kind == "ckpt_quarantined"]
        assert quarantined, "corrupt shard was never quarantined"
        rdir = os.path.join(root, "s0", "r0")
        assert any(".corrupt" in n for n in os.listdir(rdir)), (
            "no *.corrupt forensics file in the holder dir"
        )
        if fallback:
            assert any(e.kind == "ckpt_fallback" for e in seen), (
                "group never recorded the fallback decision"
            )
        # The acceptance surface: the same aggregation the metrics-dump CLI
        # runs must show the integrity counters.
        reg = aggregate([{"kind": e.kind, **e.payload} for e in seen])
        prom = reg.to_prometheus()
        assert "tpu_ckpt_integrity_failures_total" in prom, prom[:2000]
        assert 'kind="ckpt_quarantined"' in prom, prom[:2000]
        _assert_byteflow_accounts(seen)
    finally:
        chaos.clear_plan()
        tpu_events.remove_sink(seen.append)
        for s in stores:
            s.close()
        srv.close()
        shutil.rmtree(root, ignore_errors=True)
    return plan.schedule()


# -- scenario: checkpoint byte-economy (erasure + delta) ----------------------

#: Transient network pressure rides along (sender-retried, MUST converge);
#: the coding-specific faults (holder death, parity bitflip, chain break)
#: are seeded below with identities derived from the same seed.
CODING_SPEC = "{seed}:p2p.send.reset@at=2;store.send.reset@at=9"


def scenario_coding(seed: int, spec: str | None = None):
    """The byte-economy plane's fault campaign, three chained phases:

    1. a 4-rank erasure clique (k=2, parity 2) saves two iterations under a
       seeded network plan (sender-retried — the saves must converge);
    2. the seed picks a victim rank (death: disk wiped), one of its block
       HOLDERS loses the victim's newest block (holder died mid-save), and
       another holder's block takes a seeded BITFLIP — the surviving block
       census still reads reconstructible (2 of k=2 listed), so the ladder
       ATTEMPTS the reconstruction and must fail CLOSED on the corrupt
       block (no false-positive container), then the group agrees the
       fallback to the previous iteration, which reconstructs from ITS
       (intact) parity blocks byte-identically;
    3. a 2-rank delta chain (keyframe + chunk-diff rounds) where the seeded
       rank misses the base container — the next delta apply must drop that
       mirror with ``ckpt_delta_applied{broken}`` while the save and a
       subsequent load stay healthy.

    Returns ``(injection_schedule, victim, dead_holder, flip_holder,
    flip_offset, chain_breaker, fallback_iteration)`` — the whole tuple must
    reproduce run-to-run per seed."""
    import shutil

    import numpy as np

    from tpu_resiliency.checkpoint.coding import ErasureReplicationStrategy
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils.metrics import aggregate

    world = 4
    plan = chaos.ChaosPlan.parse((spec or CODING_SPEC).format(seed=seed))
    chaos.install_plan(plan)
    rng = np.random.default_rng(seed)
    victim = int(rng.integers(world))
    others = [r for r in range(world) if r != victim]
    dead_holder = others[int(rng.integers(len(others)))]
    flip_holder = [r for r in others if r != dead_holder][
        int(rng.integers(len(others) - 1))
    ]
    seen: list = []
    tpu_events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0)
    root = tempfile.mkdtemp(prefix="chaos_coding.")
    droot = tempfile.mkdtemp(prefix="chaos_coding_delta.")
    stores: list = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=30.0)
        stores.append(s)
        return s

    def tree(rank: int, it: int):
        return {"w": np.full((65536,), rank * 100.0 + it, np.float32),
                "step": it}

    def ec_body(rank: int, gen: int, do_save: bool, wipe: bool):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0,
                         generation=gen)
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            strat = ErasureReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world,
                parity=2,
            )
            mgr = LocalCheckpointManager(
                root, rank=rank, comm=comm, replication=strat, keep=2
            )
            if wipe:
                mgr.wipe()
            if do_save:
                mgr.save(1, PyTreeStateDict(tree(rank, 1)), is_async=False)
                mgr.save(2, PyTreeStateDict(tree(rank, 2)), is_async=False)
                mgr.close()
                return None
            hollow, tensors, meta = mgr.load()
            it = meta["iteration"]
            w = np.asarray(tensors[0]).copy()
            mgr.close()
            return it, w
        finally:
            ex.close()

    flip_offset = None
    chain_breaker = int(rng.integers(2))
    try:
        # Phase 1: erasure saves under the network plan.
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(ec_body, r, 0, True, False)
                      for r in range(world)]:
                f.result(timeout=120)
        # Phase 2: victim dies; one of its iter-2 block holders died
        # mid-save (block file gone), the other's block takes a bitflip.
        def block_path(holder: int, it: int):
            d = os.path.join(root, "s0", f"r{holder}")
            names = [
                n for n in os.listdir(d)
                if n.startswith(f"iter_{it:07d}_{victim}_b")
                and n.endswith(".ecblk")
            ]
            assert len(names) == 1, names
            return os.path.join(d, names[0])

        os.unlink(block_path(dead_holder, 2))
        fpath = block_path(flip_holder, 2)
        blob = bytearray(open(fpath, "rb").read())
        flip_offset = int(rng.integers(len(blob) - 64, len(blob)))
        blob[flip_offset] ^= 0x40
        open(fpath, "wb").write(bytes(blob))
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            loaded = [
                f.result(timeout=120)
                for f in [pool.submit(ec_body, r, 1, False, r == victim)
                          for r in range(world)]
            ]
        for rank, (it, w) in enumerate(loaded):
            assert it == 1, (
                f"rank {rank} resumed from {it}, wanted the agreed fallback 1"
            )
            expect = np.full((65536,), rank * 100.0 + 1, np.float32)
            assert np.array_equal(w, expect), (
                f"rank {rank}: fallback tree not byte-identical"
            )
        recon = [e for e in seen if e.kind == "ckpt_parity_reconstruct"]
        outcomes = [e.payload["outcome"] for e in recon]
        assert "failed" in outcomes and outcomes[-1] == "ok", (
            f"want a failed iter-2 reconstruction then an ok iter-1 one, "
            f"got {outcomes}"
        )
        assert any(e.kind == "ckpt_fallback" for e in seen), (
            "group never agreed the fallback"
        )
        # Phase 3: delta-chain break on a 2-rank mirror clique.
        def delta_body(rank: int):
            comm = StoreComm(mk(), rank, [0, 1], timeout=60.0, generation=9)
            ex = PeerExchange(mk(), rank, timeout=30.0)
            ex.start()
            try:
                strat = CliqueReplicationStrategy(
                    comm, ex, replication_jump=1, replication_factor=2
                )
                mgr = LocalCheckpointManager(
                    droot, rank=rank, comm=comm, replication=strat,
                    keep=2, delta_interval=4,
                )
                mgr.save(1, PyTreeStateDict(tree(rank, 1)), is_async=False)
                comm.barrier("kf")
                if rank == chain_breaker:
                    # This rank missed the keyframe base of its peer.
                    peer = 1 - rank
                    p = os.path.join(
                        droot, "s0", f"r{rank}",
                        f"iter_{1:07d}_{peer}_local.ckpt",
                    )
                    os.unlink(p)
                comm.barrier("broke")
                mgr.save(2, PyTreeStateDict(tree(rank, 2)), is_async=False)
                hollow, tensors, meta = mgr.load()
                it = meta["iteration"]
                mgr.close()
                return it
            finally:
                ex.close()

        with cf.ThreadPoolExecutor(max_workers=2) as pool:
            its = [
                f.result(timeout=120)
                for f in [pool.submit(delta_body, r) for r in range(2)]
            ]
        assert its == [2, 2], its
        broken = [
            e for e in seen
            if e.kind == "ckpt_delta_applied"
            and e.payload["outcome"] == "broken"
        ]
        assert broken and broken[0].payload["owner"] == 1 - chain_breaker, (
            f"want exactly the chain-breaker's peer mirror dropped, got "
            f"{[e.payload for e in broken]}"
        )
        assert any(
            e.kind == "ckpt_delta_applied" and e.payload["outcome"] == "ok"
            for e in seen
        ), "the intact side of the delta round never applied"
        # Acceptance surface: the same aggregation metrics_dump runs.
        reg = aggregate([{"kind": e.kind, **e.payload} for e in seen])
        prom = reg.to_prometheus()
        assert "tpu_ckpt_parity_reconstructions_total" in prom, prom[:2000]
        assert 'outcome="failed"' in prom, prom[:2000]
        assert "tpu_ckpt_delta_applied_total" in prom, prom[:2000]
        assert "tpu_ckpt_parity_bytes_total" in prom, prom[:2000]
        _assert_byteflow_accounts(seen)
    finally:
        chaos.clear_plan()
        tpu_events.remove_sink(seen.append)
        for s in stores:
            s.close()
        srv.close()
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(droot, ignore_errors=True)
    return (
        plan.schedule(), victim, dead_holder, flip_holder, flip_offset,
        chain_breaker, 1,
    )


# -- scenario: elastic shrink / resharded resume / re-expand ------------------

#: A light network plan rides along (sender-retried, MUST converge) so the
#: elastic chain is exercised under the same fault pressure as the others.
#: The second p2p reset (``at=13``) is aimed inside the shrink's parallel
#: ranged-fetch window (the save-phase replication fan-out plus its one
#: retry consume indices 0..9; the concurrent ``fetch_ranges`` traffic owns
#: 10..25), so every soak run proves the degraded-holder re-route under the
#: overlapped serve/fetch pool, not just under serial resharding. Which
#: *thread's* send draws index 13 is racy, but the convergence contract —
#: schedule, victim, and per-rank byte splits — is thread-independent: the
#: splits come from the plan summary, not from who fetched what when.
ELASTIC_SPEC = "{seed}:p2p.send.reset@at=3+13;store.send.reset@at=7"


def scenario_elastic(seed: int, spec: str | None = None):
    """Seeded preemption of one rank mid-run → shrink → resharded resume →
    save at the shrunken layout → re-expand → resharded resume again.

    The seed picks the victim rank. Convergence = every resumed world's
    reassembled global state is byte-identical to what the full world saved,
    the shrink fetched strictly newly-owned ranges (peer bytes < a full
    shard), and the ``tpu_reshard_*`` metrics aggregate from the events
    stream. Returns ``(injection_schedule, victim, per-phase byte splits)`` —
    the whole tuple must reproduce run-to-run per seed."""
    import shutil
    import numpy as np

    from tpu_resiliency.checkpoint import reshard as ckpt_reshard
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils.metrics import aggregate

    world = 4
    victim = seed % world
    survivors = [r for r in range(world) if r != victim]
    plan = chaos.ChaosPlan.parse(spec or ELASTIC_SPEC.format(seed=seed))
    chaos.install_plan(plan)
    seen: list = []
    tpu_events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0)
    root = tempfile.mkdtemp(prefix="chaos_elastic.")
    stores: list = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=30.0)
        stores.append(s)
        return s

    G = np.arange(32 * 8, dtype=np.float32).reshape(32, 8) * 3.0
    layout4 = ckpt_reshard.TreeLayout(
        [("dp", world)], list(range(world)),
        [ckpt_reshard.LeafSpec(G.shape, "float32", ("dp",))],
    )

    def mgr_for(rank, ranks, gen, ex):
        comm = StoreComm(mk(), rank, ranks, timeout=60.0, generation=gen)
        strat = CliqueReplicationStrategy(
            comm, ex, replication_jump=1, replication_factor=2
        )
        return LocalCheckpointManager(
            root, rank=rank, comm=comm, replication=strat, keep=2
        )

    def full_save(rank):
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            mgr = mgr_for(rank, list(range(world)), 0, ex)
            tree = {"w": ckpt_reshard.slice_local([G], layout4, rank)[0],
                    "step": 1}
            mgr.save(1, PyTreeStateDict(tree), is_async=False, layout=layout4)
            mgr.close()
        finally:
            ex.close()

    def shrink_resume_and_save(rank):
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            mgr = mgr_for(rank, survivors, 1, ex)
            hollow, tensors, meta = mgr.load_resharded()
            got = np.asarray(tensors[0]).copy()
            layout_m = ckpt_reshard.TreeLayout.from_meta(meta["layout"])
            mgr.save(
                2, PyTreeStateDict({"w": got, "step": 2}),
                is_async=False, layout=layout_m,
            )
            mgr.close()
            return got
        finally:
            ex.close()

    def expand_resume(rank):
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            mgr = mgr_for(rank, list(range(world)), 2, ex)
            hollow, tensors, meta = mgr.load_resharded()
            got = np.asarray(tensors[0]).copy()
            mgr.close()
            return got, meta["iteration"]
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(full_save, r) for r in range(world)]:
                f.result(timeout=120)
        # The seeded preemption: the victim's node is gone (its disk with it).
        shutil.rmtree(os.path.join(root, "s0", f"r{victim}"), ignore_errors=True)
        with cf.ThreadPoolExecutor(max_workers=len(survivors)) as pool:
            shrunk = [
                f.result(timeout=120)
                for f in [pool.submit(shrink_resume_and_save, r) for r in survivors]
            ]
        layout_m = layout4.retarget(survivors)
        for rank, got in zip(survivors, shrunk):
            want = ckpt_reshard.slice_local([G], layout_m, rank)[0]
            assert np.array_equal(got, want), (
                f"rank {rank}: shrunken resume not byte-identical"
            )
        # Re-expand: the victim returns with a wiped disk; the newest
        # iteration is the SHRUNKEN world's save, so this leg is a true grow.
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            grown = [
                f.result(timeout=120)
                for f in [pool.submit(expand_resume, r) for r in range(world)]
            ]
        for rank, (got, it) in zip(range(world), grown):
            want = ckpt_reshard.slice_local([G], layout4, rank)[0]
            assert it == 2, f"rank {rank} resumed iteration {it}, wanted 2"
            assert np.array_equal(got, want), (
                f"rank {rank}: re-expanded resume not byte-identical"
            )
        plans = [e for e in seen if e.kind == "reshard_plan"]
        directions = sorted({e.payload["direction"] for e in plans})
        assert directions == ["grow", "shrink"], directions
        fetches = [e for e in seen if e.kind == "reshard_fetch"]
        shard_bytes = layout4.local_nbytes(0, 0)
        shrink_peer = sum(
            e.payload["bytes"] for e in fetches
            if e.payload.get("via") == "peer"
            and any(p.payload["direction"] == "shrink"
                    and p.payload["rank"] == e.payload["rank"] for p in plans)
        )
        assert 0 < shrink_peer < len(survivors) * shard_bytes, (
            f"shrink moved {shrink_peer} peer bytes (full shard is "
            f"{shard_bytes}) — the ranged path should move strictly less "
            f"than whole mirrors"
        )
        reg = aggregate([{"kind": e.kind, **e.payload} for e in seen])
        prom = reg.to_prometheus()
        for want in ("tpu_reshard_bytes_total", "tpu_reshard_ranks_total",
                     'direction="shrink"', 'direction="grow"'):
            assert want in prom, f"{want} missing:\n{prom[:2000]}"
        splits = sorted(
            (e.payload["rank"], e.payload["direction"],
             e.payload["local_bytes"], e.payload["peer_bytes"])
            for e in plans
        )
        # The mid-fetch reset must have been consumed inside the reshard
        # window AND recovered from: either the sender-side retry absorbed it
        # (a ``p2p_retry`` per reset) or the requester saw the torn reply and
        # re-routed around the degraded holder (``ckpt_integrity_failure``
        # with stage="reshard-fetch"). Both are convergent; neither may be
        # silent.
        p2p_resets = [e for e in seen if e.kind == "chaos_inject"
                      and e.payload["channel"] == "p2p"
                      and e.payload["op"] == "send"]
        assert len(p2p_resets) >= 2, (
            f"expected both seeded p2p resets to fire, saw "
            f"{[(e.payload['op'], e.payload['index']) for e in p2p_resets]}"
        )
        recovered = [e for e in seen if e.kind == "p2p_retry"] + [
            e for e in seen if e.kind == "ckpt_integrity_failure"
            and e.payload.get("stage") == "reshard-fetch"
        ]
        assert len(recovered) >= len(p2p_resets), (
            f"{len(p2p_resets)} p2p resets but only {len(recovered)} "
            f"recovery artifacts — a fault was swallowed without re-route"
        )
        _assert_byteflow_accounts(seen)
    finally:
        chaos.clear_plan()
        tpu_events.remove_sink(seen.append)
        for s in stores:
            s.close()
        srv.close()
        shutil.rmtree(root, ignore_errors=True)
    return (plan.schedule(), victim, splits)


# -- scenario: mixed multi-fault campaign ------------------------------------

#: Straggler + network + disk in ONE campaign: resets on the store and p2p
#: channels while the ranks coordinate, a bitflip landing on rank 0's newest
#: shard DURING the active save, and an injected straggler report stream
#: driving the policy → remediation loop — the scenario-diversity flagship
#: (ROADMAP item 5). Network faults ride connect/send, the retried-and-MUST-
#: converge side (REPL_SPEC's comment explains why recv-side loss is a
#: degrade path, excluded from convergence scenarios).
MIXED_SPEC = (
    "{seed}:store.connect.reset@at=2;p2p.send.reset@at=2;"
    "disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"
)


def _synthetic_report(perf: dict):
    from tpu_resiliency.telemetry.reporting import Report

    return Report(
        rank=0, world_size=len(perf), iteration=0, section_names=("step",),
        relative_section_scores={"step": 1.0},
        individual_section_scores={"step": 1.0},
        perf_scores=dict(perf), z_scores={r: 0.0 for r in perf},
        ewma_scores=dict(perf),
    )


def scenario_mixed(seed: int, workdir: str, spec: str | None = None):
    """Multi-fault campaign with the incident plane watching. Asserts the
    full detect→decide→act→recover chain lands in an incident artifact that
    ``incident_report`` accepts, that recovery still converges byte-identical
    under the combined faults, and that the ``tpu_incident_*`` /
    ``tpu_remediation_actions_total`` metrics are visible through the same
    aggregation ``metrics_dump`` runs. Returns the injection schedule."""
    import shutil
    import numpy as np

    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.launcher.incident import IncidentEngine, read_incident
    from tpu_resiliency.telemetry.policy import HealthVectorPolicy
    from tpu_resiliency.telemetry.remediation import RemediationEngine
    from tpu_resiliency.tools import incident_report
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils import flight_recorder
    from tpu_resiliency.utils.metrics import aggregate

    world = 2
    os.makedirs(workdir, exist_ok=True)
    events_file = os.path.join(workdir, "events.jsonl")
    incidents_dir = os.path.join(workdir, "incidents")
    ckpt_root = os.path.join(workdir, "ckpt")
    for stale in (events_file,):
        if os.path.exists(stale):
            os.unlink(stale)
    shutil.rmtree(incidents_dir, ignore_errors=True)
    shutil.rmtree(ckpt_root, ignore_errors=True)

    plan = chaos.ChaosPlan.parse(spec or MIXED_SPEC.format(seed=seed))
    chaos.install_plan(plan)
    seen: list = []
    jsonl = tpu_events.JsonlSink(events_file)
    tpu_events.add_sink(seen.append)
    tpu_events.add_sink(jsonl)
    flight_recorder.install(incidents_dir, capacity=64, install_handlers=False)
    engine = IncidentEngine(
        incidents_dir, node_id="mixed", auto_open=True, events_file=events_file
    )
    engine.attach()
    srv = KVServer(host="127.0.0.1", port=0)
    stores: list = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=30.0)
        stores.append(s)
        return s

    def tree(rank: int, it: int):
        return {"w": np.full((2048,), rank * 10.0 + it, np.float32), "step": it}

    def body(rank: int, gen: int, do_save: bool):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0,
                         generation=gen)
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            mgr = LocalCheckpointManager(
                ckpt_root, rank=rank, comm=comm, replication=strat, keep=2
            )
            if do_save:
                mgr.save(1, PyTreeStateDict(tree(rank, 1)), is_async=False)
                mgr.save(2, PyTreeStateDict(tree(rank, 2)), is_async=False)
            it_loaded, tensors = None, None
            if not do_save:
                hollow, tensors, meta = mgr.load()
                it_loaded = meta["iteration"]
                tensors = np.asarray(tensors[0]).copy()
            mgr.close()
            return it_loaded, tensors
        finally:
            ex.close()

    try:
        # Phase 1: the straggler leg — synthetic slow-rank reports drive the
        # policy into remediation (proactive checkpoint + exclude), then clean
        # reports recover it; the incident engine auto-opens and auto-closes.
        ckpt_calls: list = []
        remediation = RemediationEngine(
            checkpoint_fn=lambda: ckpt_calls.append(1),
            publish_degraded_fn=lambda d: None,
        )
        policy = HealthVectorPolicy(patience=2, recovery=1, sinks=[remediation])
        policy.observe(_synthetic_report({0: 1.0, 1: 0.3}))
        policy.observe(_synthetic_report({0: 1.0, 1: 0.3}))
        assert engine.is_open, "straggler incident never opened"
        policy.observe(_synthetic_report({0: 1.0, 1: 0.99}))
        assert not engine.is_open, "straggler incident never auto-closed"
        assert ckpt_calls, "remediation never ran the proactive checkpoint"
        assert ("exclude", "ok") in remediation.history, remediation.history

        # Phase 2: saves under the store-reset + disk-bitflip plan (the flip
        # lands mid-save on rank 0's newest shard), then a collective load
        # climbing the recovery ladder — this is its own incident.
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            for f in [pool.submit(body, r, 0, True) for r in range(world)]:
                f.result(timeout=120)
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            loaded = [
                f.result(timeout=120)
                for f in [pool.submit(body, r, 1, False) for r in range(world)]
            ]
        for rank, (it, w) in enumerate(loaded):
            assert it == 2, f"rank {rank} resumed from {it}, wanted 2"
            expect = np.full((2048,), rank * 10.0 + 2, np.float32)
            assert np.array_equal(w, expect), (
                f"rank {rank}: recovered tree not byte-identical under "
                f"mixed faults"
            )
        assert engine.is_open, "quarantine incident never opened"
        engine.close(outcome="recovered")

        assert len(engine.artifacts) >= 2, engine.artifacts
        import contextlib
        import io

        for path in engine.artifacts:
            doc = read_incident(path)
            with contextlib.redirect_stdout(io.StringIO()):
                assert incident_report.main([path]) == 0, path
        straggler_doc = read_incident(engine.artifacts[0])
        phases = [m["phase"] for m in straggler_doc["chain"]]
        for p in ("detect", "decide", "act", "recover"):
            assert p in phases, (p, phases)
        assert straggler_doc["slo"]["time_to_detect_s"] is not None
        assert straggler_doc["slo"]["time_to_recover_s"] is not None

        # The acceptance surface: the same aggregation metrics_dump runs.
        reg = aggregate(read_events(events_file))
        prom = reg.to_prometheus()
        for want in (
            "tpu_incidents_total", "tpu_incident_time_to_recover_seconds",
            "tpu_remediation_actions_total", 'kind="bitflip"',
        ):
            assert want in prom, f"{want} missing from metrics:\n{prom[:2000]}"

        # Goodput attribution: the campaign's incident windows must be
        # charged to the ``incident`` phase by the same ledger the launcher's
        # /goodput endpoint and metrics_dump --goodput run.
        from tpu_resiliency.utils.goodput import GoodputLedger

        ledger = GoodputLedger()
        ledger.observe_many(read_events(events_file))
        gp = ledger.summary()
        assert gp["phases"]["incident"] > 0, (
            f"mixed campaign charged no incident time: {gp['phases']}"
        )
        assert abs(sum(gp["phases"].values()) - gp["wall_clock_s"]) < 1e-3, gp
    finally:
        chaos.clear_plan()
        engine.detach()
        flight_recorder.uninstall()
        tpu_events.remove_sink(seen.append)
        tpu_events.remove_sink(jsonl)
        jsonl.close()
        for s in stores:
            s.close()
        srv.close()
    return plan.schedule()


# -- scenario: launcher restart chain ---------------------------------------

LAUNCHER_SPEC = (
    "{seed}:store.send.reset@at=3;store.send.truncate@at=9;"
    "ipc.send.reset@at=1;ipc.send.truncate@at=4"
)

_WORKER = textwrap.dedent(
    """
    import os, sys, time
    from tpu_resiliency.watchdog import RankMonitorClient

    rnd = int(os.environ["TPU_FT_RESTART_COUNT"])
    c = RankMonitorClient()
    c.init_workload_monitoring()
    for _ in range(4):
        c.send_heartbeat()
        time.sleep(0.05)
    c.shutdown_workload_monitoring()
    if rnd == 0:
        sys.exit(3)
    print("recovered in round", rnd)
    """
)


def scenario_launcher(seed: int, workdir: str, timeout: float = 180.0):
    """Real restart chain under env-propagated chaos. Returns per-channel
    ``{(channel, fault): count}`` observed in the events stream."""
    os.makedirs(workdir, exist_ok=True)
    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    events_file = os.path.join(workdir, "events.jsonl")
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.update(
        JAX_PLATFORMS="cpu",
        TPU_RESILIENCY_CHAOS=LAUNCHER_SPEC.format(seed=seed),
        TPU_RESILIENCY_EVENTS_FILE=events_file,
        PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""),
    )
    cmd = [
        sys.executable, "-m", "tpu_resiliency.launcher.launch",
        "--standalone", "--nproc-per-node", "1", "--max-restarts", "3",
        "--rdzv-last-call", "0.2", "--monitor-interval", "0.1",
        "--ft-param-initial_rank_heartbeat_timeout", "30",
        "--ft-param-rank_heartbeat_timeout", "30",
        "--run-dir", os.path.join(workdir, "run"),
        script,
    ]
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, env=env, cwd=workdir
    )
    assert r.returncode == 0, (
        f"launcher chain under chaos failed rc={r.returncode}\n"
        f"stdout: {r.stdout[-2000:]}\nstderr: {r.stderr[-2000:]}"
    )
    assert "recovered in round" in r.stdout, r.stdout[-2000:]
    injected: dict[tuple, int] = {}
    for ev in read_events(events_file):
        if ev.get("kind") == "chaos_inject":
            key = (ev.get("channel"), ev.get("fault"))
            injected[key] = injected.get(key, 0) + 1
    for want in (
        ("store", "reset"), ("store", "truncate"),
        ("ipc", "reset"), ("ipc", "truncate"),
    ):
        assert injected.get(want, 0) >= 1, (
            f"fault {want} never injected — the channel survived nothing; "
            f"observed: {injected}"
        )
    return injected


# -- scenario: hang forensics ------------------------------------------------

_HANG_WORKER = textwrap.dedent(
    """
    import importlib, json, os, sys, threading, time
    from tpu_resiliency.platform.store import CoordStore
    from tpu_resiliency.utils import location
    from tpu_resiliency.utils.events import record
    from tpu_resiliency.watchdog.monitor_client import RankMonitorClient

    inj = importlib.import_module("tpu_resiliency.inprocess.tools.inject_fault")
    inj.GIL_SLEEP_CHUNK_S = 2.0

    victim = int(sys.argv[1])
    rank = int(os.environ["RANK"])
    rnd = int(os.environ["TPU_FT_RESTART_COUNT"])

    client = RankMonitorClient()
    client.init_workload_monitoring()

    def beats():
        while True:
            try:
                client.send_heartbeat()
            except Exception:
                return
            time.sleep(0.2)

    threading.Thread(target=beats, daemon=True).start()
    store = CoordStore(
        os.environ["TPU_RESILIENCY_STORE_HOST"],
        int(os.environ["TPU_RESILIENCY_STORE_PORT"]), prefix="hangsoak/",
    )
    for i in range(2):
        location.note_step(i)
        record("inprocess", "iteration_start", iteration=i)
        store.barrier(f"step-{rnd}-{i}", rank, 2, timeout=60.0)

    if rnd == 0:
        if rank == victim:
            client.start_section("step")
            inj.inject_fault(inj.Fault.GIL_SLEEP, duration=60.0)
            sys.exit(0)
        try:
            store.barrier("stall", rank, 2, timeout=120.0)
        except Exception:
            pass
        time.sleep(120)
        sys.exit(0)
    print("recovered in round", rnd)
    """
)


def scenario_hang(seed: int, workdir: str, timeout: float = 180.0):
    """Seeded stall -> detection -> stack capture -> kill ladder -> restart.

    The seed picks the victim rank; the schedule compared across the two
    per-seed runs is the deterministic forensics chain (victim, detection
    kind, ladder steps, recovery round). The last good ``/hangz`` census is
    saved to ``<workdir>/hangz.json`` so downstream smoke legs can grep the
    live view the operator would have seen.
    """
    import urllib.request

    os.makedirs(workdir, exist_ok=True)
    victim = seed % 2
    script = os.path.join(workdir, "worker.py")
    with open(script, "w") as f:
        f.write(_HANG_WORKER)
    events_file = os.path.join(workdir, "events.jsonl")
    for stale in (events_file, os.path.join(workdir, "hangz.json")):
        if os.path.exists(stale):
            os.unlink(stale)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.update(
        JAX_PLATFORMS="cpu",
        TPU_RESILIENCY_EVENTS_FILE=events_file,
        PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""),
    )
    run_dir = os.path.join(workdir, "run")
    out_path = os.path.join(workdir, "launcher.out")
    cmd = [
        sys.executable, "-m", "tpu_resiliency.launcher.launch",
        "--standalone", "--nproc-per-node", "2", "--max-restarts", "2",
        "--rdzv-last-call", "0.2", "--monitor-interval", "0.1",
        "--telemetry-port", "0",
        "--ft-param-initial_rank_heartbeat_timeout", "15",
        "--ft-param-rank_heartbeat_timeout", "1.0",
        "--ft-param-workload_check_interval", "0.25",
        "--ft-param-stack_dump_grace", "5.0",
        "--run-dir", run_dir,
        "--incidents-dir", os.path.join(workdir, "incidents"),
        script, str(victim),
    ]
    # File-backed stdio: monitors/workers inherit these fds, so pipes would
    # deadlock once full and never EOF while any child lives.
    with open(out_path, "w") as out:
        proc = subprocess.Popen(
            cmd, stdout=out, stderr=subprocess.STDOUT, env=env, cwd=workdir
        )
    hangz = None
    try:
        port_file = os.path.join(run_dir, "telemetry.port")
        deadline = time.time() + 60
        while not os.path.exists(port_file) and time.time() < deadline:
            assert proc.poll() is None, open(out_path).read()[-2000:]
            time.sleep(0.2)
        port = int(open(port_file).read().strip())
        deadline = time.time() + 90
        while time.time() < deadline and proc.poll() is None:
            try:
                doc = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/hangz", timeout=5).read())
            except OSError:
                time.sleep(0.2)
                continue
            if any(s.get("rank") == victim for s in doc.get("suspects", [])):
                hangz = doc
                break
            time.sleep(0.2)
        assert hangz is not None, "/hangz never named the seeded victim"
        with open(os.path.join(workdir, "hangz.json"), "w") as f:
            json.dump(hangz, f, indent=2)
        rc = proc.wait(timeout=timeout)
        assert rc == 0, f"hang chain rc={rc}\n" + open(out_path).read()[-2000:]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    # -- the deterministic schedule ---------------------------------------
    evs = read_events(events_file)
    hangs = [e for e in evs if e.get("kind") == "hang_detected"]
    assert len(hangs) == 1 and hangs[0].get("global_rank") == victim, hangs
    assert "last seen in" in hangs[0].get("reason", ""), hangs[0]
    ladder = tuple(
        e.get("step") for e in evs
        if e.get("kind") == "kill_ladder" and e.get("global_rank") == victim
    )
    # Two capture paths race inside the victim (monitor long-poll vs SIGUSR1
    # nudge); under GIL starvation either may be the one that lands before
    # SIGKILL — any victim capture satisfies the contract.
    victim_dumped = any(
        e.get("kind") == "stack_dump" and e.get("rank") == victim
        for e in evs
    )
    assert victim_dumped, "victim never captured a stack dump"
    census_evs = [e for e in evs if e.get("kind") == "hang_census"]
    assert census_evs and any(
        s.get("rank") == victim for s in (census_evs[0].get("suspects") or [])
    ), census_evs
    recovered = max(
        (e.get("round", 0) for e in evs if e.get("kind") == "round_succeeded"),
        default=None,
    )
    assert recovered is not None, "no successful round after the hang"
    return (victim, ladder, recovered)


# -- scenario: goodput-optimal autoscale under fluctuating capacity -----------

#: The disk fault both arms pay identically: a seeded bitflip on the newest
#: proactive-checkpoint container, forcing the quarantine→fallback ladder.
AUTOSCALE_DISK_SPEC = "{seed}:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"


class _AutoscaleSim:
    """A miniature 4-rank job on real wall clock: iteration_start markers at a
    step cadence that the injected conditions (straggler slowdown, restarts,
    resharding stalls) modulate, so the goodput ledger measures the campaign
    exactly as it measures a real run. Record shape = the events JSONL line."""

    STEP_S = 0.02
    WARM_RESTART_S = 0.06
    COLD_RESTART_S = 0.5
    RESHARD_S = 0.12
    PREEMPT_BLOCK_S = 0.4

    def __init__(self, recs: list, ctl=None, world: int = 4):
        self.recs = recs
        self.ctl = ctl
        self.world = world
        self.full_world = world
        self.it = 0

    def emit(self, source, kind, rank=None, pid=0, **payload):
        rec = {"ts": time.time(), "source": source, "kind": kind,
               "pid": pid, "rank": rank, **payload}
        self.recs.append(rec)
        if self.ctl is not None:
            self.ctl.observe(rec)
        return rec

    def steps(self, n: int, slow: float = 1.0):
        """n training steps; a shrunken world steps proportionally slower,
        a straggler inflates every step (synchronous training gates on it)."""
        for _ in range(n):
            time.sleep(self.STEP_S * slow * (self.full_world / self.world))
            self.it += 1
            self.emit("inprocess", "iteration_start", pid=1000,
                      iteration=self.it)

    def downtime(self, seconds: float, kind: str, **payload):
        """Fault evidence, then a dead window; the next step's
        iteration_start closes the ledger's restart interval."""
        self.emit("launcher", kind, **payload)
        time.sleep(seconds)

    # -- controlled-arm actuators (wired into the controller) ---------------

    def swap(self, reason: str):
        self.downtime(self.WARM_RESTART_S, "restart_requested", reason=reason)
        self.emit("launcher", "worker_promoted", outcome="promoted",
                  round=1, park_depth=2)

    def shrink(self, victims, reason: str):
        self.downtime(self.RESHARD_S, "restart_requested", reason=reason)
        self.emit("launcher", "world_resized", direction="shrink",
                  from_world=self.world, to_world=self.world - len(victims))
        self.world -= len(victims)

    def expand(self, reason: str):
        self.downtime(self.RESHARD_S, "restart_requested", reason=reason)
        self.emit("launcher", "world_resized", direction="grow",
                  from_world=self.world, to_world=self.full_world)
        self.world = self.full_world


def _autoscale_campaign(seed: int, workdir: str, controlled: bool,
                        repriced: bool = True):
    """One arm of the campaign: fluctuating capacity (a preemption notice
    that rescinds, then one that doesn't) + an injected straggler + a seeded
    disk fault. ``controlled`` runs the AutoscaleController in act mode;
    the baseline runs the identical fault script with today's hard-coded
    reactions (straggle until death, drain-and-stop on every notice, die at
    the deadline). Returns ``(records, decision_schedule, disk_schedule)``.

    ``repriced`` selects which reshard price the controlled arm's cost model
    reads from its (synthetic) bench artifact — both prices via the SAME
    ``CostModel.from_bench`` path production uses. ``True`` gives it the
    ``phases`` decomposition (plan + fetch = the real per-rank stall once
    serve/fetch/assembly overlap); ``False`` strips the ``phases`` block so
    ``from_bench`` falls back to the serial-era ``ranged_s`` top line, which
    also charges the local assembly that now hides under the fetch. The
    inflated price keeps shrink's predicted gain under the hysteresis bar at
    the ripe preemption, so the old-priced arm declines the resize and pays
    the death it could have dodged — identical fault script, identical
    physics, different constants, measurably worse goodput."""
    import shutil
    import numpy as np

    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
    from tpu_resiliency.launcher.autoscale import AutoscaleController, CostModel
    from tpu_resiliency.telemetry.policy import HealthVectorPolicy
    from tpu_resiliency.telemetry.remediation import RemediationEngine
    from tpu_resiliency.utils import events as tpu_events
    from tpu_resiliency.utils.events import RESERVED_KEYS

    world = 4
    v_straggler = seed % world
    v_rescind = (seed // 4) % world
    v_preempt = (seed // 16) % world
    recs: list = []

    def flatten(e):
        recs.append({
            "ts": e.ts, "source": e.source, "kind": e.kind,
            "pid": e.pid, "rank": e.rank,
            **{f"p_{k}" if k in RESERVED_KEYS else k: v
               for k, v in e.payload.items()},
        })
        if ctl is not None:
            ctl.observe(recs[-1])

    arm = ("ctl_phases" if repriced else "ctl_ranged") if controlled else "base"
    ckpt_root = os.path.join(workdir, f"ckpt_{arm}")
    shutil.rmtree(ckpt_root, ignore_errors=True)
    spares = [1]

    ctl = None
    sim = _AutoscaleSim(recs, ctl=None, world=world)
    proactive_mgr = [None]

    def proactive_ckpt():
        # A REAL checkpoint save: its events (and the disk fault below, which
        # corrupts its successor) ride the same stream the ledger reads.
        if proactive_mgr[0] is None:
            proactive_mgr[0] = LocalCheckpointManager(
                ckpt_root, rank=0, keep=2
            )
        proactive_mgr[0].save(
            1, PyTreeStateDict({"w": np.arange(2048, dtype=np.float32), "step": 1}),
            is_async=False,
        )

    if controlled:
        def swap_restart(reason):
            spares[0] -= 1
            sim.swap(reason)

        engine = RemediationEngine(
            checkpoint_fn=proactive_ckpt,
            spare_capacity_fn=lambda: spares[0],
            publish_degraded_fn=lambda d: None,
            request_restart_fn=swap_restart,
            cooldown=0.0,
        )
        # Price the model the way production does — ``from_bench`` over a
        # bench artifact. Both arms share ranged_s (the serial-era top line
        # = the sim's actual reshard stall); only the repriced arm's doc
        # carries the phase decomposition, whose plan+fetch sum is what the
        # overlapped hot path really stalls a rank for.
        bench_dir = os.path.join(workdir, f"bench_{arm}")
        os.makedirs(bench_dir, exist_ok=True)
        bench_doc = {"ranged_s": _AutoscaleSim.RESHARD_S}
        if repriced:
            bench_doc["phases"] = {"plan_s": 0.002, "fetch_s": 0.038}
        with open(os.path.join(bench_dir, "BENCH_reshard.json"), "w") as f:
            json.dump(bench_doc, f)
        cost_model = CostModel.from_bench(
            bench_dir,
            horizon_s=4.0,
            warm_restart_s=_AutoscaleSim.WARM_RESTART_S,
            cold_restart_s=_AutoscaleSim.COLD_RESTART_S,
            ckpt_s=0.02,
            preempt_block_s=_AutoscaleSim.PREEMPT_BLOCK_S,
        )
        assert abs(cost_model.reshard_s - (0.04 if repriced else 0.12)) < 1e-9, (
            f"from_bench priced reshard_s={cost_model.reshard_s} "
            f"(repriced={repriced})"
        )
        ctl = AutoscaleController(
            mode="act",
            cost_model=cost_model,
            remediation=engine,
            spare_capacity_fn=lambda: spares[0],
            shrink_fn=sim.shrink,
            expand_fn=sim.expand,
            target_world=world,
            rescind_grace_s=0.6,
            shrink_lead_s=0.1,
            # Sits between the two priced shrink gains (0.51 with the serial
            # ranged_s, 0.59 with plan+fetch): the repricing alone flips the
            # ripe-preemption decision.
            hysteresis_s=0.55,
            dwell_s=0.3,
            decision_cooldown_s=10.0,
            outcome_window_s=0.5,
        )
        sim.ctl = ctl
    policy = HealthVectorPolicy(
        patience=2, recovery=1,
        sinks=[ctl.note_health] if ctl is not None else [],
    )
    tpu_events.add_sink(flatten)
    try:
        sim.emit("launcher", "rendezvous_round", round=0, world_size=world,
                 active=list(range(world)))
        if controlled:
            sim.emit("launcher", "warm_spare_pool", size=1, parked=1, warm=1)
        # -- phase 0: healthy -------------------------------------------------
        sim.steps(10)
        # -- phase 1: straggler ----------------------------------------------
        scores_bad = {r: (0.3 if r == v_straggler else 1.0)
                      for r in range(world)}
        for _ in range(2):  # patience rounds: the straggler gates the job
            sim.steps(1, slow=3.0)
            policy.observe(_synthetic_report(scores_bad))
        if controlled:
            d = ctl.tick()
            assert d is not None and d.action == "swap", d
            assert d.victims == [v_straggler], (d.victims, v_straggler)
            sim.emit("telemetry", "degraded_set", degraded=[], newly=[],
                     recovered=[v_straggler], scores={})
        else:
            # No controller: the straggler gates the job until it dies, then
            # the round cold-restarts — today's reality.
            sim.steps(18, slow=3.0)
            sim.downtime(
                _AutoscaleSim.COLD_RESTART_S, "worker_failed",
                global_rank=v_straggler, exitcode=1,
                detail="straggler died",
            )
        sim.steps(10)
        # -- phase 2: preemption notice that RESCINDS ------------------------
        sim.emit("preemption", "preemption_sync_point", rank=v_rescind,
                 step=sim.it)
        if controlled:
            d = ctl.tick()  # fresh notice: bank progress, don't panic
            assert d is not None and d.action == "checkpoint", d
            sim.steps(5)
            sim.emit("preemption", "preemption_rescinded", rank=v_rescind,
                     step=sim.it, noticed_step=sim.it - 5)
            assert ctl.tick() is None  # notice gone: nothing to do
            sim.steps(5)
        else:
            # Today's path: the notice forces drain-and-stop; the rescind
            # arrives after the job already paid the restart.
            proactive_ckpt()
            sim.downtime(
                _AutoscaleSim.COLD_RESTART_S, "restart_requested",
                reason=f"preemption notice on rank {v_rescind}: drain and stop",
            )
            sim.emit("preemption", "preemption_rescinded", rank=v_rescind,
                     step=sim.it, noticed_step=sim.it)
            sim.steps(10)
        # -- phase 3: real preemption (deadline hits) ------------------------
        if controlled and repriced:
            ctl.note_preemption(
                f"r{v_preempt}", rank=v_preempt, deadline=time.time()
            )
            sim.emit("preemption", "preemption_sync_point", rank=v_preempt,
                     step=sim.it)
            d = ctl.tick()
            assert d is not None and d.action == "shrink", d
            sim.steps(15)  # training continues at 3/4 capacity
            spares[0] = 1  # the reclaimed capacity returns
            sim.emit("launcher", "warm_spare_pool", size=1, parked=1, warm=1)
            d = ctl.tick()
            assert d is not None and d.action == "expand", d
            sim.steps(10)
        elif controlled:
            # The serial-era price keeps shrink's predicted gain under the
            # hysteresis bar: the controller banks progress at most (or stays
            # silent under the per-victim cooldown) and the rank dies at the
            # deadline — the exact regression the phase repricing closes.
            ctl.note_preemption(
                f"r{v_preempt}", rank=v_preempt, deadline=time.time()
            )
            sim.emit("preemption", "preemption_sync_point", rank=v_preempt,
                     step=sim.it)
            d = ctl.tick()
            assert d is None or d.action == "checkpoint", d
            sim.steps(2)  # the grace window ticks away, nothing resizes
            sim.downtime(
                _AutoscaleSim.COLD_RESTART_S + _AutoscaleSim.PREEMPT_BLOCK_S,
                "worker_failed", global_rank=v_preempt, exitcode=137,
                detail="preempted at deadline; shrink underpriced by the "
                       "serial-era ranged_s constant",
            )
            sim.steps(25)
        else:
            sim.emit("preemption", "preemption_sync_point", rank=v_preempt,
                     step=sim.it)
            sim.steps(2)  # the grace window ticks away, nothing prepares
            sim.downtime(
                _AutoscaleSim.COLD_RESTART_S + _AutoscaleSim.PREEMPT_BLOCK_S,
                "worker_failed", global_rank=v_preempt, exitcode=137,
                detail="preempted at deadline; blocked for capacity",
            )
            sim.steps(25)
        # -- phase 4: the disk fault (identical in both arms) ----------------
        proactive_ckpt()  # ensures iteration 1 exists under this arm's root
        plan = chaos.ChaosPlan.parse(AUTOSCALE_DISK_SPEC.format(seed=seed))
        chaos.install_plan(plan)
        try:
            mgr = proactive_mgr[0]
            import numpy as _np

            mgr.save(
                2,
                PyTreeStateDict({"w": _np.arange(2048, dtype=_np.float32),
                                 "step": 2}),
                is_async=False,
            )
            hollow, tensors, meta = mgr.load()
            assert meta["iteration"] == 1, (
                f"disk-fault ladder resumed iteration {meta['iteration']}, "
                f"wanted the fallback to 1 (bitflipped 2)"
            )
        finally:
            chaos.clear_plan()
        sim.steps(5)
        if ctl is not None:
            ctl.finalize()
        schedule = (
            tuple(
                (d.decision_id, d.action, tuple(d.victims))
                for d in ctl.decisions
            )
            if ctl is not None else ()
        )
        return recs, schedule, tuple(plan.schedule())
    finally:
        tpu_events.remove_sink(flatten)
        if proactive_mgr[0] is not None:
            proactive_mgr[0].close()


def scenario_autoscale(seed: int, workdir: str):
    """The detect→decide→act acceptance: the controlled arm's measured
    goodput ratio must STRICTLY beat the no-controller baseline of the same
    seed, the controlled run's (decision, action, victim) schedule must
    reproduce across two runs, and every decision event must pair with an
    outcome event carrying both predicted and realized goodput deltas.

    A third arm reprices nothing BUT the cost model: same controller, same
    fault script, constants drawn from the same bench artifact minus its
    ``phases`` block (the pre-overlap ``ranged_s`` price). That arm must
    decline the ripe-preemption shrink, never expand, and land a strictly
    WORSE goodput ratio than the phase-priced arm — the decision-schedule
    diff is visible in the two arms' ``autoscale_decision`` audit events.
    Leaves ``controlled.jsonl`` / ``baseline.jsonl`` in ``workdir`` for the
    smoke leg's offline ``tpu-metrics-dump --goodput --baseline`` check."""
    from tpu_resiliency.utils.goodput import GoodputLedger, compare
    from tpu_resiliency.utils.metrics import aggregate

    os.makedirs(workdir, exist_ok=True)
    c1_recs, c1_sched, c1_disk = _autoscale_campaign(seed, workdir, True)
    c2_recs, c2_sched, c2_disk = _autoscale_campaign(seed, workdir, True)
    assert (c1_sched, c1_disk) == (c2_sched, c2_disk), (
        f"autoscale decision schedule not reproducible:\n{c1_sched}\n{c2_sched}"
    )
    assert [a for _, a, _ in c1_sched] == [
        "swap", "checkpoint", "shrink", "expand",
    ], c1_sched
    o_recs, o_sched, o_disk = _autoscale_campaign(
        seed, workdir, True, repriced=False
    )
    b_recs, _, b_disk = _autoscale_campaign(seed, workdir, False)
    assert b_disk == c1_disk, "disk fault schedule diverged between arms"
    assert o_disk == c1_disk, "disk fault schedule diverged (serial-priced)"

    # The repricing IS the decision diff: the serial-priced arm never
    # resizes — and the divergence is auditable from the decision events
    # alone, no internal state needed.
    old_actions = [a for _, a, _ in o_sched]
    assert old_actions[:2] == ["swap", "checkpoint"], o_sched
    assert "shrink" not in old_actions and "expand" not in old_actions, o_sched
    audit_new = [r["action"] for r in c1_recs
                 if r.get("kind") == "autoscale_decision"]
    audit_old = [r["action"] for r in o_recs
                 if r.get("kind") == "autoscale_decision"]
    assert "shrink" in audit_new and "expand" in audit_new, audit_new
    assert "shrink" not in audit_old and "expand" not in audit_old, audit_old

    # Every decision carries predicted AND realized goodput delta (the
    # outcome event pairs them; finalize settled any stragglers).
    decisions = [r for r in c1_recs if r.get("kind") == "autoscale_decision"]
    outcomes = {
        r.get("decision_id"): r
        for r in c1_recs if r.get("kind") == "autoscale_outcome"
    }
    assert len(decisions) == len(c1_sched), decisions
    for d in decisions:
        assert isinstance(d.get("predicted_delta_s"), (int, float)), d
        o = outcomes.get(d.get("decision_id"))
        assert o is not None, f"decision {d.get('decision_id')} never settled"
        assert isinstance(o.get("predicted_delta_s"), (int, float)), o
        assert isinstance(o.get("realized_delta_s"), (int, float)), o

    # The acceptance inequalities, via the same compare() helper the CLI
    # uses: phase-priced > serial-priced > no controller at all.
    controlled, old_priced, baseline = (
        GoodputLedger(), GoodputLedger(), GoodputLedger()
    )
    controlled.observe_many(c1_recs)
    old_priced.observe_many(o_recs)
    baseline.observe_many(b_recs)
    cmp_doc = compare(controlled, baseline)
    assert cmp_doc["ratio_delta"] > 0, (
        f"controller did NOT beat the no-controller baseline: {cmp_doc}"
    )
    cmp_old = compare(old_priced, baseline)
    assert cmp_old["ratio_delta"] > 0, (
        f"serial-priced controller did NOT beat the baseline: {cmp_old}"
    )
    cmp_reprice = compare(controlled, old_priced)
    assert cmp_reprice["ratio_delta"] > 0, (
        f"phase repricing did NOT beat the serial-era constants: {cmp_reprice}"
    )

    # Every arm climbed the identical disk-fault ladder.
    for name, arm in (("controlled", c1_recs), ("serial_priced", o_recs),
                      ("baseline", b_recs)):
        assert any(r.get("kind") == "ckpt_quarantined" for r in arm), (
            f"{name}: bitflipped container never quarantined"
        )
        assert any(r.get("kind") == "ckpt_fallback" for r in arm), (
            f"{name}: ladder never recorded the fallback"
        )

    # The metrics surface: the same aggregation metrics_dump runs.
    prom = aggregate(c1_recs).to_prometheus()
    for want in (
        "tpu_autoscale_decisions_total", 'action="swap"', 'action="shrink"',
        "tpu_autoscale_predicted_vs_realized", "tpu_preemption_rescinded_total",
    ):
        assert want in prom, f"{want} missing:\n{prom[:2000]}"

    for name, arm in (("controlled", c1_recs),
                      ("controlled_serial_priced", o_recs),
                      ("baseline", b_recs)):
        with open(os.path.join(workdir, f"{name}.jsonl"), "w") as f:
            for rec in arm:
                f.write(json.dumps(rec) + "\n")
    return (
        [list(s) for s in c1_sched],
        (seed % 4, (seed // 4) % 4, (seed // 16) % 4),
        [list(i) for i in c1_disk],
        (cmp_doc["goodput_ratio"][0], cmp_old["goodput_ratio"][0],
         cmp_doc["goodput_ratio"][1]),
    )


def _alerts_campaign(seed: int):
    """One synthetic run of the watchtower campaign: a fully seeded stream
    (synthetic timestamps — the watchtower runs on stream time, so the whole
    campaign is wall-clock-free) through a live-wired engine whose emitted
    alert events are appended back into the stream, exactly as a real run's
    telemetry tail sees its own ``alert_fired`` records. Returns
    ``(records, sequence, hang_ts)``."""
    import random

    from tpu_resiliency.telemetry.watchtower import Watchtower, default_rules

    rng = random.Random(seed)
    recs: list = []
    sequence: list = []
    tower = Watchtower(
        rules=default_rules(),
        emit=lambda kind, payload: sequence.append({"kind": kind, **payload}),
    )
    t = [1_000_000.0 + (seed % 997)]

    def emit(source, kind, **payload):
        rec = {"ts": t[0], "source": source, "kind": kind, "pid": 0,
               "rank": None, **payload}
        recs.append(rec)
        n = len(sequence)
        tower.observe(rec)
        # The engine's own transitions ride the stream too (a live run's
        # events tail feeds them back); stamped at their boundary ts they
        # never cross a boundary themselves — inert on replay, by design.
        for tr in sequence[n:]:
            recs.append({
                "ts": tr.get("resolve_ts") or tr.get("fire_ts") or t[0],
                "source": "watchtower", "pid": 0, "rank": None, **tr,
            })

    it = [0]

    def steps(n, step_s):
        for _ in range(n):
            t[0] += step_s * (1.0 + 0.1 * rng.random())
            it[0] += 1
            emit("inprocess", "iteration_start", iteration=it[0], pid=1000)

    # -- phase 0: healthy baseline (jittered so MAD is honest) --------------
    steps(20, 0.1)
    # -- phase 1: seeded straggler — the pre-hang early warning -------------
    steps(8, 3.0)
    fired_rules = [s["rule"] for s in sequence if s["kind"] == "alert_fired"]
    assert "step_anomaly" in fired_rules, (
        f"straggler ramp never fired step_anomaly: {sequence}"
    )
    # ... and only THEN does the monitor's verdict land: the whole point.
    t[0] += 1.0
    hang_ts = t[0]
    emit("monitor", "hang_detected", rank=seed % 4, detail="seeded straggler")
    steps(20, 0.1)  # replacement rank: step time recovers, alert resolves
    # -- phase 2: injected restart burns the goodput SLO fast window --------
    for _ in range(30):
        t[0] += 2.0
        emit("telemetry", "goodput_update", ratio=0.2)
    for _ in range(40):  # recovery refills the fast window, burn resolves
        t[0] += 2.0
        emit("telemetry", "goodput_update", ratio=1.0)
    steps(5, 0.1)  # trailing boundary crossings flush pending resolves
    return recs, sequence, hang_ts


def scenario_alerts(seed: int, workdir: str):
    """The watchtower acceptance: the seeded straggler's ``step_anomaly``
    alert fires STRICTLY BEFORE the monitor's hang verdict (the early-warning
    lead), the injected restart burns the goodput SLO fast window and
    resolves after recovery, two same-seed runs produce identical
    (rule, fire_ts, resolve) sequences, and an offline replay of the saved
    events JSONL reproduces the live sequence byte-identically. Leaves
    ``events.jsonl`` / ``sequence.jsonl`` in ``workdir`` for the smoke leg's
    ``tpu-alerts`` check."""
    from tpu_resiliency.telemetry.watchtower import replay
    from tpu_resiliency.utils.metrics import aggregate

    os.makedirs(workdir, exist_ok=True)
    recs, seq, hang_ts = _alerts_campaign(seed)
    recs2, seq2, hang_ts2 = _alerts_campaign(seed)
    assert (seq, hang_ts) == (seq2, hang_ts2), (
        f"alert sequence not reproducible:\n{seq}\n{seq2}"
    )

    # The early-warning inequality: fired before the verdict, strictly.
    anomaly_fire = next(
        s for s in seq
        if s["kind"] == "alert_fired" and s["rule"] == "step_anomaly"
    )
    assert anomaly_fire["fire_ts"] < hang_ts, (
        f"step_anomaly fired at {anomaly_fire['fire_ts']}, NOT before the "
        f"hang verdict at {hang_ts}"
    )
    anomaly_resolve = next(
        s for s in seq
        if s["kind"] == "alert_resolved" and s["rule"] == "step_anomaly"
    )
    assert anomaly_resolve["resolve_ts"] > hang_ts

    # The SLO burn fires on the injected restart and resolves on recovery.
    burn = [s for s in seq if s["rule"] == "goodput_burn"]
    assert [s["kind"] for s in burn] == ["alert_fired", "alert_resolved"], burn

    # Offline replay of the saved stream reproduces the live sequence
    # byte-identically (the recorded alert events in the file are inert).
    events_path = os.path.join(workdir, "events.jsonl")
    with open(events_path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    with open(events_path) as f:
        loaded = [json.loads(line) for line in f if line.strip()]
    _, replayed = replay(loaded)
    live_bytes = [json.dumps(s, sort_keys=True) for s in seq]
    replay_bytes = [json.dumps(s, sort_keys=True) for s in replayed]
    assert live_bytes == replay_bytes, (
        f"offline replay diverged from the live sequence:\n"
        f"{live_bytes}\n{replay_bytes}"
    )
    with open(os.path.join(workdir, "sequence.jsonl"), "w") as f:
        for line in live_bytes:
            f.write(line + "\n")

    # The metrics surface: alert events aggregate like any other stream.
    prom = aggregate(recs).to_prometheus()
    for want in (
        "tpu_alerts_total", 'rule="step_anomaly"', 'rule="goodput_burn"',
        'severity="page"', "tpu_alerts_active 0",
    ):
        assert want in prom, f"{want} missing:\n{prom[:2000]}"

    ordinals = [
        (s["kind"], s["rule"], i) for i, s in enumerate(seq)
    ]
    return ordinals, round(hang_ts - anomaly_fire["fire_ts"], 3)


# -- scenario: cold-start (checkpoints that outlive the job) -----------------

#: The cold-start campaign's fixed geometry: a 3-rank dp world whose global
#: "w" is reassembled by a 2-rank fresh world — rows divisible by both.
COLD_WORLD = 3
COLD_RESUME_RANKS = [0, 1]


def _cold_global():
    import numpy as np

    return np.arange(24 * 8, dtype=np.float32).reshape(24, 8) * 0.5


def _cold_job_child(base: str) -> int:
    """Hidden ``--_cold-job`` mode: the victim job of
    :func:`scenario_cold_start`. A 3-rank world saves two cold-archived
    keyframe iterations (layout-bearing, clique-replicated), spawns a worker
    subprocess so there is a real process TREE to kill, signals readiness,
    then "trains" forever — the parent SIGKILLs the whole group mid-step, so
    nothing here ever closes cleanly. Durability must come from what already
    landed in the cold tier."""
    from tpu_resiliency.checkpoint import reshard as ckpt_reshard
    from tpu_resiliency.checkpoint.coldtier import ColdTier, FilesystemStore
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

    G = _cold_global()
    world = COLD_WORLD
    layout = ckpt_reshard.TreeLayout(
        [("dp", world)], list(range(world)),
        [ckpt_reshard.LeafSpec(G.shape, "float32", ("dp",))],
    )
    srv = KVServer(host="127.0.0.1", port=0)

    def mk():
        return CoordStore("127.0.0.1", srv.port, timeout=30.0)

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=60.0)
        ex = PeerExchange(mk(), rank, timeout=30.0)
        ex.start()
        strat = CliqueReplicationStrategy(
            comm, ex, replication_jump=1, replication_factor=2
        )
        cold = ColdTier(
            FilesystemStore(os.path.join(base, "cold")), session=0, rank=rank
        )
        mgr = LocalCheckpointManager(
            os.path.join(base, "root"), rank=rank, comm=comm,
            replication=strat, cold=cold, keep=2,
        )
        for it in (1, 2):
            tree = {
                "w": ckpt_reshard.slice_local([G], layout, rank)[0]
                + float(it),
                "step": it,
            }
            mgr.save(it, PyTreeStateDict(tree), is_async=False, layout=layout)
        assert cold.flush(timeout=60.0), "cold uploads did not drain"
        # Deliberately no mgr.close()/ex.close(): this job dies by SIGKILL.

    with cf.ThreadPoolExecutor(max_workers=world) as pool:
        for f in [pool.submit(body, r) for r in range(world)]:
            f.result(timeout=180)
    worker = subprocess.Popen(
        [sys.executable, "-c", "import time\nwhile True: time.sleep(1)"]
    )
    tmp = os.path.join(base, "ready.tmp")
    with open(tmp, "w") as f:
        f.write(str(worker.pid))
    os.replace(tmp, os.path.join(base, "ready"))
    while True:  # "training" — the parent kills the process group here
        time.sleep(0.05)


def _proc_gone(pid: int) -> bool:
    """Dead-or-zombie (a zombie no longer executes anything; whether it is
    reaped depends on the container's init)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] == "Z"
    except OSError:
        return True


def scenario_cold_start(seed: int, workdir: str):
    """Checkpoints that outlive the job: SIGKILL an entire job's process tree
    mid-training, then resume a FRESH world with an EMPTY workdir from the
    cold tier alone, on a DIFFERENT world size (3 -> 2), byte-identical.

    The seeded bitflip variant corrupts one byte of the newest archived
    iteration (victim owner and payload offset both derived from the seed):
    the fresh world must refuse the corrupt bytes fail-closed and agree to
    climb to the next-older covered iteration. Returns the full outcome
    tuple (kill signal, resumed iterations, state digests, fault identity) —
    reproducible run-to-run per seed."""
    import hashlib
    import shutil
    import signal

    import numpy as np

    from tpu_resiliency.checkpoint import reshard as ckpt_reshard
    from tpu_resiliency.checkpoint.coldtier import (
        ColdTier,
        FilesystemStore,
        artifact_key,
    )
    from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
    from tpu_resiliency.utils import events as tpu_events

    base = workdir
    shutil.rmtree(base, ignore_errors=True)
    os.makedirs(base)
    logpath = os.path.join(base, "job.log")
    with open(logpath, "wb") as logf:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--_cold-job", base],
            stdout=logf, stderr=subprocess.STDOUT, start_new_session=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    ready = os.path.join(base, "ready")
    deadline = time.monotonic() + 180.0
    try:
        while not os.path.exists(ready):
            if proc.poll() is not None:
                with open(logpath, errors="replace") as f:
                    tail = f.read()[-2000:]
                raise AssertionError(
                    f"cold-start job died before readiness (rc="
                    f"{proc.returncode}):\n{tail}"
                )
            if time.monotonic() > deadline:
                raise AssertionError("cold-start job never became ready")
            time.sleep(0.05)
        with open(ready) as f:
            worker_pid = int(f.read().strip())
        # The whole tree, not just the leader: the job runs in its own
        # session/process group, so one killpg takes worker and leader alike.
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == -signal.SIGKILL, f"job exited {rc}, wanted SIGKILL"
    kill_deadline = time.monotonic() + 10.0
    while not _proc_gone(worker_pid):
        assert time.monotonic() < kill_deadline, (
            f"worker {worker_pid} survived the process-tree kill"
        )
        time.sleep(0.05)

    G = _cold_global()
    ranks = list(COLD_RESUME_RANKS)
    tgt = ckpt_reshard.TreeLayout(
        [("dp", len(ranks))], ranks,
        [ckpt_reshard.LeafSpec(G.shape, "float32", ("dp",))],
    )

    def restore(tag, gen):
        """A fresh launcher's view: empty workdir, only the cold tier and a
        new rendezvous store."""
        srv = KVServer(host="127.0.0.1", port=0)
        stores: list = []
        seen: list = []
        tpu_events.add_sink(seen.append)
        fresh = os.path.join(base, f"fresh_{tag}")

        def mk():
            s = CoordStore("127.0.0.1", srv.port, timeout=30.0)
            stores.append(s)
            return s

        def body(rank):
            comm = StoreComm(mk(), rank, ranks, timeout=60.0, generation=gen)
            ex = PeerExchange(mk(), rank, timeout=30.0)
            ex.start()
            try:
                mgr = LocalCheckpointManager(
                    fresh, rank=rank, comm=comm,
                    cold=ColdTier(
                        FilesystemStore(os.path.join(base, "cold")),
                        session=0, rank=rank,
                    ),
                )
                hollow, tensors, meta = mgr.load_resharded()
                mgr.close()
                return meta["iteration"], [
                    np.asarray(t).copy() for t in tensors
                ]
            finally:
                ex.close()

        try:
            with cf.ThreadPoolExecutor(max_workers=len(ranks)) as pool:
                out = [
                    f.result(timeout=180)
                    for f in [pool.submit(body, r) for r in ranks]
                ]
        finally:
            tpu_events.remove_sink(seen.append)
            for s in stores:
                s.close()
            srv.close()
        return out, seen

    def digest(out):
        h = hashlib.sha256()
        for _, tensors in out:
            for t in tensors:
                h.update(t.tobytes())
        return h.hexdigest()

    # Leg 1: clean restore-anywhere — fresh world 2 resumes the killed
    # world-3 job's newest keyframe, byte-identical, straight from cold.
    out_a, seen_a = restore("clean", gen=1)
    for rank, (it, tensors) in zip(ranks, out_a):
        assert it == 2, f"rank {rank} resumed iteration {it}, wanted 2"
        want = ckpt_reshard.slice_local([G], tgt, rank)[0] + 2.0
        assert np.array_equal(tensors[0], want), (
            f"rank {rank}: cold restore not byte-identical"
        )
    fetches = [e for e in seen_a if e.kind == "coldtier_fetch"]
    assert fetches and all(
        e.payload["outcome"] == "ok" for e in fetches
    ), f"clean leg cold fetches: {[e.payload for e in fetches]}"

    # Leg 2: the seeded cold-tier bitflip — victim owner and offset inside
    # the sharded "w" payload both derive from the seed; the fresh world must
    # climb to the next-older covered iteration, never restoring flipped
    # bytes.
    colddir = os.path.join(base, "cold")
    victim = seed % COLD_WORLD
    probe = ColdTier(FilesystemStore(colddir))
    doc = probe.manifest(2, victim)
    assert doc is not None, f"no cold manifest for iter 2 owner {victim}"
    off = doc["prefix_len"]
    for leaf in doc["leaves"]:
        if leaf["nbytes"] == max(l["nbytes"] for l in doc["leaves"]):
            break
        off += leaf["nbytes"]
    flip_at = off + seed % leaf["nbytes"]
    apath = os.path.join(colddir, artifact_key(0, 2, victim))
    with open(apath, "r+b") as f:
        f.seek(flip_at)
        b = f.read(1)
        f.seek(flip_at)
        f.write(bytes([b[0] ^ 0x01]))

    out_b, seen_b = restore("bitflip", gen=2)
    for rank, (it, tensors) in zip(ranks, out_b):
        assert it == 1, (
            f"rank {rank} resumed iteration {it} — must climb below the "
            f"corrupt iter 2"
        )
        want = ckpt_reshard.slice_local([G], tgt, rank)[0] + 1.0
        assert np.array_equal(tensors[0], want), (
            f"rank {rank}: climbed restore not byte-identical"
        )
    corrupt = [
        e for e in seen_b
        if e.kind == "coldtier_fetch" and e.payload["outcome"] == "corrupt"
    ]
    assert corrupt, "bitflip leg never surfaced a corrupt cold fetch"
    # Persist both restore legs' event streams for downstream smoke legs
    # (metrics_dump must aggregate tpu_coldtier_* from this file).
    with open(os.path.join(base, "events.jsonl"), "w") as f:
        for e in seen_a + seen_b:
            f.write(json.dumps(e.to_record(), default=str) + "\n")
    return (
        rc,
        [it for it, _ in out_a], digest(out_a),
        victim, flip_at,
        [it for it, _ in out_b], digest(out_b),
    )


# -- driver ------------------------------------------------------------------


def run_seed(seed: int, workdir: str, with_launcher: bool = True,
             randomized: bool = False) -> dict:
    """One seeded pass over every scenario. ``randomized`` swaps the fixed
    fault templates for :func:`chaos.random_spec`-generated plans (still fully
    determined by ``seed`` — the soak stays replayable)."""
    out: dict = {"seed": seed, "randomized": randomized}
    t0 = time.perf_counter()
    store_spec = (
        chaos.random_spec(seed, channels=("store",), ops=("send", "recv", "connect"))
        if randomized else None
    )
    # p2p random plans stay off the recv op: recv-side payload truncation is
    # silent loss (degrade path), which this scenario's no-degrade assertion
    # intentionally excludes — see REPL_SPEC's comment.
    repl_spec = (
        chaos.random_spec(seed, channels=("p2p",), ops=("send", "connect"))
        if randomized else None
    )
    s1 = scenario_store(seed, spec=store_spec)
    s2 = scenario_store(seed, spec=store_spec)
    assert s1 == s2, f"store schedule not reproducible:\n{s1}\n{s2}"
    out["store_injections"] = [list(i) for i in s1]
    # Sharded clique + tree collectives under the same store-channel faults,
    # twice per seed: schedule AND gathered bytes must both reproduce.
    scale_spec = (
        chaos.random_spec(seed, channels=("store",), ops=("send", "recv", "connect"))
        if randomized else None
    )
    ss1 = scenario_store_scale(seed, spec=scale_spec)
    ss2 = scenario_store_scale(seed, spec=scale_spec)
    assert ss1[0] == ss2[0], (
        f"store-scale schedule not reproducible:\n{ss1[0]}\n{ss2[0]}"
    )
    assert ss1[1] == ss2[1], "store-scale gathered bytes not reproducible"
    out["store_scale_injections"] = [list(i) for i in ss1[0]]
    out["store_scale_digest"] = ss1[1]
    # Replicated-clique failover campaign (SIGKILL a shard mid-barrier-storm
    # and mid-rendezvous), twice per seed: the victims, the deduped counter,
    # the final keyspace digest and the rendezvous outcome must all reproduce.
    fo1 = scenario_store_failover(seed)
    fo2 = scenario_store_failover(seed)
    assert fo1 == fo2, f"store-failover outcome not reproducible:\n{fo1}\n{fo2}"
    out["store_failover_kill_round"] = fo1[0]
    out["store_failover_victims"] = list(fo1[1])
    out["store_failover_counter"] = fo1[2]
    out["store_failover_digest"] = fo1[3]
    r1 = scenario_replication(seed, spec=repl_spec)
    r2 = scenario_replication(seed, spec=repl_spec)
    assert r1 == r2, f"replication schedule not reproducible:\n{r1}\n{r2}"
    out["replication_injections"] = [list(i) for i in r1]
    # Disk-fault ladder, both rungs, each run twice per seed: the injection
    # schedule (per-file write indices) must reproduce exactly.
    d1 = scenario_disk(seed)
    d2 = scenario_disk(seed)
    assert d1 == d2, f"disk schedule not reproducible:\n{d1}\n{d2}"
    f1 = scenario_disk(seed, fallback=True)
    f2 = scenario_disk(seed, fallback=True)
    assert f1 == f2, f"disk-fallback schedule not reproducible:\n{f1}\n{f2}"
    out["disk_injections"] = [list(i) for i in d1]
    out["disk_fallback_injections"] = [list(i) for i in f1]
    # Byte-economy campaign (erasure holder death + parity bitflip + delta
    # chain break), twice per seed: the whole composite tuple — injection
    # schedule AND every seeded fault identity — must reproduce.
    c1 = scenario_coding(seed)
    c2 = scenario_coding(seed)
    assert c1 == c2, f"coding schedule not reproducible:\n{c1}\n{c2}"
    out["coding_injections"] = [list(i) for i in c1[0]]
    out["coding_victim"] = c1[1]
    out["coding_faults"] = list(c1[2:6])
    # Elastic shrink → resharded resume → re-expand, twice per seed: the
    # (injection schedule, victim, per-rank byte splits) must reproduce.
    e1 = scenario_elastic(seed)
    e2 = scenario_elastic(seed)
    assert e1 == e2, f"elastic schedule not reproducible:\n{e1}\n{e2}"
    out["elastic_victim"] = e1[1]
    out["elastic_splits"] = [list(s) for s in e1[2]]
    out["elastic_injections"] = [list(i) for i in e1[0]]
    # Cold-start: SIGKILL the whole job tree mid-training, fresh empty-workdir
    # world resumes from the cold tier on a different world size — twice per
    # seed, and the (kill, resumed iterations, digests, fault identity) tuple
    # must reproduce exactly, bitflip-climb variant included.
    cold_dir = os.path.join(workdir, f"cold_{seed}")
    cs1 = scenario_cold_start(seed, cold_dir)
    cs2 = scenario_cold_start(seed, cold_dir)
    assert cs1 == cs2, f"cold-start outcome not reproducible:\n{cs1}\n{cs2}"
    out["cold_start_resumed"] = {"clean": cs1[1], "bitflip": cs1[5]}
    out["cold_start_digests"] = {"clean": cs1[2], "bitflip": cs1[6]}
    out["cold_start_fault"] = {"victim_owner": cs1[3], "flip_at": cs1[4]}
    out["cold_start_workdir"] = cold_dir
    # Mixed multi-fault campaign (straggler + network + disk), twice per seed:
    # the combined schedule must reproduce exactly like the single-channel ones.
    mixed_dir = os.path.join(workdir, f"mixed_{seed}")
    m1 = scenario_mixed(seed, mixed_dir)
    m2 = scenario_mixed(seed, mixed_dir)
    assert m1 == m2, f"mixed schedule not reproducible:\n{m1}\n{m2}"
    out["mixed_injections"] = [list(i) for i in m1]
    out["mixed_workdir"] = mixed_dir
    # Hang forensics chain (seeded stall -> detection -> capture -> ladder ->
    # restart), twice per seed: the forensics schedule must reproduce exactly.
    hang_dir = os.path.join(workdir, f"hang_{seed}")
    h1 = scenario_hang(seed, hang_dir)
    h2 = scenario_hang(seed, hang_dir)
    assert h1 == h2, f"hang schedule not reproducible:\n{h1}\n{h2}"
    out["hang_schedule"] = [h1[0], list(h1[1]), h1[2]]
    out["hang_workdir"] = hang_dir
    # Autoscale campaign: scenario_autoscale internally runs the phase-priced
    # controlled arm twice (identical decision schedules) plus the
    # serial-priced arm and the baseline, asserting the strict goodput
    # ordering phase-priced > serial-priced > no controller.
    autoscale_dir = os.path.join(workdir, f"autoscale_{seed}")
    a_sched, a_victims, a_disk, a_ratios = scenario_autoscale(seed, autoscale_dir)
    out["autoscale_schedule"] = a_sched
    out["autoscale_victims"] = list(a_victims)
    out["autoscale_goodput"] = {"controlled": a_ratios[0],
                                "serial_priced": a_ratios[1],
                                "baseline": a_ratios[2]}
    out["autoscale_workdir"] = autoscale_dir
    # Watchtower campaign: scenario_alerts internally runs the synthetic
    # stream twice (identical fire/resolve sequences) and byte-compares the
    # offline replay of its saved events JSONL against the live sequence.
    alerts_dir = os.path.join(workdir, f"alerts_{seed}")
    al_seq, al_lead = scenario_alerts(seed, alerts_dir)
    out["alerts_sequence"] = [list(s) for s in al_seq]
    out["alerts_early_warning_lead_s"] = al_lead
    out["alerts_workdir"] = alerts_dir
    if with_launcher:
        counts = scenario_launcher(seed, os.path.join(workdir, f"launcher_{seed}"))
        out["launcher_injections"] = {f"{c}.{k}": n for (c, k), n in counts.items()}
    out["elapsed_s"] = round(time.perf_counter() - t0, 2)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="fast fixed-seed pass (store + replication + launcher)")
    ap.add_argument("--seed", type=int, default=None, help="single seeded pass")
    ap.add_argument("--soak-runs", type=int, default=0,
                    help="randomized soak: N random seeds, launcher every 4th")
    ap.add_argument("--out", default=None, help="write a JSON report here")
    ap.add_argument(
        "--workdir", default=None,
        help="run under this directory instead of a self-deleting tempdir "
        "(keeps the mixed scenario's events/incident artifacts for "
        "downstream smoke legs)")
    ap.add_argument("--_cold-job", dest="cold_job", metavar="DIR",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.cold_job:
        return _cold_job_child(args.cold_job)

    results = []
    import contextlib

    ctx = (
        contextlib.nullcontext(args.workdir) if args.workdir
        else tempfile.TemporaryDirectory(prefix="chaos_soak.")
    )
    with ctx as workdir:
        os.makedirs(workdir, exist_ok=True)
        if args.smoke or args.seed is not None:
            seed = 1234 if args.seed is None else args.seed
            res = run_seed(seed, workdir, with_launcher=True)
            results.append(res)
            print(f"seed {seed}: store={len(res['store_injections'])} "
                  f"repl={len(res['replication_injections'])} "
                  f"mixed={len(res['mixed_injections'])} "
                  f"autoscale={res.get('autoscale_goodput')} "
                  f"alerts_lead={res.get('alerts_early_warning_lead_s')}s "
                  f"launcher={res.get('launcher_injections')} "
                  f"({res['elapsed_s']}s)")
        base = int.from_bytes(os.urandom(4), "big")
        for i in range(args.soak_runs):
            seed = base + i
            res = run_seed(seed, workdir, with_launcher=(i % 4 == 0),
                           randomized=True)
            results.append(res)
            print(f"soak[{i}] seed {seed}: OK ({res['elapsed_s']}s)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"runs": results}, f, indent=2)
            f.write("\n")
    print(f"chaos_soak: PASS ({len(results)} seeded run(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
