"""BASELINE configs 1-3 replay harnesses (config 4 lives in bench.py).

The reference publishes no numbers (BASELINE.md), so these harnesses *measure* the
TPU-native path on replayed synthetic telemetry at the three scales BASELINE.json
names, against the same detection semantics the reference implements:

- **Config 1** — 64-rank single-process section-timing report (the reference
  ``examples/straggler`` semantics: per-section relative scores = min-of-medians /
  local-median, total-time weighting, 0.75 threshold). Scored by the real device
  pipeline (``ReportGenerator.generate_summary_report``).
- **Config 2** — 256-rank heartbeat replay with one injected hang, driven through
  the REAL monitor decision code (``RankMonitorServer._hb_timeout_elapsed``,
  reference ``rank_monitor_client.py:221-237`` / ``rank_monitor_server.py:349``)
  on a virtual clock: measures detection latency and F1.
- **Config 3** — 1024-rank kernel-style timing stream with 5% slow nodes, scored
  by the fused window pipeline (``scoring.score_round_jit``): report latency + F1.

Usage::

    python scripts/bench_configs.py [--out-dir DIR] [--iters N] [--configs 1,2,3]

Prints one JSON line per config and writes ``BENCH_config{N}.json`` to the out dir.
Run on CPU or TPU; CI runs it via ``tests/test_bench_configs.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def f1(pred: set, truth: set) -> float:
    tp = len(pred & truth)
    fp = len(pred - truth)
    fn = len(truth - pred)
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


# ---------------------------------------------------------------------------
# Config 1: 64-rank section-timing report parity
# ---------------------------------------------------------------------------

def config1(iters: int) -> dict:
    import jax.numpy as jnp

    from tpu_resiliency.telemetry.reporting import ReportGenerator

    ranks, sections = 64, 3
    names = ("sec/fwd", "sec/bwd", "sec/opt")
    slow = {17}
    rng = np.random.default_rng(1)
    base = rng.uniform(0.010, 0.030, size=(1, sections))
    medians = np.tile(base, (ranks, 1)) * (
        1.0 + 0.02 * rng.standard_normal((ranks, sections))
    )
    for r in slow:
        medians[r] *= 2.0
    weights = medians * 100.0  # total time over ~100 samples
    counts = np.full((ranks, sections), 100, np.int32)

    gen = ReportGenerator(world_size=ranks, max_signals=sections)
    m, w, c = jnp.asarray(medians), jnp.asarray(weights), jnp.asarray(counts)
    report = gen.generate_summary_report(m, w, c, names)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        report = gen.generate_summary_report(m, w, c, names)
    report_ms = (time.perf_counter() - t0) / iters * 1e3

    stragglers = report.identify_stragglers(perf_threshold=0.75)
    flagged = {s.rank for s in stragglers.by_perf}
    # Reference-semantics parity checks (examples/straggler): healthy ranks score
    # ~1.0, the slow rank scores ~min/median = ~0.5 and is flagged.
    healthy = [v for r, v in report.perf_scores.items() if r not in slow]
    parity = (
        min(healthy) > 0.9
        and max(healthy) <= 1.0 + 1e-6
        and report.perf_scores[17] < 0.6
    )
    return {
        "config": 1,
        "ranks": ranks,
        "report_ms": round(report_ms, 4),
        "f1": round(f1(flagged, slow), 4),
        "flagged": sorted(flagged),
        "parity_semantics_ok": bool(parity),
    }


# ---------------------------------------------------------------------------
# Config 2: 256-rank heartbeat replay, one injected hang
# ---------------------------------------------------------------------------

def config2(_: int) -> dict:
    from tpu_resiliency.watchdog.config import FaultToleranceConfig
    from tpu_resiliency.watchdog.data import RankInfo
    from tpu_resiliency.watchdog.monitor_server import RankMonitorServer, _RankSession

    ranks = 256
    hang_rank = 101
    hb_interval = 1.0
    hb_timeout = 3.0
    check_interval = 0.5
    hang_at = 30.0
    horizon = 60.0

    cfg = FaultToleranceConfig(
        initial_rank_heartbeat_timeout=10.0,
        rank_heartbeat_timeout=hb_timeout,
        workload_check_interval=check_interval,
    )
    servers = []
    for r in range(ranks):
        srv = RankMonitorServer(cfg, socket_path=f"/nonexistent/replay_{r}.sock")
        srv.session = _RankSession(
            info=RankInfo(global_rank=r, local_rank=r % 8, host=f"host{r // 8}", pid=0),
            connected_at=0.0,
        )
        servers.append(srv)

    detected: dict[int, float] = {}
    scan_times = []
    now = 0.0
    while now < horizon:
        now = round(now + check_interval, 6)
        # Replay heartbeats that arrived since the last tick (virtual clock).
        for r, srv in enumerate(servers):
            last_beat = None
            t = hb_interval
            while t <= now:
                if not (r == hang_rank and t >= hang_at):
                    last_beat = t
                t += hb_interval
            srv.session.last_hb = last_beat
        # The real decision code, timed: one full 256-rank scan per tick.
        t0 = time.perf_counter()
        for r, srv in enumerate(servers):
            if r in detected:
                continue
            reason = srv._hb_timeout_elapsed(now)
            if reason is not None:
                detected[r] = now
        scan_times.append(time.perf_counter() - t0)

    truth = {hang_rank}
    pred = set(detected)
    # Latency from the hang (last heartbeat the rank would have sent) to the tick
    # that flagged it. Expected: hb_timeout .. hb_timeout + hb_interval + tick.
    last_hb_sent = hang_at - hb_interval
    # None (JSON null), not inf: json.dumps would emit the non-standard Infinity.
    latency = (
        round(detected[hang_rank] - last_hb_sent, 3) if hang_rank in detected else None
    )
    return {
        "config": 2,
        "ranks": ranks,
        "hang_rank": hang_rank,
        "detection_latency_s": latency,
        "latency_budget_s": hb_timeout + hb_interval + check_interval,
        "f1": round(f1(pred, truth), 4),
        "scan_us_per_tick": round(float(np.mean(scan_times)) * 1e6, 2),
    }


# ---------------------------------------------------------------------------
# Config 3: 1024-rank kernel-timing stream, 5% slow nodes
# ---------------------------------------------------------------------------

def config3(iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu_resiliency.telemetry import scoring

    ranks, signals, window = 1024, 16, 32
    rng = np.random.default_rng(3)
    base = rng.uniform(0.8, 1.2, size=(1, signals, 1)).astype(np.float32)
    data = base * (1.0 + 0.05 * rng.standard_normal((ranks, signals, window)).astype(np.float32))
    n_slow = ranks // 20  # 5%
    slow = set(rng.choice(ranks, size=n_slow, replace=False).tolist())
    for r in slow:
        data[r] *= 1.6
    counts = np.full((ranks, signals), window, np.int32)

    d, c = jnp.asarray(data), jnp.asarray(counts)
    ewma = jnp.ones((ranks,))
    hist = jnp.full((ranks, signals), jnp.inf)
    out = scoring.score_round_jit(d, c, ewma, hist)  # warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = scoring.score_round_jit(d, c, out.ewma, hist)
    jax.block_until_ready(out)
    report_ms = (time.perf_counter() - t0) / iters * 1e3

    pred = set(np.nonzero(np.asarray(out.straggler))[0].tolist())
    return {
        "config": 3,
        "ranks": ranks,
        "slow_fraction": 0.05,
        "report_ms": round(report_ms, 4),
        "f1": round(f1(pred, slow), 4),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default=REPO_ROOT)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--configs", default="1,2,3")
    args = ap.parse_args()

    from tpu_resiliency.platform.device import apply_platform_env

    apply_platform_env()

    os.makedirs(args.out_dir, exist_ok=True)
    runners = {1: config1, 2: config2, 3: config3}
    ok = True
    import jax

    combined_path = os.path.join(args.out_dir, "BENCH_configs.json")
    combined = {}
    if os.path.exists(combined_path):
        # A partial --configs rerun refreshes only its own entries; the other
        # configs' previously measured results stay in the artifact.
        try:
            with open(combined_path) as f:
                combined = json.load(f)
        except (OSError, ValueError):
            combined = {}
    combined["backend"] = jax.default_backend()
    combined["note"] = (
        "configs 1-3 are host-semantic detection benchmarks (section "
        "report, heartbeat replay, timing-stream scoring); latency figures "
        "are host-side, F1 is backend-independent"
    )
    for n in (int(x) for x in args.configs.split(",")):
        result = runners[n](args.iters)
        line = json.dumps(result)
        print(line)
        with open(os.path.join(args.out_dir, f"BENCH_config{n}.json"), "w") as f:
            f.write(line + "\n")
        combined[f"config{n}"] = result
        if result["f1"] < 1.0:
            ok = False
    with open(combined_path, "w") as f:
        json.dump(combined, f, indent=1)
        f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
