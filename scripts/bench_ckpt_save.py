"""Benchmark the pipelined snapshot engine: foreground stall vs the sync-D2H path.

Drives the exact save paths a training loop uses, on a loopback clique of
``--world`` ranks (threads against one KVServer, the repo's standard multi-rank
harness), at each ``--mb`` tree size:

- **sync**: ``LocalCheckpointManager.save(pipelined=False)`` — the legacy
  engine: blocking batched ``jax.device_get``, whole-tree serialize, the full
  replication fan-out, all inside the caller-visible window; only file writes
  are async.
- **pipelined**: the snapshot engine — the caller-visible window is enqueue +
  skeleton pickle; D2H resolution, peer sends, and the shard write stream leaf
  by leaf in the background out of the pooled staging buffers.

Reported per size: **foreground-blocked ms** (what the train loop feels — the
time ``save()`` holds the caller) and **end-to-end ms** (save + blocking
finalize with coverage agreement), max-across-ranks per round, median across
rounds; plus the staging-pool stats proving the steady-state save allocated
nothing. A single-rank ``AsyncCheckpointer`` comparison and a steady-state
tracemalloc probe (peak transient alloc during a warm pipelined save) complete
the picture.

    python scripts/bench_ckpt_save.py [--mb 256 1024] [--world 3] [--rounds 3] \
        [--out BENCH_ckpt_save.json]
    python scripts/bench_ckpt_save.py --smoke   # tiny run + assert spans/metrics

The committed ``BENCH_ckpt_save.json`` comes from the default invocation; the
slow-marked regression test runs ``--mb 48 --world 2`` and enforces
``fg_ratio <= 0.25``.
"""

import argparse
import concurrent.futures as cf
import json
import os
import platform
import shutil
import statistics
import sys
import tempfile
import time
import tracemalloc

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_resiliency.checkpoint.async_ckpt import AsyncCheckpointer  # noqa: E402
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm  # noqa: E402
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager  # noqa: E402
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy  # noqa: E402
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict  # noqa: E402
from tpu_resiliency.platform.store import CoordStore, KVServer  # noqa: E402

LEAF_MB = 16


def make_tree(mb: int, seed: float):
    """A checkpoint-shaped tree: 16 MB float32 leaves plus scalar state."""
    n = max(1, mb // LEAF_MB)
    leaf = (mb * (1 << 20)) // (4 * n)
    tree = {
        "params": {f"w{i}": jnp.full((leaf,), seed + i, jnp.float32) for i in range(n)},
        "step": int(seed),
    }
    jax.block_until_ready(tree)
    return tree


def _touch_tree(tree, it):
    """A steady-state step's worth of mutation: one small slice of the first
    parameter leaf moves, everything else is byte-identical — the shape the
    delta chunk-diff exploits."""
    import jax.numpy as jnp

    params = dict(tree["params"])
    first = sorted(params)[0]
    leaf = params[first]
    params[first] = jnp.concatenate(
        [jnp.full((64,), float(it), leaf.dtype), leaf[64:]]
    )
    return {"params": params, "step": it}


def bench_clique(
    world: int, mb: int, rounds: int, pipelined: bool, root: str,
    delta_interval: int = 0, mutate: bool = False, cold_dir: str = None,
):
    """Per-round (foreground_s, e2e_s) as max across ranks; returns medians.

    ``delta_interval`` > 1 turns on chunk-diff replication between keyframes
    (the steady-state byte-economy leg); ``mutate`` applies a small per-round
    parameter update so consecutive saves differ realistically.
    ``cold_dir`` attaches a durable cold tier (``checkpoint/coldtier.py``)
    to every rank — the spiller's claim is that the foreground numbers do
    not move, since uploads ride the background worker off save-finalize."""
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=300.0)
        stores.append(s)
        return s

    staging_stats = {}

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=300.0)
        ex = PeerExchange(mk(), rank, timeout=300.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            cold = None
            if cold_dir is not None:
                from tpu_resiliency.checkpoint.coldtier import (
                    ColdTier,
                    FilesystemStore,
                )

                cold = ColdTier(
                    FilesystemStore(cold_dir), session=0, rank=rank
                )
            mgr = LocalCheckpointManager(
                root, rank=rank, comm=comm, replication=strat,
                pipelined=pipelined, delta_interval=delta_interval,
                cold=cold if cold is not None else False,
            )
            tree = make_tree(mb, float(rank))
            out = []
            for it in range(1, rounds + 1):
                sd = PyTreeStateDict(
                    _touch_tree(tree, it) if mutate else dict(tree, step=it)
                )
                comm.barrier("round-in")
                t0 = time.perf_counter()
                mgr.save(it, sd)
                fg = time.perf_counter() - t0
                mgr.maybe_finalize(blocking=True)
                e2e = time.perf_counter() - t0
                comm.barrier("round-out")
                out.append((fg, e2e))
            if rank == 0:
                staging_stats.update(mgr.staging.stats())
            if cold is not None:
                # Drain OUTSIDE the timed loop: upload completion is the
                # background worker's business, never the train loop's.
                assert cold.flush(timeout=600.0), "cold uploads did not drain"
                cold.close()
            mgr.close()
            return out
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=3600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        for s in stores:
            s.close()
        srv.close()
    fg_rounds = [max(t[0] for t in rnd) for rnd in zip(*per_rank)]
    e2e_rounds = [max(t[1] for t in rnd) for rnd in zip(*per_rank)]
    return (
        statistics.median(fg_rounds),
        statistics.median(e2e_rounds),
        staging_stats,
    )


def bench_delta_leg(world: int, mb: int, rounds: int, root: str) -> dict:
    """Steady-state byte economy: the same clique save loop with
    ``delta_interval`` on and a realistic small per-round mutation. Reports
    the replication bytes a delta round shipped vs the full container a
    mirror round moves (from the save path's own ``ckpt_delta`` events) plus
    the e2e save time."""
    from tpu_resiliency.utils import events as events_mod

    seen = []
    events_mod.add_sink(seen.append)
    try:
        fg, e2e, _ = bench_clique(
            world, mb, rounds, pipelined=True, root=root,
            delta_interval=rounds + 2, mutate=True,
        )
    finally:
        events_mod.remove_sink(seen.append)
    deltas = [e.payload for e in seen if e.kind == "ckpt_delta"]
    applied = [e.payload for e in seen if e.kind == "ckpt_delta_applied"]
    frame = statistics.median(d["frame_bytes"] for d in deltas)
    full = statistics.median(d["full_bytes"] for d in deltas)
    return {
        "rounds_delta": len(deltas),
        "applied_ok": sum(1 for a in applied if a["outcome"] == "ok"),
        "fg_ms": round(fg * 1e3, 3),
        "e2e_ms": round(e2e * 1e3, 1),
        "frame_bytes": int(frame),
        "full_bytes": int(full),
        #: the ≥5x-fewer-bytes acceptance reads from here
        "bytes_ratio": round(frame / full, 4),
        "bytes_win": round(full / frame, 1),
    }


def bench_cold_leg(world: int, mb: int, rounds: int, root: str) -> dict:
    """The cold-tier non-interference gate: the same pipelined clique loop
    with and without a durable cold tier attached. Reports both foreground
    medians plus what the spiller archived (from ``coldtier_spilled``
    events) — the acceptance is that ``fg_ms`` is unchanged within noise
    while every keyframe still lands in the object store."""
    from tpu_resiliency.utils import events as events_mod

    base_fg, base_e2e, _ = bench_clique(
        world, mb, rounds, pipelined=True, root=os.path.join(root, "nocold")
    )
    seen = []
    events_mod.add_sink(seen.append)
    try:
        cold_fg, cold_e2e, _ = bench_clique(
            world, mb, rounds, pipelined=True,
            root=os.path.join(root, "cold"),
            cold_dir=os.path.join(root, "coldstore"),
        )
    finally:
        events_mod.remove_sink(seen.append)
    spills = [e.payload for e in seen if e.kind == "coldtier_spilled"]
    degraded = [e.payload for e in seen if e.kind == "coldtier_degraded"]
    return {
        "base_fg_ms": round(base_fg * 1e3, 3),
        "cold_fg_ms": round(cold_fg * 1e3, 3),
        "fg_delta_ms": round((cold_fg - base_fg) * 1e3, 3),
        "base_e2e_ms": round(base_e2e * 1e3, 1),
        "cold_e2e_ms": round(cold_e2e * 1e3, 1),
        "spills": len(spills),
        "spilled_bytes": int(sum(p.get("bytes", 0) for p in spills)),
        "degraded": len(degraded),
    }


def bench_checkpointer(mb: int, root: str):
    """Single-rank AsyncCheckpointer foreground: sync-D2H engine vs pipelined."""
    out = {}
    for label, pipelined in (("sync", False), ("pipelined", True)):
        ckpt = AsyncCheckpointer(pipelined=pipelined)
        tree = make_tree(mb, 3.0)
        fgs = []
        for it in range(3):
            path = os.path.join(root, f"ckpt_{label}_{it}.ckpt")
            t0 = time.perf_counter()
            ckpt.async_save(dict(tree, step=it), path)
            fgs.append(time.perf_counter() - t0)
            ckpt.finalize_all()
        ckpt.close()
        out[f"{label}_fg_ms"] = round(statistics.median(fgs) * 1e3, 3)
    return out


def steady_state_alloc_probe(mb: int, root: str) -> float:
    """Peak transient host allocation (MB) during a WARM pipelined save —
    the staging-pool claim is that this stays under 1 MB at any tree size."""
    ckpt = AsyncCheckpointer()
    tree = make_tree(mb, 5.0)
    for it in range(2):  # warm both double-buffer slots
        ckpt.async_save(dict(tree, step=it), os.path.join(root, f"warm{it}.ckpt"))
        ckpt.finalize_all()
    tracemalloc.start()
    base, _ = tracemalloc.get_traced_memory()
    ckpt.async_save(dict(tree, step=9), os.path.join(root, "steady.ckpt"))
    ckpt.finalize_all()
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    ckpt.close()
    return (peak - base) / (1 << 20)


def run_smoke() -> int:
    """Tiny end-to-end run asserting the new spans/metrics actually appear in
    the event stream (wired from scripts/smoke_observability.sh)."""
    from tpu_resiliency.utils import events as events_mod
    from tpu_resiliency.utils.metrics import aggregate

    captured = []
    sink = captured.append
    events_mod.add_sink(sink)
    root = tempfile.mkdtemp(prefix="ckpt_save_smoke.")
    try:
        fg, e2e, staging = bench_clique(2, LEAF_MB, 2, pipelined=True, root=root)
        records = [
            {"ts": e.ts, "source": e.source, "kind": e.kind, **e.payload}
            for e in captured
        ]
        kinds = {r["kind"] for r in records}
        spans = {
            r.get("span") for r in records if r["kind"] in ("span_begin", "span_end")
        }
        assert "ckpt.save.enqueue" in spans, f"missing enqueue span: {sorted(spans)}"
        assert "ckpt.replicate.fanout" in spans, sorted(spans)
        assert "ckpt_foreground_blocked" in kinds, sorted(kinds)
        assert "staging_pool" in kinds, sorted(kinds)
        assert "ckpt_saved" in kinds, sorted(kinds)
        reg = aggregate(records)
        prom = reg.to_prometheus()
        for metric in (
            "tpu_ckpt_foreground_blocked_seconds",
            "tpu_ckpt_staging_pool_bytes",
            "tpu_ckpt_staging_requests_total",
        ):
            assert metric in prom, f"{metric} missing from aggregated metrics"
        assert staging.get("hits", 0) >= 1, staging
        # Delta steady-state leg: chunk-diff frames ship, apply cleanly, and
        # move a fraction of the container.
        droot = os.path.join(root, "delta")
        delta = bench_delta_leg(2, LEAF_MB, 2, droot)
        assert delta["rounds_delta"] >= 1, delta
        assert delta["applied_ok"] >= 1, delta
        assert delta["bytes_ratio"] < 0.5, delta
        # Cold-tier non-interference: the spiller must not move the
        # foreground window (within loopback noise — a synchronous upload
        # would add the whole container's write time and fail this by a
        # mile), while every keyframe still lands in the store.
        cold = bench_cold_leg(2, LEAF_MB, 2, os.path.join(root, "coldleg"))
        assert cold["spills"] >= 2 * 2, cold  # world x rounds keyframes
        assert cold["degraded"] == 0, cold
        assert cold["cold_fg_ms"] <= max(
            cold["base_fg_ms"] * 2.0, cold["base_fg_ms"] + 25.0
        ), f"cold tier moved the foreground window: {cold}"
        print(
            f"bench_ckpt_save smoke OK: fg={fg*1e3:.2f} ms, e2e={e2e*1e3:.1f} ms, "
            f"staging={staging}, delta_ratio={delta['bytes_ratio']}, "
            f"cold_fg_delta={cold['fg_delta_ms']} ms "
            f"({cold['spills']} spills, {cold['spilled_bytes']} B)"
        )
        return 0
    finally:
        events_mod.remove_sink(sink)
        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=int, nargs="+", default=[256, 1024],
                    help="tree sizes (MiB)")
    ap.add_argument("--world", type=int, default=3, help="clique size")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--out", default=None, help="write results JSON here")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny run asserting the new spans/metrics appear")
    args = ap.parse_args(argv)

    if args.smoke:
        return run_smoke()

    sizes = []
    workdir = tempfile.mkdtemp(prefix="bench_ckpt_save.")
    try:
        for mb in args.mb:
            root_s = os.path.join(workdir, f"sync{mb}")
            root_p = os.path.join(workdir, f"pipe{mb}")
            sync_fg, sync_e2e, _ = bench_clique(
                args.world, mb, args.rounds, pipelined=False, root=root_s
            )
            pipe_fg, pipe_e2e, staging = bench_clique(
                args.world, mb, args.rounds, pipelined=True, root=root_p
            )
            root_d = os.path.join(workdir, f"delta{mb}")
            delta = bench_delta_leg(args.world, mb, args.rounds, root_d)
            root_c = os.path.join(workdir, f"cold{mb}")
            cold = bench_cold_leg(args.world, mb, args.rounds, root_c)
            sizes.append({
                "mb": mb,
                "sync_fg_ms": round(sync_fg * 1e3, 3),
                "pipelined_fg_ms": round(pipe_fg * 1e3, 3),
                "fg_ratio": round(pipe_fg / sync_fg, 4),
                "sync_e2e_ms": round(sync_e2e * 1e3, 1),
                "pipelined_e2e_ms": round(pipe_e2e * 1e3, 1),
                "staging": staging,
                "delta": delta,
                "cold": cold,
            })
            shutil.rmtree(root_s, ignore_errors=True)
            shutil.rmtree(root_p, ignore_errors=True)
            shutil.rmtree(root_d, ignore_errors=True)
            shutil.rmtree(root_c, ignore_errors=True)
        probe_mb = min(args.mb)
        results = {
            "world": args.world,
            "rounds": args.rounds,
            "sizes": sizes,
            "checkpointer_256": bench_checkpointer(
                probe_mb, os.path.join(workdir, "single")
            ),
            "steady_state_peak_alloc_mb": round(
                steady_state_alloc_probe(probe_mb, os.path.join(workdir, "probe")), 3
            ),
            "host": platform.node(),
            "cpus": os.cpu_count(),
            "python": platform.python_version(),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
