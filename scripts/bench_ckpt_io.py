"""Measure single-stream vs N-way fan-out checkpoint writes (fsync'd, warm,
alternating runs) — the measurement behind checkpoint/format.py's single-stream
design decision. Re-run on new storage before changing the writer topology.

    python scripts/bench_ckpt_io.py [--gib 1] [--ways 1,4] [--dir DIR]
"""
import argparse
import concurrent.futures as cf
import os
import tempfile
import time

import numpy as np


def write_one(path, data):
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def bench(blob, n_ways, d):
    size = len(blob)
    chunk = size // n_ways
    parts = [blob[i * chunk:(i + 1) * chunk] for i in range(n_ways)]
    paths = [os.path.join(d, f"part{i}.bin") for i in range(n_ways)]
    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(n_ways) as ex:
        list(ex.map(write_one, paths, parts))
    dt = time.perf_counter() - t0
    for p in paths:
        os.unlink(p)
    return dt


def bench_payload(arrays, stripes, d):
    """The PRODUCT write path (format.write_payload), striped vs sequential —
    what $TPU_RESILIENCY_CKPT_STRIPES actually controls."""
    from tpu_resiliency.checkpoint import format as ckpt_format

    path = os.path.join(d, "payload.ckpt")
    t0 = time.perf_counter()
    ckpt_format.write_payload(path, b"hollow", arrays, stripes=stripes)
    dt = time.perf_counter() - t0
    os.unlink(path)
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gib", type=float, default=1.0)
    ap.add_argument("--ways", default="1,4")
    ap.add_argument("--dir", default=None)
    ap.add_argument("--rounds", type=int, default=4)
    args = ap.parse_args()
    size = int(args.gib * (1 << 30))
    ways = [int(w) for w in args.ways.split(",")]
    blob = np.random.default_rng(0).integers(0, 255, size, dtype=np.uint8).tobytes()
    # 64 leaves of 1/64th each: the leaf-count shape write_payload stripes over.
    # Views into the one blob (bytes slicing would copy and double peak memory).
    leaf = size // 64
    full = np.frombuffer(blob, dtype=np.uint8)
    arrays = [full[i * leaf:(i + 1) * leaf] for i in range(64)]
    with tempfile.TemporaryDirectory(dir=args.dir) as d:
        bench(blob, 1, d)  # warm the page cache / allocator
        results = {w: [] for w in ways}
        payload_results = {w: [] for w in ways}
        for _ in range(args.rounds):
            for w in ways:
                results[w].append(bench(blob, w, d))
                payload_results[w].append(bench_payload(arrays, w, d))
        for label, res in (("raw fan-out", results), ("write_payload", payload_results)):
            for w, ts in res.items():
                med = sorted(ts)[len(ts) // 2]
                print(
                    f"{label} {w}-way: {min(ts):.2f}-{max(ts):.2f}s, median {med:.2f}s "
                    f"({size / med / 1e9:.2f} GB/s)"
                )


if __name__ == "__main__":
    main()
