"""Benchmark clique replication: pickled-blob (v1) vs streaming bulk (v2) path.

Loopback clique of N ranks (threads against one KVServer), each replicating a
shard of ``--mb`` megabytes to every clique peer per round — the exact code
path ``LocalCheckpointManager.save`` drives. Two configurations:

- **old**: ``serialize_to_bytes`` (joined blob) + ``replicate()`` over
  ``PeerExchange(protocol=1)`` — every send pickles ``{"src", "tag", "blob"}``
  into fresh contiguous buffers and the receiver copies the payload again.
- **new**: ``serialize_parts`` + ``replicate_parts()`` over the v2 bulk frames —
  sends scatter-gather the caller's buffers (``sendmsg``), receives land in one
  preallocated buffer (``recv_into``), concurrent peer fan-out.

Also measures peak extra allocation of a single send→recv transfer per path
(``tracemalloc``): the zero-copy claim is ``alloc_ratio_new ≤ 1.25`` (the
receive buffer itself is the 1.0; everything beyond it is protocol overhead).

**Byte-economy legs** (checkpoint/coding/): the same clique re-run under

- **erasure** — ``ErasureReplicationStrategy`` ships one RS block per peer
  (k = world-1, parity 1) instead of whole mirrors: the acceptance claim is
  wire bytes per rank ≤ ``(1 + 1/k)×`` the payload vs the mirror path's
  ``(world-1)×``;
- **delta** — steady-state chunk-diff frames between keyframes (a seeded
  ``--dirty-frac`` fraction of chunks mutated per round): the acceptance
  claim is frame bytes ≤ the dirty fraction (plus manifest overhead) of a
  full container, i.e. ≥5× fewer bytes at small dirty fractions;
- **delta_erasure** — the COMPOSED leg: steady-state delta frames shipped
  through ``ErasureReplicationStrategy`` (one RS block of the frame per
  peer). Wire cost per rank per round is ``frame × (1 + m/k)`` against the
  mirror path's ``full × (world-1)`` — the acceptance claim is a ≥20×
  bytes win at 5% dirty on real payloads, plus byte-identical k-of-n
  reconstruction of the frame from the blocks the surviving peers hold.

    python scripts/bench_replication.py [--mb 256] [--world 3] [--rounds 3] \
        [--dirty-frac 0.05] [--out BENCH_replication.json]
    python scripts/bench_replication.py --smoke   # tiny run, assert the gates
"""

import argparse
import concurrent.futures as cf
import json
import os
import platform
import sys
import time
import tracemalloc

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_resiliency.checkpoint import format as ckpt_format  # noqa: E402
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm  # noqa: E402
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy  # noqa: E402
from tpu_resiliency.platform.store import CoordStore, KVServer  # noqa: E402


def _payload(mb: int, rank: int):
    """One leaf-per-16MB tree, the shape serialize_parts scatter-gathers."""
    n = mb * (1 << 20)
    leaf = min(n, 16 << 20)
    rng = np.random.default_rng(rank)
    return [rng.integers(0, 255, leaf, dtype=np.uint8) for _ in range(n // leaf)]


def bench_clique(world: int, mb: int, rounds: int, streaming: bool) -> float:
    """Median seconds per replicate round across the clique."""
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    proto = None if streaming else 1

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0, protocol=proto)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            tensors = _payload(mb, rank)
            times = []
            for _ in range(rounds):
                comm.barrier("round-in")
                t0 = time.perf_counter()
                if streaming:
                    prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
                    held = strat.replicate_parts([prefix, *views])
                    assert len(held) == world - 1
                else:
                    blob = ckpt_format.serialize_to_bytes(b"hollow", tensors)
                    held = strat.replicate(blob)
                    assert len(held) == world
                comm.barrier("round-out")
                times.append(time.perf_counter() - t0)
            return times
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        for s in stores:
            s.close()
        srv.close()
    # A round ends when the slowest rank finishes; barrier timing makes every
    # rank's per-round wall time comparable — take the max across ranks.
    round_times = [max(ts) for ts in zip(*per_rank)]
    return sorted(round_times)[len(round_times) // 2]


def bench_alloc(mb: int, streaming: bool) -> float:
    """Peak extra allocation of ONE send→recv transfer, as a multiple of the
    payload size. Serial phases (send fully buffered by the kernel? no — run
    the send on a thread while the receiver drains) under tracemalloc."""
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=60.0)
        stores.append(s)
        return s

    proto = None if streaming else 1
    nbytes = mb * (1 << 20)
    tensors = _payload(mb, 0)
    exs = []
    try:
        for rank in (0, 1):
            ex = PeerExchange(mk(), rank, timeout=60.0, protocol=proto)
            ex.start()
            exs.append(ex)
        prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        with cf.ThreadPoolExecutor(max_workers=1) as pool:
            if streaming:
                fut = pool.submit(exs[0].send_parts, 1, "t", [prefix, *views])
            else:
                blob = b"".join([prefix, *[bytes(v) for v in views]])
                fut = pool.submit(exs[0].send, 1, "t", blob)
            got = exs[1].recv(0, "t", timeout=60.0)
            fut.result()
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # Container = prefix + payload + v2 integrity trailer.
        assert memoryview(got).cast("B").nbytes == ckpt_format.parts_nbytes(
            prefix, views
        )
        return (peak - base) / nbytes
    finally:
        for ex in exs:
            ex.close()
        for s in stores:
            s.close()
        srv.close()


def bench_erasure(world: int, mb: int, rounds: int) -> dict:
    """Erasure replication round: median seconds + wire bytes per rank per
    round (from the strategy's own ``ckpt_parity`` accounting)."""
    from tpu_resiliency.checkpoint.coding import ErasureReplicationStrategy
    from tpu_resiliency.utils import events as tpu_events

    seen = []
    tpu_events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = ErasureReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world,
                parity=1,
            )
            tensors = _payload(mb, rank)
            times = []
            for _ in range(rounds):
                comm.barrier("round-in")
                t0 = time.perf_counter()
                prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
                held = strat.replicate_parts([prefix, *views])
                assert len(held) == world - 1
                comm.barrier("round-out")
                times.append(time.perf_counter() - t0)
            return times
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        tpu_events.remove_sink(seen.append)
        for s in stores:
            s.close()
        srv.close()
    round_times = [max(ts) for ts in zip(*per_rank)]
    parity = [e.payload for e in seen if e.kind == "ckpt_parity"]
    payload = max(p["payload_bytes"] for p in parity)
    sent = max(p["sent_bytes"] for p in parity)
    k = parity[0]["k"]
    return {
        "round_s": round(sorted(round_times)[len(round_times) // 2], 4),
        "k": k,
        "m": parity[0]["m"],
        "payload_bytes": payload,
        "sent_bytes_per_rank": sent,
        #: the acceptance ratio: wire bytes per rank / payload (mirror = world-1)
        "payload_ratio": round(sent / payload, 4),
        "mirror_payload_ratio": world - 1,
    }


def bench_delta(world: int, mb: int, rounds: int, dirty_frac: float) -> dict:
    """Steady-state delta replication: keyframe round 0, then ``rounds``
    chunk-diff rounds with ``dirty_frac`` of each shard's chunks mutated —
    the exact wire path ``LocalCheckpointManager.save`` ships between
    keyframes. Reports frame bytes vs the full container bytes a mirror
    round moves."""
    from tpu_resiliency.checkpoint.coding import delta as delta_mod

    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    stats_out: dict = {}

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            tensors = _payload(mb, rank)
            rng = np.random.default_rng(rank + 99)
            # Keyframe: full mirror round seeds every peer's base.
            comm.barrier("kf-in")
            prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
            strat.replicate_parts([prefix, *views])
            comm.barrier("kf-out")
            info = ckpt_format.parse_trailer_v3(views[-1])
            leaf_sizes = [v.nbytes for v in views[:-1]]
            base = {
                "iteration": 0,
                "leaf_sizes": leaf_sizes,
                "chunk_size": info.chunk_size,
                "leaf_chunks": info.leaf_chunk_crcs(leaf_sizes),
                "container_crc": info.container_crc,
            }
            times, frames, fulls = [], [], []
            for it in range(1, rounds + 1):
                cs = info.chunk_size
                for t in tensors:  # mutate dirty_frac of each leaf's chunks
                    nchunks = max(1, t.nbytes // cs)
                    for c in range(nchunks):
                        if rng.random() < dirty_frac:
                            t[c * cs] ^= 0xFF
                comm.barrier("d-in")
                t0 = time.perf_counter()
                prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
                frame, st = delta_mod.encode_delta(
                    rank, it, base, prefix, views[:-1], bytes(views[-1])
                )
                strat.replicate_parts([frame])
                comm.barrier("d-out")
                times.append(time.perf_counter() - t0)
                frames.append(st["frame_bytes"])
                fulls.append(st["full_bytes"])
                leaf_sizes = [v.nbytes for v in views[:-1]]
                info2 = ckpt_format.parse_trailer_v3(views[-1])
                base = {
                    "iteration": it,
                    "leaf_sizes": leaf_sizes,
                    "chunk_size": info2.chunk_size,
                    "leaf_chunks": info2.leaf_chunk_crcs(leaf_sizes),
                    "container_crc": info2.container_crc,
                }
            if rank == 0:
                stats_out.update(
                    frame_bytes=int(np.median(frames)),
                    full_bytes=int(np.median(fulls)),
                )
            return times
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        for s in stores:
            s.close()
        srv.close()
    round_times = [max(ts) for ts in zip(*per_rank)]
    frame_b, full_b = stats_out["frame_bytes"], stats_out["full_bytes"]
    return {
        "round_s": round(sorted(round_times)[len(round_times) // 2], 4),
        "dirty_frac": dirty_frac,
        "frame_bytes": frame_b,
        "full_bytes": full_b,
        #: the acceptance ratio: delta wire bytes / full-mirror wire bytes
        "bytes_ratio": round(frame_b / full_b, 4),
        "bytes_win": round(full_b / frame_b, 1),
    }


def bench_delta_erasure(world: int, mb: int, rounds: int,
                        dirty_frac: float) -> dict:
    """The COMPOSED byte-economy leg: steady-state delta frames between
    keyframes, each frame itself SHIPPED erasure-coded — one RS block per
    peer instead of whole-frame mirrors. The wire cost per round is
    ``frame_bytes × (1 + m/k)`` against the mirror path's
    ``full_bytes × (world-1)``, which is where the two planes multiply.

    Also proves the resilience side of the claim on the REAL wire
    artifacts: the blocks the peers hold after the last round (k of n —
    the source rank and its local block presumed lost) reconstruct the
    delta frame byte-identically through the production
    ``reconstruct_container`` fences."""
    from tpu_resiliency.checkpoint.coding import (
        ErasureReplicationStrategy,
        delta as delta_mod,
        strategy as ec_strategy,
    )
    from tpu_resiliency.utils import events as tpu_events

    seen = []
    tpu_events.add_sink(seen.append)
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    stats_out: dict = {}

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0)
        ex.start()
        try:
            strat = ErasureReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world,
                parity=1,
            )
            tensors = _payload(mb, rank)
            rng = np.random.default_rng(rank + 99)
            comm.barrier("kfe-in")
            prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
            strat.replicate_parts([prefix, *views])
            comm.barrier("kfe-out")
            info = ckpt_format.parse_trailer_v3(views[-1])
            leaf_sizes = [v.nbytes for v in views[:-1]]
            base = {
                "iteration": 0,
                "leaf_sizes": leaf_sizes,
                "chunk_size": info.chunk_size,
                "leaf_chunks": info.leaf_chunk_crcs(leaf_sizes),
                "container_crc": info.container_crc,
            }
            times, frames, fulls = [], [], []
            held = []
            frame = b""
            for it in range(1, rounds + 1):
                cs = info.chunk_size
                for t in tensors:
                    nchunks = max(1, t.nbytes // cs)
                    for c in range(nchunks):
                        if rng.random() < dirty_frac:
                            t[c * cs] ^= 0xFF
                comm.barrier("de-in")
                t0 = time.perf_counter()
                prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
                frame, st = delta_mod.encode_delta(
                    rank, it, base, prefix, views[:-1], bytes(views[-1])
                )
                held = strat.replicate_parts([frame])
                comm.barrier("de-out")
                times.append(time.perf_counter() - t0)
                frames.append(st["frame_bytes"])
                fulls.append(st["full_bytes"])
                leaf_sizes = [v.nbytes for v in views[:-1]]
                info2 = ckpt_format.parse_trailer_v3(views[-1])
                base = {
                    "iteration": it,
                    "leaf_sizes": leaf_sizes,
                    "chunk_size": info2.chunk_size,
                    "leaf_chunks": info2.leaf_chunk_crcs(leaf_sizes),
                    "container_crc": info2.container_crc,
                }
            if rank == 0:
                stats_out.update(
                    frame_bytes=int(np.median(frames)),
                    full_bytes=int(np.median(fulls)),
                    last_frame=bytes(frame),
                )
            return times, held
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        tpu_events.remove_sink(seen.append)
        for s in stores:
            s.close()
        srv.close()
    round_times = [max(ts) for ts in zip(*[ts for ts, _ in per_rank])]
    frame_b, full_b = stats_out["frame_bytes"], stats_out["full_bytes"]

    # Per-round wire accounting off the strategy's own ckpt_parity events;
    # the keyframe round codes the full container, the steady-state rounds
    # code frames a fraction of its size — split on payload size.
    parity = [e.payload for e in seen if e.kind == "ckpt_parity"]
    kf_payload = max(p["payload_bytes"] for p in parity)
    delta_rounds = [p for p in parity if p["payload_bytes"] < kf_payload / 2]
    assert delta_rounds, "no delta-coded rounds observed"
    k = delta_rounds[0]["k"]
    m = delta_rounds[0]["m"]
    payload = max(p["payload_bytes"] for p in delta_rounds)
    sent = max(p["sent_bytes"] for p in delta_rounds)

    # k-of-n reconstruction of rank 0's LAST frame from the blocks its
    # peers actually hold (source rank dead, its local block lost with it).
    want_frame = stats_out["last_frame"]
    survivors_blocks = []
    for _, held in per_rank[1:]:
        art = held.get(0)
        if art is None:
            continue
        header, _ = ec_strategy.parse_block(art)
        assert header.get("payload") == "delta", header
        survivors_blocks.append(art)
    assert len(survivors_blocks) >= k, (
        f"peers hold {len(survivors_blocks)} of rank 0's frame blocks, "
        f"need k={k}"
    )
    rebuilt = ec_strategy.reconstruct_container(
        survivors_blocks[:k], source="bench-delta-erasure"
    )
    assert rebuilt == want_frame, (
        "k-of-n reconstructed delta frame is NOT byte-identical"
    )

    return {
        "round_s": round(sorted(round_times)[len(round_times) // 2], 4),
        "dirty_frac": dirty_frac,
        "k": k,
        "m": m,
        "frame_bytes": frame_b,
        "full_bytes": full_b,
        #: wire bytes per rank per round / the frame payload (≤ 1 + m/k)
        "payload_ratio": round(sent / payload, 4),
        #: composed win: full-mirror round bytes / coded delta round bytes
        "bytes_win": round((full_b * (world - 1)) / sent, 1),
        "reconstruct_ok": True,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=int, default=256, help="shard size per rank (MiB)")
    ap.add_argument("--world", type=int, default=3, help="clique size")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--dirty-frac", type=float, default=0.05,
                    help="fraction of chunks mutated per delta round")
    ap.add_argument("--alloc-mb", type=int, default=None,
                    help="payload for the allocation probe (default: --mb)")
    ap.add_argument("--out", default=None, help="write results JSON here")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny run asserting the byte-economy gates, exit 0/1")
    args = ap.parse_args(argv)

    if args.smoke:
        args.mb, args.world, args.rounds = 8, 3, 2
        args.alloc_mb = 2

    # Bytes exchanged per round: every rank sends its shard to world-1 peers.
    exchanged = args.world * (args.world - 1) * args.mb * (1 << 20)

    old_s = bench_clique(args.world, args.mb, args.rounds, streaming=False)
    new_s = bench_clique(args.world, args.mb, args.rounds, streaming=True)
    alloc_mb = args.alloc_mb or args.mb
    alloc_old = bench_alloc(alloc_mb, streaming=False)
    alloc_new = bench_alloc(alloc_mb, streaming=True)
    erasure = bench_erasure(args.world, args.mb, args.rounds)
    delta = bench_delta(args.world, args.mb, args.rounds, args.dirty_frac)
    delta_erasure = bench_delta_erasure(
        args.world, args.mb, args.rounds, args.dirty_frac
    )

    results = {
        "world": args.world,
        "payload_mb": args.mb,
        "rounds": args.rounds,
        "old_round_s": round(old_s, 4),
        "new_round_s": round(new_s, 4),
        "old_mbps": round(exchanged / old_s / 1e6, 1),
        "new_mbps": round(exchanged / new_s / 1e6, 1),
        "speedup": round(old_s / new_s, 2),
        "alloc_probe_mb": alloc_mb,
        "alloc_ratio_old": round(alloc_old, 3),
        "alloc_ratio_new": round(alloc_new, 3),
        "erasure": erasure,
        "delta": delta,
        "delta_erasure": delta_erasure,
        "host": platform.node(),
        "python": platform.python_version(),
    }
    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    if args.smoke:
        k = erasure["k"]
        ce_k = delta_erasure["k"]
        # bytes_win at full scale must clear 20× (the 5%-dirty composed
        # claim); the smoke payload is tiny so manifest overhead dominates —
        # gate the composition mechanics (coded ratio + reconstruction)
        # there, and still require a material win over plain mirroring.
        ok = (
            erasure["payload_ratio"] <= (1 + 1 / k) + 0.05
            and erasure["payload_ratio"] < erasure["mirror_payload_ratio"]
            and delta["bytes_ratio"] < 0.5
            and delta_erasure["payload_ratio"] <= (1 + 1 / ce_k) + 0.05
            and delta_erasure["bytes_win"] >= 2.0
            and delta_erasure["reconstruct_ok"]
        )
        print(f"bench_replication smoke: {'PASS' if ok else 'FAIL'} "
              f"(erasure ratio {erasure['payload_ratio']} vs mirror "
              f"{erasure['mirror_payload_ratio']}; delta ratio "
              f"{delta['bytes_ratio']}; composed win "
              f"{delta_erasure['bytes_win']}x ratio "
              f"{delta_erasure['payload_ratio']})")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
