"""Benchmark clique replication: pickled-blob (v1) vs streaming bulk (v2) path.

Loopback clique of N ranks (threads against one KVServer), each replicating a
shard of ``--mb`` megabytes to every clique peer per round — the exact code
path ``LocalCheckpointManager.save`` drives. Two configurations:

- **old**: ``serialize_to_bytes`` (joined blob) + ``replicate()`` over
  ``PeerExchange(protocol=1)`` — every send pickles ``{"src", "tag", "blob"}``
  into fresh contiguous buffers and the receiver copies the payload again.
- **new**: ``serialize_parts`` + ``replicate_parts()`` over the v2 bulk frames —
  sends scatter-gather the caller's buffers (``sendmsg``), receives land in one
  preallocated buffer (``recv_into``), concurrent peer fan-out.

Also measures peak extra allocation of a single send→recv transfer per path
(``tracemalloc``): the zero-copy claim is ``alloc_ratio_new ≤ 1.25`` (the
receive buffer itself is the 1.0; everything beyond it is protocol overhead).

    python scripts/bench_replication.py [--mb 256] [--world 3] [--rounds 3] \
        [--out BENCH_replication.json]
"""

import argparse
import concurrent.futures as cf
import json
import os
import platform
import sys
import time
import tracemalloc

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_resiliency.checkpoint import format as ckpt_format  # noqa: E402
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm  # noqa: E402
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy  # noqa: E402
from tpu_resiliency.platform.store import CoordStore, KVServer  # noqa: E402


def _payload(mb: int, rank: int):
    """One leaf-per-16MB tree, the shape serialize_parts scatter-gathers."""
    n = mb * (1 << 20)
    leaf = min(n, 16 << 20)
    rng = np.random.default_rng(rank)
    return [rng.integers(0, 255, leaf, dtype=np.uint8) for _ in range(n // leaf)]


def bench_clique(world: int, mb: int, rounds: int, streaming: bool) -> float:
    """Median seconds per replicate round across the clique."""
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=120.0)
        stores.append(s)
        return s

    proto = None if streaming else 1

    def body(rank):
        comm = StoreComm(mk(), rank, list(range(world)), timeout=120.0)
        ex = PeerExchange(mk(), rank, timeout=120.0, protocol=proto)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=world
            )
            tensors = _payload(mb, rank)
            times = []
            for _ in range(rounds):
                comm.barrier("round-in")
                t0 = time.perf_counter()
                if streaming:
                    prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
                    held = strat.replicate_parts([prefix, *views])
                    assert len(held) == world - 1
                else:
                    blob = ckpt_format.serialize_to_bytes(b"hollow", tensors)
                    held = strat.replicate(blob)
                    assert len(held) == world
                comm.barrier("round-out")
                times.append(time.perf_counter() - t0)
            return times
        finally:
            ex.close()

    try:
        with cf.ThreadPoolExecutor(max_workers=world) as pool:
            per_rank = [
                f.result(timeout=600.0)
                for f in [pool.submit(body, r) for r in range(world)]
            ]
    finally:
        for s in stores:
            s.close()
        srv.close()
    # A round ends when the slowest rank finishes; barrier timing makes every
    # rank's per-round wall time comparable — take the max across ranks.
    round_times = [max(ts) for ts in zip(*per_rank)]
    return sorted(round_times)[len(round_times) // 2]


def bench_alloc(mb: int, streaming: bool) -> float:
    """Peak extra allocation of ONE send→recv transfer, as a multiple of the
    payload size. Serial phases (send fully buffered by the kernel? no — run
    the send on a thread while the receiver drains) under tracemalloc."""
    srv = KVServer(host="127.0.0.1", port=0)
    stores = []

    def mk():
        s = CoordStore("127.0.0.1", srv.port, timeout=60.0)
        stores.append(s)
        return s

    proto = None if streaming else 1
    nbytes = mb * (1 << 20)
    tensors = _payload(mb, 0)
    exs = []
    try:
        for rank in (0, 1):
            ex = PeerExchange(mk(), rank, timeout=60.0, protocol=proto)
            ex.start()
            exs.append(ex)
        prefix, views = ckpt_format.serialize_parts(b"hollow", tensors)
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        with cf.ThreadPoolExecutor(max_workers=1) as pool:
            if streaming:
                fut = pool.submit(exs[0].send_parts, 1, "t", [prefix, *views])
            else:
                blob = b"".join([prefix, *[bytes(v) for v in views]])
                fut = pool.submit(exs[0].send, 1, "t", blob)
            got = exs[1].recv(0, "t", timeout=60.0)
            fut.result()
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # Container = prefix + payload + v2 integrity trailer.
        assert memoryview(got).cast("B").nbytes == ckpt_format.parts_nbytes(
            prefix, views
        )
        return (peak - base) / nbytes
    finally:
        for ex in exs:
            ex.close()
        for s in stores:
            s.close()
        srv.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=int, default=256, help="shard size per rank (MiB)")
    ap.add_argument("--world", type=int, default=3, help="clique size")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--alloc-mb", type=int, default=None,
                    help="payload for the allocation probe (default: --mb)")
    ap.add_argument("--out", default=None, help="write results JSON here")
    args = ap.parse_args(argv)

    # Bytes exchanged per round: every rank sends its shard to world-1 peers.
    exchanged = args.world * (args.world - 1) * args.mb * (1 << 20)

    old_s = bench_clique(args.world, args.mb, args.rounds, streaming=False)
    new_s = bench_clique(args.world, args.mb, args.rounds, streaming=True)
    alloc_mb = args.alloc_mb or args.mb
    alloc_old = bench_alloc(alloc_mb, streaming=False)
    alloc_new = bench_alloc(alloc_mb, streaming=True)

    results = {
        "world": args.world,
        "payload_mb": args.mb,
        "rounds": args.rounds,
        "old_round_s": round(old_s, 4),
        "new_round_s": round(new_s, 4),
        "old_mbps": round(exchanged / old_s / 1e6, 1),
        "new_mbps": round(exchanged / new_s / 1e6, 1),
        "speedup": round(old_s / new_s, 2),
        "alloc_probe_mb": alloc_mb,
        "alloc_ratio_old": round(alloc_old, 3),
        "alloc_ratio_new": round(alloc_new, 3),
        "host": platform.node(),
        "python": platform.python_version(),
    }
    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
