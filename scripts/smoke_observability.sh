#!/usr/bin/env bash
# Smoke-check the observability pipeline end to end on one machine:
# a tiny standalone launch with $TPU_RESILIENCY_EVENTS_FILE set must yield an
# events JSONL from which BOTH the Chrome-trace export and the metrics dump
# produce non-empty, schema-valid output. Exits non-zero on any gap.
#
# Usage: scripts/smoke_observability.sh [workdir]
set -euo pipefail

cd "$(dirname "$0")/.."
WORKDIR="${1:-$(mktemp -d /tmp/tpu_obs_smoke.XXXXXX)}"
mkdir -p "$WORKDIR"
EVENTS="$WORKDIR/events.jsonl"
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

echo "== smoke: standalone launch (1 fault, 1 restart) -> $EVENTS"
cat > "$WORKDIR/worker.py" <<'PY'
import os, sys
round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
if round_no == 0:
    sys.exit(3)
print("recovered in round", round_no)
PY
python -m tpu_resiliency.launcher.launch \
    --standalone --nproc-per-node 1 --max-restarts 2 --no-ft-monitors \
    --rdzv-last-call 0.2 --monitor-interval 0.1 \
    --events-file "$EVENTS" --run-dir "$WORKDIR/run" \
    "$WORKDIR/worker.py"

test -s "$EVENTS" || { echo "FAIL: events file empty"; exit 1; }

echo "== smoke: trace export"
python -m tpu_resiliency.tools.trace_export "$EVENTS" -o "$WORKDIR/trace.json"
python - "$WORKDIR/trace.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
evs = doc["traceEvents"]
assert evs, "empty traceEvents"
assert all({"name", "ph", "pid"} <= set(e) for e in evs), "malformed trace event"
slices = {e["name"] for e in evs if e["ph"] == "X"}
assert "launcher.job" in slices and "launcher.round" in slices, slices
assert sum(1 for e in evs if e["ph"] == "X" and e["name"] == "launcher.round") >= 2, \
    "restart chain missing its second round"
print(f"trace OK: {len(evs)} events, spans: {sorted(slices)}")
PY

echo "== smoke: metrics dump"
python -m tpu_resiliency.tools.metrics_dump "$EVENTS" --format json -o "$WORKDIR/metrics.json"
python - "$WORKDIR/metrics.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
m = doc["metrics"]
assert m, "empty metrics"
restarts = sum(e["value"] for e in m.get("tpu_restarts_total", []))
assert restarts >= 1, f"no restarts aggregated: {sorted(m)}"
spans = m.get("tpu_span_seconds", [])
assert any(e["labels"].get("span") == "rendezvous.round" and e["count"] >= 1
           for e in spans), "no rendezvous duration quantiles"
print(f"metrics OK: {len(m)} families, restarts={int(restarts)}")
PY
python -m tpu_resiliency.tools.metrics_dump "$EVENTS" | sed 's/^/    /'

echo "== smoke: restart latency (warm-spare promotion + fast-path rendezvous + compile-cache hit)"
python scripts/bench_restart.py --smoke

echo "== smoke: pipelined checkpoint save (spans + staging metrics)"
python scripts/bench_ckpt_save.py --smoke

echo "== smoke: checkpoint integrity (v2 checksums + ckpt_info --verify preflight)"
python - "$WORKDIR" <<'PY'
import os, sys
import numpy as np
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

root = os.path.join(sys.argv[1], "ckpt_root")
mgr = LocalCheckpointManager(root, rank=0)
mgr.save(1, PyTreeStateDict({"w": np.arange(4096, dtype=np.float32)}), is_async=False)
mgr.close()
PY
python -m tpu_resiliency.tools.ckpt_info "$WORKDIR/ckpt_root" --verify
python - "$WORKDIR" <<'PY'
import os, sys
rdir = os.path.join(sys.argv[1], "ckpt_root", "s0", "r0")
path = [os.path.join(rdir, n) for n in os.listdir(rdir) if n.endswith(".ckpt")][0]
with open(path, "r+b") as f:          # flip one payload bit
    f.seek(os.path.getsize(path) // 2)
    b = f.read(1); f.seek(-1, 1); f.write(bytes([b[0] ^ 1]))
PY
if python -m tpu_resiliency.tools.ckpt_info "$WORKDIR/ckpt_root" --verify; then
    echo "FAIL: ckpt_info --verify missed an injected bit flip"; exit 1
else
    echo "integrity OK: --verify caught the flipped bit (exit 1 as designed)"
fi
# The chunk-manifest view must LOCATE the flip (exact leaf/chunk coordinates).
if python -m tpu_resiliency.tools.ckpt_info "$WORKDIR/ckpt_root" --chunks > "$WORKDIR/chunks.out" 2>&1; then
    echo "FAIL: ckpt_info --chunks missed the injected bit flip"; exit 1
fi
sed 's/^/    /' "$WORKDIR/chunks.out"
grep -q "chunk" "$WORKDIR/chunks.out" || { echo "FAIL: --chunks named no chunk"; exit 1; }
echo "chunk-manifest OK: --chunks located the corrupt chunk (exit 1 as designed)"

echo "== smoke: checkpoint byte economy (erasure k-of-n + delta chunk-diff)"
python scripts/bench_replication.py --smoke

echo "== smoke: goodput plane (live /metrics + /goodput on the launcher vs offline --goodput)"
GP="$WORKDIR/goodput"
mkdir -p "$GP"
cat > "$GP/worker.py" <<'PY'
import os, sys, time
import numpy as np
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.utils.events import record

stop, ckpt_root = sys.argv[1], sys.argv[2]
round_no = int(os.environ["TPU_FT_RESTART_COUNT"])
rank = int(os.environ.get("RANK", "0"))
for i in range(10):
    record("inprocess", "iteration_start", iteration=i)
    time.sleep(0.05)
m = LocalCheckpointManager(ckpt_root, rank=rank)
m.save(round_no, PyTreeStateDict({"w": np.arange(8192, dtype=np.float32)}), is_async=False)
m.close()
if round_no == 0 and rank == 0:
    sys.exit(3)  # round 0 fault: the restart phase must show up in /goodput
i = 10
deadline = time.time() + 90
while not os.path.exists(stop) and time.time() < deadline:
    record("inprocess", "iteration_start", iteration=i)
    i += 1
    time.sleep(0.05)
PY
python -m tpu_resiliency.launcher.launch \
    --standalone --nproc-per-node 2 --max-restarts 2 --no-ft-monitors \
    --rdzv-last-call 0.2 --monitor-interval 0.1 --telemetry-port 0 \
    --events-file "$GP/events.jsonl" --run-dir "$GP/run" \
    "$GP/worker.py" "$GP/stop" "$GP/ckpt" &
GP_PID=$!
python - "$GP" <<'PY'
import json, os, sys, time, urllib.error, urllib.request

gp = sys.argv[1]
port_file = os.path.join(gp, "run", "telemetry.port")
deadline = time.time() + 60
while not os.path.exists(port_file):
    assert time.time() < deadline, "telemetry.port handshake file never appeared"
    time.sleep(0.2)
port = int(open(port_file).read().strip())
summary = None
while time.time() < deadline:
    try:
        summary = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/goodput", timeout=5).read())
    except OSError:
        time.sleep(0.3)
        continue
    ph = summary["phases"]
    if ph["train"] > 0 and ph["ckpt_stall"] > 0 and ph["restart"] > 0:
        break
    time.sleep(0.3)
ph = summary["phases"]
assert ph["train"] > 0 and ph["ckpt_stall"] > 0 and ph["restart"] > 0, summary
wall = summary["wall_clock_s"]
assert abs(sum(ph.values()) - wall) <= 0.05 * wall, (
    f"attribution phases {ph} do not sum to wall clock {wall}")
prom = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
assert "tpu_goodput_ratio" in prom, prom[:2000]
assert "tpu_time_attributed_seconds_total" in prom, prom[:2000]
assert "tpu_step_seconds_bucket" in prom, prom[:2000]
# Forensics plane: the live /storez document must answer 200 with nonzero
# op counts from the launcher-hosted coordination store.
sz = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/storez", timeout=5).read())
assert sz["schema"] == "tpu-storez-1", sz
assert sz.get("enabled") is True, sz
assert sum(r.get("count", 0) for r in (sz.get("ops") or {}).values()) > 0, sz
print(f"/storez OK: {len(sz.get('ops') or {})} op families, "
      f"conns={sz.get('conns')}")
try:
    hz = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=5).read())
except urllib.error.HTTPError as e:
    hz = json.loads(e.read())  # 503 mid-restart still carries the document
assert "healthy" in hz, hz
print(f"goodput live OK: ratio={summary['goodput_ratio']} phases={ph}")
PY
touch "$GP/stop"
wait "$GP_PID"
python -m tpu_resiliency.tools.metrics_dump "$GP/events.jsonl" --goodput | sed 's/^/    /'
python -m tpu_resiliency.tools.metrics_dump "$GP/events.jsonl" --goodput --format json | \
    python -c "import json,sys; d=json.load(sys.stdin); assert d['phases']['restart']>0 and d['phases']['ckpt_stall']>0, d" \
    || { echo "FAIL: offline --goodput lost the restart/ckpt attribution"; exit 1; }

echo "== smoke: performance forensics (critical path + byte-flow ledger + store op storm)"
# The restart episode in the goodput run's stream must name rendezvous.round
# on its critical path, and the milestone decomposition must be present.
CP=$(python -m tpu_resiliency.tools.critpath "$GP/events.jsonl" --episode restart)
echo "$CP" | sed 's/^/    /'
echo "$CP" | grep -q "rendezvous.round" \
    || { echo "FAIL: rendezvous.round missing from the restart critical path"; exit 1; }
echo "$CP" | grep -q "rendezvous " \
    || { echo "FAIL: milestone segments missing from tpu-critpath output"; exit 1; }
# Highlighted trace export round-trips.
python -m tpu_resiliency.tools.critpath "$GP/events.jsonl" --trace "$GP/crit.trace.json" > /dev/null
python - "$GP/crit.trace.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
crit = [e for e in doc["traceEvents"] if e.get("args", {}).get("critical_path")]
assert crit, "no critical-path spans highlighted in the trace"
assert all("self_time_ms" in e["args"] for e in doc["traceEvents"]
           if e.get("ph") == "X"), "span slices lost self_time_ms"
print(f"highlighted trace OK: {len(crit)} critical-path spans")
PY
# Byte-flow ledger: the run's bytes attribute to purposes with <5% residue.
python -m tpu_resiliency.tools.metrics_dump "$GP/events.jsonl" --bytes | sed 's/^/    /'
python -m tpu_resiliency.tools.metrics_dump "$GP/events.jsonl" --bytes --format json | \
    python -c "import json,sys; d=json.load(sys.stdin); assert d['total_bytes']>0 and d['accounted_frac']>=0.95, d" \
    || { echo "FAIL: byte-flow ledger residue exceeds 5%"; exit 1; }
# Store op storm: telemetry answers under load (server-side account sane),
# plus the store-scale leg — reduced-rank sharded storm (clique spawn, hash
# fan-out, tree DAG, aggregated per-shard stats asserted inside).
python scripts/bench_store.py --smoke --ranks 128 --shards 2

echo "== smoke: store scale (clique shard map + per-shard op totals render)"
SSDIR="$WORKDIR/store_scale"
mkdir -p "$SSDIR"
python - "$SSDIR" <<'PY'
import subprocess, sys
from tpu_resiliency.platform.shardstore import CLIQUE_KEY, SpawnedClique
from tpu_resiliency.platform.store import CoordStore

clique = SpawnedClique(2)
try:
    shard0 = CoordStore(*clique.endpoints[0])
    shard0.set(CLIQUE_KEY, clique.spec)
    st = clique.client()
    for i in range(32):
        st.set(f"smoke/{i}", i)
    # Single classic endpoint in, whole-clique aggregate out (discovery).
    out = subprocess.run(
        [sys.executable, "-m", "tpu_resiliency.tools.store_info",
         f"127.0.0.1:{clique.port}", "--stats"],
        capture_output=True, text=True, timeout=60,
    )
    sys.stdout.write(out.stdout)
    assert out.returncode == 0, out.stderr
    assert "backend: epoll" in out.stdout, out.stdout
    assert "shards: 2 (crc32" in out.stdout, out.stdout
    assert "per-shard op totals:" in out.stdout, out.stdout
    assert out.stdout.count("epoll") >= 3, out.stdout  # header + 2 shard rows
    st.close(); shard0.close()
finally:
    clique.close()
print("store-scale stats render OK: backend + shard map + per-shard totals")
PY

echo "== smoke: elastic reshard (ranged fetch moves fewer bytes than full mirrors)"
python scripts/bench_reshard.py --smoke

echo "== smoke: sub-second elastic resume (shrink-to-trainable < 1s at 64 MB)"
python scripts/bench_reshard.py --mb 64 --assert-subsecond

echo "== smoke: elastic reshard plan preflight (ckpt_info --plan)"
RS="$WORKDIR/reshard"
mkdir -p "$RS"
python - "$RS" <<'PY'
import os, sys
import numpy as np
from tpu_resiliency.checkpoint import reshard as R
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict

root = os.path.join(sys.argv[1], "root")
G = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
layout = R.TreeLayout([("dp", 2)], [0, 1], [R.LeafSpec(G.shape, "float32", ("dp",))])
for rank in (0, 1):
    m = LocalCheckpointManager(root, rank=rank)
    m.save(1, PyTreeStateDict({"w": R.slice_local([G], layout, rank)[0]}),
           is_async=False, layout=layout)
    m.close()
PY
python -m tpu_resiliency.tools.ckpt_info "$RS/root" --world 0 --plan | sed 's/^/    /'
rm -rf "$RS/root/s0/r1"
if python -m tpu_resiliency.tools.ckpt_info "$RS/root" --world 0 --plan > "$RS/plan.out" 2>&1; then
    echo "FAIL: --plan missed the uncovered source rank"; exit 1
else
    grep -q "UNCOVERED" "$RS/plan.out" || { echo "FAIL: --plan exit 1 without naming the gap"; exit 1; }
    echo "reshard plan OK: --plan caught the uncovered rank (exit 1 as designed)"
fi

echo "== smoke: chaos (seeded fault injection across store/p2p/ipc/disk channels + mixed campaign + elastic chain + store failover)"
python scripts/chaos_soak.py --smoke --workdir "$WORKDIR/chaos" --out "$WORKDIR/chaos/report.json"
# The store-failover campaign (SIGKILL a shard mid-barrier-storm and
# mid-rendezvous) must have run inside the seeded pass and reproduced: exact
# deduped counter, a keyspace digest, and both victims recorded.
python - "$WORKDIR/chaos/report.json" <<'PY'
import json, sys
run = json.load(open(sys.argv[1]))["runs"][0]
assert run.get("store_failover_digest"), "store-failover scenario left no keyspace digest"
assert run.get("store_failover_counter", 0) > 0, run.get("store_failover_counter")
assert len(run.get("store_failover_victims", [])) == 2, run
print(f"store-failover chaos OK: kill_round={run['store_failover_kill_round']} "
      f"victims={run['store_failover_victims']} counter={run['store_failover_counter']}")
PY

echo "== smoke: cold start (job-tree SIGKILL -> fresh-workdir resume from the cold tier + offline --cold audit)"
COLD_DIR="$WORKDIR/chaos/cold_1234"
# The chaos leg already ran scenario_cold_start twice-per-seed: clean restore
# on a different world size resumed iter 2, the seeded archive bitflip climbed
# to iter 1, and the two legs restored different bytes.
python - "$WORKDIR/chaos/report.json" <<'PY'
import json, sys
run = json.load(open(sys.argv[1]))["runs"][0]
assert run["cold_start_resumed"]["clean"] == [2, 2], run["cold_start_resumed"]
assert run["cold_start_resumed"]["bitflip"] == [1, 1], run["cold_start_resumed"]
assert run["cold_start_digests"]["clean"] != run["cold_start_digests"]["bitflip"]
f = run["cold_start_fault"]
print(f"cold-start chaos OK: clean resume iter 2, seeded bitflip "
      f"(owner {f['victim_owner']} @ byte {f['flip_at']}) climbed to iter 1")
PY
# Offline audit of the killed job's workdir: archived owners join coverage as
# the third rung and render per iteration.
python -m tpu_resiliency.tools.ckpt_info "$COLD_DIR/root" --cold "$COLD_DIR/cold" \
    > "$COLD_DIR/coldinfo.out"
sed 's/^/    /' "$COLD_DIR/coldinfo.out"
grep -q "in cold tier" "$COLD_DIR/coldinfo.out" \
    || { echo "FAIL: --cold audit lost the cold-tier iteration count"; exit 1; }
grep -q "cold: \[0, 1, 2\]" "$COLD_DIR/coldinfo.out" \
    || { echo "FAIL: --cold audit lost the archived owners"; exit 1; }
# Restore-anywhere: an EMPTY workdir still audits what a new job could
# bootstrap from the object store alone.
mkdir -p "$COLD_DIR/nowhere"
python -m tpu_resiliency.tools.ckpt_info "$COLD_DIR/nowhere" --cold "$COLD_DIR/cold" \
    | grep -q "resumable from: iter" \
    || { echo "FAIL: empty workdir + --cold found nothing resumable"; exit 1; }
# --verify must catch the scenario's seeded archive bitflip (exit 1) and name
# the digest mismatch.
if python -m tpu_resiliency.tools.ckpt_info "$COLD_DIR/nowhere" --cold "$COLD_DIR/cold" \
    --verify > "$COLD_DIR/coldverify.out" 2>&1; then
    echo "FAIL: --cold --verify missed the seeded archive bitflip"; exit 1
fi
sed 's/^/    /' "$COLD_DIR/coldverify.out"
grep -q "digest mismatch" "$COLD_DIR/coldverify.out" \
    || { echo "FAIL: --cold --verify verdict lost the digest mismatch"; exit 1; }
# The tpu_coldtier_* families aggregate from the restore legs' event stream.
python -m tpu_resiliency.tools.metrics_dump "$COLD_DIR/events.jsonl" --format prom | \
    grep -q "tpu_coldtier_fetch_total" \
    || { echo "FAIL: tpu_coldtier_fetch_total missing from metrics dump"; exit 1; }
python -m tpu_resiliency.tools.metrics_dump "$COLD_DIR/events.jsonl" --format prom | \
    grep -q 'outcome="corrupt"' \
    || { echo "FAIL: corrupt cold fetch never reached the metrics plane"; exit 1; }
echo "cold-start smoke OK: offline --cold audit, empty-workdir bootstrap view, archive verify, metrics"

echo "== smoke: incident plane (artifact renders + tpu_incident_*/tpu_remediation_* metrics)"
MIXED_DIR="$WORKDIR/chaos/mixed_1234"
python -m tpu_resiliency.tools.incident_report "$MIXED_DIR/incidents" --list
python -m tpu_resiliency.tools.incident_report "$MIXED_DIR/incidents" | sed 's/^/    /'
python -m tpu_resiliency.tools.metrics_dump "$MIXED_DIR/events.jsonl" --format prom | \
    grep -q "tpu_incidents_total" || { echo "FAIL: tpu_incident_* missing from metrics dump"; exit 1; }
python -m tpu_resiliency.tools.metrics_dump "$MIXED_DIR/events.jsonl" --format prom | \
    grep -q "tpu_remediation_actions_total" || { echo "FAIL: tpu_remediation_actions_total missing"; exit 1; }
python -m tpu_resiliency.tools.events_summary "$MIXED_DIR/events.jsonl" --kind incident_closed --no-timeline > /dev/null

echo "== smoke: hang forensics (/hangz census + stack dumps + incident table)"
HANG_DIR="$WORKDIR/chaos/hang_1234"
# The live /hangz view captured mid-stall must name the seeded victim and a
# blocked barrier with missing ranks.
python - "$HANG_DIR/hangz.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "tpu-hangz-1", doc.get("schema")
assert doc["suspects"], "no suspects in the captured /hangz census"
assert any(b.get("missing") for b in doc["barriers"]), doc["barriers"]
assert any(r.get("stuck_s") for r in doc["ranks"]), doc["ranks"]
print(f"/hangz OK: suspects={[s['rank'] for s in doc['suspects']]} "
      f"open_barriers={len(doc['barriers'])}")
PY
# The rendered incident table shows who was stuck where and who never
# arrived. Captured once: `grep -q` would close the pipe early and turn the
# CLI's deliberate SIGPIPE exit (141) into a pipefail failure.
HANG_REPORT=$(python -m tpu_resiliency.tools.incident_report "$HANG_DIR/incidents")
echo "$HANG_REPORT" | sed 's/^/    /'
echo "$HANG_REPORT" | grep -q "hang census" \
    || { echo "FAIL: incident report lost the hang census table"; exit 1; }
echo "$HANG_REPORT" | grep -q "never arrived" \
    || { echo "FAIL: census table lost the missing ranks"; exit 1; }
# The new metric families aggregate from the hang run's events stream.
python -m tpu_resiliency.tools.metrics_dump "$HANG_DIR/events.jsonl" --format prom | \
    grep -q "tpu_stack_dumps_total" || { echo "FAIL: tpu_stack_dumps_total missing"; exit 1; }
python -m tpu_resiliency.tools.metrics_dump "$HANG_DIR/events.jsonl" --format prom | \
    grep -q "tpu_hang_suspects_total" || { echo "FAIL: tpu_hang_suspects_total missing"; exit 1; }
# --kind composes: slice the stream to the forensics chain only.
python -m tpu_resiliency.tools.events_summary "$HANG_DIR/events.jsonl" \
    --kind hang_detected,stack_dump,kill_ladder,hang_census --no-timeline | sed 's/^/    /'
python -m tpu_resiliency.tools.store_info --help | grep -q -- "--barriers" \
    || { echo "FAIL: store_info lost --barriers"; exit 1; }

echo "== smoke: autoscale act mode (controlled goodput strictly beats the no-controller baseline)"
AS_DIR="$WORKDIR/chaos/autoscale_1234"
# The chaos leg already ran scenario_autoscale (twice-per-seed controlled arm
# + baseline); the offline CLI must agree that the controller won.
python -m tpu_resiliency.tools.metrics_dump "$AS_DIR/controlled.jsonl" \
    --goodput --baseline "$AS_DIR/baseline.jsonl" | sed 's/^/    /'
python -m tpu_resiliency.tools.metrics_dump "$AS_DIR/controlled.jsonl" \
    --goodput --baseline "$AS_DIR/baseline.jsonl" --format json | \
    python -c "import json,sys; d=json.load(sys.stdin); assert d['ratio_delta']>0, d" \
    || { echo "FAIL: controlled run did not beat the baseline"; exit 1; }
for fam in tpu_autoscale_decisions_total tpu_autoscale_predicted_vs_realized tpu_preemption_rescinded_total; do
    python -m tpu_resiliency.tools.metrics_dump "$AS_DIR/controlled.jsonl" --format prom | \
        grep -q "$fam" || { echo "FAIL: $fam missing from metrics dump"; exit 1; }
done
python -m tpu_resiliency.tools.events_summary "$AS_DIR/controlled.jsonl" \
    --kind autoscale_decision,autoscale_outcome,preemption_rescinded | sed 's/^/    /'

echo "== smoke: autoscale advise mode (live decisions audited on /autoscale without acting)"
AD="$WORKDIR/advise"
mkdir -p "$AD"
cat > "$AD/worker.py" <<'PY'
import os, sys, time
from tpu_resiliency.utils.events import record

stop = sys.argv[1]
rank = int(os.environ.get("RANK", "0"))
i = 0
deadline = time.time() + 90
while not os.path.exists(stop) and time.time() < deadline:
    if rank == 0:
        record("inprocess", "iteration_start", iteration=i)
        if i == 20:
            # An injected straggler signal: the advise-mode controller must
            # turn it into an audited decision without acting on it.
            record("telemetry", "degraded_set", degraded=[1], newly=[1],
                   recovered=[], scores={"0": 1.0, "1": 0.2})
    i += 1
    time.sleep(0.05)
PY
python -m tpu_resiliency.launcher.launch \
    --standalone --nproc-per-node 2 --max-restarts 1 --no-ft-monitors \
    --rdzv-last-call 0.2 --monitor-interval 0.1 --telemetry-port 0 \
    --autoscale advise --warm-spares 1 --warm-spare-preload os \
    --events-file "$AD/events.jsonl" --run-dir "$AD/run" \
    "$AD/worker.py" "$AD/stop" &
AD_PID=$!
python - "$AD" <<'PY'
import json, os, sys, time, urllib.request

ad = sys.argv[1]
port_file = os.path.join(ad, "run", "telemetry.port")
deadline = time.time() + 60
while not os.path.exists(port_file):
    assert time.time() < deadline, "telemetry.port never appeared"
    time.sleep(0.2)
port = int(open(port_file).read().strip())
doc = None
while time.time() < deadline:
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/autoscale", timeout=5).read())
    except OSError:
        time.sleep(0.3)
        continue
    if doc.get("decisions_total", 0) >= 1:
        break
    time.sleep(0.3)
assert doc is not None and doc["schema"] == "tpu-autoscale-1", doc
assert doc["mode"] == "advise", doc
assert doc["decisions_total"] >= 1, f"/autoscale never showed a decision: {doc}"
d = doc["decisions"][0]
assert d["outcome"] == "advised", d  # advise mode must not act
assert d["predicted_delta_s"] is not None, d
print(f"autoscale advise OK: {doc['decisions_total']} decision(s), "
      f"first={d['action']}{d['victims']} predicted={d['predicted_delta_s']}s")
PY
touch "$AD/stop"
wait "$AD_PID"
grep -q '"kind": *"autoscale_decision"' "$AD/events.jsonl" \
    || { echo "FAIL: advise run left no autoscale_decision events"; exit 1; }
grep -q '"kind": *"autoscale_outcome"' "$AD/events.jsonl" \
    || { echo "FAIL: advise run never settled a realized outcome"; exit 1; }

echo "== smoke: watchtower (seeded straggler -> /alerts fires then resolves; offline replay reproduces the live record)"
WT="$WORKDIR/watchtower"
mkdir -p "$WT"
cat > "$WT/worker.py" <<'PY'
import os, sys, time
from tpu_resiliency.utils.events import record

stop = sys.argv[1]
rank = int(os.environ.get("RANK", "0"))
i = 0
deadline = time.time() + 120
while not os.path.exists(stop) and time.time() < deadline:
    if rank == 0:
        record("inprocess", "iteration_start", iteration=i)
    i += 1
    # Seeded straggler: steps 30..37 run ~25x slower, then recover — the
    # step_anomaly early warning must fire on /alerts, then resolve.
    time.sleep(1.2 if 30 <= i < 38 else 0.05)
PY
python -m tpu_resiliency.launcher.launch \
    --standalone --nproc-per-node 2 --max-restarts 1 --no-ft-monitors \
    --rdzv-last-call 0.2 --monitor-interval 0.1 --telemetry-port 0 \
    --alerts on \
    --events-file "$WT/events.jsonl" --run-dir "$WT/run" \
    "$WT/worker.py" "$WT/stop" &
WT_PID=$!
python - "$WT" <<'PY'
import json, os, sys, time, urllib.request

wt = sys.argv[1]
port_file = os.path.join(wt, "run", "telemetry.port")
deadline = time.time() + 90
while not os.path.exists(port_file):
    assert time.time() < deadline, "telemetry.port never appeared"
    time.sleep(0.2)
port = int(open(port_file).read().strip())
doc = None
seen = set()
while time.time() < deadline:
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/alerts", timeout=5).read())
    except OSError:
        time.sleep(0.3)
        continue
    seen = {(h.get("kind"), h.get("rule")) for h in doc.get("history", [])}
    if {("alert_fired", "step_anomaly"),
        ("alert_resolved", "step_anomaly")} <= seen:
        break
    time.sleep(0.3)
assert doc is not None and doc["schema"] == "tpu-alerts-1", doc
assert ("alert_fired", "step_anomaly") in seen, (
    f"straggler never fired step_anomaly: {doc}")
assert ("alert_resolved", "step_anomaly") in seen, (
    f"step_anomaly never resolved after recovery: {doc}")
snap = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/snapshot", timeout=5).read())
assert snap.get("alerts", {}).get("schema") == "tpu-alerts-1", (
    "alerts section missing from /snapshot")
with open(os.path.join(wt, "alerts_live.json"), "w") as f:
    json.dump(doc, f)
fired = next(h for h in doc["history"]
             if (h["kind"], h["rule"]) == ("alert_fired", "step_anomaly"))
print(f"watchtower live OK: step_anomaly fired at {fired['fire_ts']:.3f} "
      f"and resolved; {len(doc['history'])} transition(s) recorded")
PY
touch "$WT/stop"
wait "$WT_PID"
# The live run's alert record must fall out of a cold offline replay of its
# events JSONL — same engine, stream clock, so the live history is a
# byte-exact prefix of the replayed sequence (the doc froze mid-run).
python - "$WT" <<'PY'
import json, os, sys

from tpu_resiliency.telemetry.watchtower import replay

wt = sys.argv[1]
doc = json.load(open(os.path.join(wt, "alerts_live.json")))
recs = []
for line in open(os.path.join(wt, "events.jsonl")):
    line = line.strip()
    if line:
        try:
            recs.append(json.loads(line))
        except ValueError:
            pass
_, seq = replay(recs)
hist = doc["history"]
enc = lambda rows: [json.dumps(r, sort_keys=True) for r in rows]
assert enc(seq[:len(hist)]) == enc(hist), (
    f"offline replay diverged from the live /alerts history:\n"
    f"{enc(seq[:len(hist)])}\n{enc(hist)}")
print(f"watchtower replay OK: live history ({len(hist)} transition(s)) is a "
      f"byte-exact prefix of the {len(seq)}-transition offline replay")
PY
python -m tpu_resiliency.tools.alerts_cli "$WT/events.jsonl" | sed 's/^/    /'
python -m tpu_resiliency.tools.alerts_cli --rules | sed 's/^/    /'
# The chaos campaign's saved stream replays byte-identically through the CLI.
AL_DIR="$WORKDIR/chaos/alerts_1234"
python -m tpu_resiliency.tools.alerts_cli "$AL_DIR/events.jsonl" --json \
    | diff - "$AL_DIR/sequence.jsonl" \
    || { echo "FAIL: tpu-alerts replay diverged from the campaign sequence"; exit 1; }

echo "== smoke: fleet federation (2 concurrent jobs -> fleetd scoreboard; SIGKILL one, fleet endpoints stay up)"
FL="$WORKDIR/fleet"
mkdir -p "$FL"
cat > "$FL/worker.py" <<'PY'
import os, sys, time
from tpu_resiliency.utils.events import record

stop = sys.argv[1]
i = 0
deadline = time.time() + 120
while not os.path.exists(stop) and time.time() < deadline:
    record("inprocess", "iteration_start", iteration=i)
    i += 1
    time.sleep(0.1)
PY
FLEET_PIDS=()
for J in alpha beta; do
    setsid python -m tpu_resiliency.launcher.launch \
        --standalone --nproc-per-node 2 --max-restarts 1 --no-ft-monitors \
        --rdzv-last-call 0.2 --monitor-interval 0.1 \
        --rdzv-id "job-$J" --fleet-dir "$FL/dir" \
        --events-file "$FL/events-$J.jsonl" --run-dir "$FL/run-$J" \
        "$FL/worker.py" "$FL/stop" > "$FL/launcher-$J.log" 2>&1 &
    FLEET_PIDS+=($!)
done
python -m tpu_resiliency.tools.fleetd --fleet-dir "$FL/dir" --port 0 \
    --scrape-interval 1 --snapshot "$FL/fleet.json" > "$FL/fleetd.log" 2>&1 &
FLEETD_PID=$!
python - "$FL" <<'PY'
import json, os, sys, time, urllib.request

fl = sys.argv[1]
port_file = os.path.join(fl, "dir", "fleetd.port")
deadline = time.time() + 60
while not os.path.exists(port_file):
    assert time.time() < deadline, "fleetd.port handshake never appeared"
    time.sleep(0.2)
port = int(open(port_file).read().strip())
doc, rows = None, {}
while time.time() < deadline:
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/goodput", timeout=5).read())
    except OSError:
        time.sleep(0.3)
        continue
    rows = {r["job"]: r["status"] for r in doc.get("jobs", [])}
    if rows.get("job-alpha") == "ok" and rows.get("job-beta") == "ok":
        break
    time.sleep(0.3)
assert rows.get("job-alpha") == "ok" and rows.get("job-beta") == "ok", doc
print(f"fleet scoreboard OK: {rows}")
with open(os.path.join(fl, "fleetd.port.resolved"), "w") as f:
    f.write(str(port))
PY
FLEETD_PORT=$(cat "$FL/fleetd.port.resolved")
# SIGKILL one whole job (launcher + workers): the fleet view must keep
# serving with the dead job marked unreachable, never a non-200.
kill -9 -- "-${FLEET_PIDS[0]}" 2>/dev/null || kill -9 "${FLEET_PIDS[0]}"
python - "$FLEETD_PORT" <<'PY'
import json, sys, time, urllib.request

port = int(sys.argv[1])
deadline = time.time() + 30
rows = {}
while time.time() < deadline:
    slo = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/fleet/slo", timeout=10).read())
    rows = {r["job"]: r["status"] for r in slo.get("jobs", [])}
    if rows.get("job-alpha") == "unreachable":
        break
    time.sleep(0.3)
assert rows.get("job-alpha") == "unreachable", rows
assert rows.get("job-beta") == "ok", rows
for ep in ("/fleet/metrics", "/fleet/goodput", "/fleet/slo",
           "/fleet/incidents", "/fleet/hangz", "/fleet/alerts",
           "/fleet/snapshot"):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{ep}", timeout=10) as r:
        assert r.status == 200, (ep, r.status)
# The cross-job alert feed degrades the dead job to a row, never a non-200.
al = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/fleet/alerts", timeout=10).read())
assert al["schema"] == "tpu-fleet-alerts-1", al
al_rows = {r["job"]: r["status"] for r in al.get("jobs", [])}
assert al_rows.get("job-alpha") == "unreachable", al_rows
assert al_rows.get("job-beta") == "ok", al_rows
assert "job-alpha" in (al.get("unreachable") or []), al
prom = urllib.request.urlopen(
    f"http://127.0.0.1:{port}/fleet/metrics", timeout=10).read().decode()
assert 'job="job-beta"' in prom, prom[:2000]
assert "tpu_fleet_jobs" in prom and "tpu_fleet_scrape_seconds" in prom, prom[:2000]
assert 'tpu_fleet_scrape_errors_total{job="job-alpha"}' in prom, prom[:2000]
print("fleet kill leg OK: job-alpha unreachable, all /fleet/* endpoints 200")
PY
touch "$FL/stop"
# The persisted snapshot renders offline, and --job slices the dead job's
# stamped stream back out of its events file.
python -m tpu_resiliency.tools.fleet_cli scoreboard --snapshot "$FL/fleet.json" | sed 's/^/    /'
python -m tpu_resiliency.tools.fleet_cli slo --snapshot "$FL/fleet.json" | sed 's/^/    /'
python -m tpu_resiliency.tools.events_summary "$FL/events-beta.jsonl" \
    --job job-beta --no-timeline | sed 's/^/    /'
python -m tpu_resiliency.tools.metrics_dump "$FL/events-beta.jsonl" \
    --job job-beta --format prom | grep -q "tpu_events_total" \
    || { echo "FAIL: --job slice lost the job's own events"; exit 1; }
kill "$FLEETD_PID" 2>/dev/null || true
kill -- "-${FLEET_PIDS[1]}" 2>/dev/null || kill "${FLEET_PIDS[1]}" 2>/dev/null || true
wait "${FLEET_PIDS[1]}" 2>/dev/null || true
wait "$FLEETD_PID" 2>/dev/null || true

echo "== smoke: fleet scrape scaling (bench --smoke: sub-linear + SIGKILL containment)"
python scripts/bench_fleet.py --smoke

echo "smoke_observability: PASS ($WORKDIR)"
