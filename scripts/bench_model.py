"""Flagship-model throughput on the local accelerator: tokens/sec for the
Llama-style transformer's full train step (fwd + bwd + adamw), bf16 activations.

Not the driver's headline metric (that's bench.py's telemetry hot loop) — this
validates the model/parallelism stack on real hardware and gives the resiliency
overhead a denominator: a telemetry push at ~0.03 ms/step is noise against a real
step. Prints one JSON line.

    python scripts/bench_model.py [--layers 8] [--d-model 1024] [--batch 8] [--seq 1024]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--d-ff", type=int, default=2816)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.models import transformer as tfm
    from tpu_resiliency.platform.device import apply_platform_env

    apply_platform_env()

    cfg = tfm.TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.heads,
        n_kv_heads=args.kv_heads,
        d_ff=args.d_ff,
        max_seq_len=args.seq,
    )
    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}", file=sys.stderr)

    train_step, init_opt = tfm.make_train_step(cfg)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt_state = jax.jit(init_opt)(params)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (args.batch, args.seq)), jnp.int32)

    step = jax.jit(train_step, donate_argnums=(0, 1))
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, tokens)
    jax.block_until_ready(loss)
    compile_s = time.perf_counter() - t0

    # Device-true per-step time via the framework's profiler: wall-clock loops
    # under-report ~500x on remote-dispatch runtimes (BASELINE.md measurement-
    # integrity note).
    from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

    prof = DeviceTimeProfiler()
    with prof:
        for _ in range(args.iters):
            params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
    per_step = None
    for name, st in prof.get_stats().items():
        if "train_step" in name:
            per_step = st["med"]
    if per_step is None:
        # No device plane (CPU simulation): fall back to blocking wall clock.
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, opt_state, loss = step(params, opt_state, tokens)
            loss.block_until_ready()
        per_step = (time.perf_counter() - t0) / args.iters
    tokens_per_s = args.batch * args.seq / per_step

    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    print(
        json.dumps(
            {
                "metric": (
                    f"transformer train-step throughput ({n_params / 1e6:.0f}M params, "
                    f"{args.layers}L x {args.d_model}d, batch {args.batch} x seq "
                    f"{args.seq}, bf16, compile {compile_s:.1f}s)"
                ),
                "value": round(tokens_per_s, 1),
                "unit": "tokens/s",
                "ms_per_step": round(per_step * 1e3, 2),
                "final_loss": round(float(loss), 4),
                "backend": jax.default_backend(),
                "mfu_vs_v5e_peak": round(
                    # 6*N*tokens/s FLOPs vs v5e bf16 peak 197 TFLOP/s — only
                    # meaningful when backend == tpu.
                    6 * n_params * tokens_per_s / 197e12, 4
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
