from tpu_resiliency.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)

__all__ = ["TransformerConfig", "forward", "init_params", "loss_fn", "make_train_step"]
