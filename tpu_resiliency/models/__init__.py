from tpu_resiliency.models import moe
from tpu_resiliency.models.moe import MoEConfig
from tpu_resiliency.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)

__all__ = [
    "MoEConfig",
    "TransformerConfig",
    "forward",
    "init_params",
    "loss_fn",
    "make_train_step",
    "moe",
]
