"""Mixture-of-experts model family: top-k routed experts, expert-parallel over ``ep``.

The second model family exercising the resiliency framework (the first is the dense
Llama-style ``models/transformer.py``; the reference itself ships no model code —
SURVEY.md §2.7 checklist — these exist so the framework is proven against real sharded
workloads). Built TPU-first:

- **Static shapes everywhere.** Routing uses the GShard/Switch dense-dispatch
  formulation: top-k gates → capacity-bounded one-hot dispatch/combine tensors →
  batched einsums over the expert dimension. No sorting networks, no dynamic
  gather/scatter — everything lowers to MXU-sized batched matmuls.
- **Expert parallelism is a sharding, not code.** Expert weights carry a leading
  ``[E]`` axis sharded over the mesh's ``ep`` axis (``parallel/mesh.py``
  ``moe_param_specs``); the dispatch einsum's contraction over tokens/experts makes
  XLA insert the token all-to-all over ICI. The model code never names a collective.
- **Scan-stacked layers** like the dense model: one trace of the layer body, with the
  router aux (load-balance) loss accumulated through the scan carry.

Every layer is an MoE layer (Mixtral-style); attention is reused verbatim from the
dense model (``transformer._attn_block``), so ring attention over ``sp`` composes.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from tpu_resiliency.models import transformer as tfm


@dataclasses.dataclass(frozen=True)
class MoEConfig(tfm.TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.5
    router_aux_weight: float = 1e-2

    @staticmethod
    def tiny(**kw) -> "MoEConfig":
        base = dict(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, n_experts=4, top_k=2,
        )
        base.update(kw)
        return MoEConfig(**base)

    def capacity(self, seq_len: int) -> int:
        """Per-expert token capacity for one batch row (static)."""
        cap = int(math.ceil(self.top_k * seq_len * self.capacity_factor / self.n_experts))
        return max(cap, 1)


def init_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    """Dense-model pytree with the per-layer MLP replaced by router + [E]-stacked
    experts. The dense MLP weights are never materialized (at scale they would
    transiently double the parameter memory next to the expert stacks)."""
    base = tfm.init_params(rng, cfg, with_mlp=False)
    d, f, L, E = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.n_experts
    kr, kg, ku, kd = jax.random.split(jax.random.fold_in(rng, 7), 4)

    def dense_init(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)

    layers = dict(base["layers"])
    layers["w_router"] = dense_init(kr, (L, d, E), d)
    layers["we_gate"] = dense_init(kg, (L, E, d, f), d)
    layers["we_up"] = dense_init(ku, (L, E, d, f), d)
    layers["we_down"] = dense_init(kd, (L, E, f, d), f)
    base["layers"] = layers
    return base


def _route(cfg: MoEConfig, y: jax.Array, w_router: jax.Array):
    """Top-k routing with per-batch-row capacity.

    y: [B, T, D] → dispatch [B, T, E, C] (0/1), combine [B, T, E, C] (gates),
    aux (scalar load-balance loss, Switch-style fraction·probability product).
    """
    B, T, _ = y.shape
    E, K, C = cfg.n_experts, cfg.top_k, cfg.capacity(T)

    logits = (y.astype(jnp.float32) @ w_router.astype(jnp.float32))  # [B, T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, K)  # [B, T, K]
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    # First-come-first-served capacity: flatten (T, K) token-major so earlier
    # tokens (and higher-ranked choices) win slots, as in the GShard formulation.
    mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B, T, K, E]
    flat = mask.reshape(B, T * K, E)
    pos = jnp.cumsum(flat, axis=1) - flat  # slot index if admitted
    flat = flat * (pos < C)
    pos_in_expert = (pos * flat).sum(-1).astype(jnp.int32)  # [B, T*K]
    admitted_gates = gates.reshape(B, T * K) * flat.sum(-1)

    dispatch = flat[..., None] * jax.nn.one_hot(pos_in_expert, C, dtype=jnp.float32)[:, :, None, :]
    combine = admitted_gates[..., None, None] * dispatch  # [B, T*K, E, C]
    dispatch = dispatch.reshape(B, T, K, E, C).sum(2)
    combine = combine.reshape(B, T, K, E, C).sum(2)

    # Load-balance aux: E * mean_e(fraction of tokens routed to e * mean router prob of e).
    frac = mask.reshape(B, T * K, E).mean(axis=(0, 1)) * K  # fraction per expert
    mean_prob = probs.mean(axis=(0, 1))
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def _moe_block(cfg: MoEConfig, x: jax.Array, lp: dict):
    """Routed SwiGLU experts with residual. Expert weights [E, D, F] shard over ``ep``;
    the ``ebcd``-shaped dispatch/expert einsums are where XLA places the all-to-all."""
    y = tfm.rms_norm(x, lp["mlp_norm"])
    dispatch, combine, aux = _route(cfg, y, lp["w_router"])
    d, c = dispatch.astype(y.dtype), combine.astype(y.dtype)

    expert_in = jnp.einsum("btec,btd->ebcd", d, y)  # [E, B, C, D]
    gate = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", expert_in, lp["we_gate"].astype(y.dtype)))
    up = jnp.einsum("ebcd,edf->ebcf", expert_in, lp["we_up"].astype(y.dtype))
    out = jnp.einsum("ebcf,efd->ebcd", gate * up, lp["we_down"].astype(y.dtype))
    y_out = jnp.einsum("btec,ebcd->btd", c, out)
    return x + y_out, aux


def _moe_layer(cfg: MoEConfig, x: jax.Array, lp: dict, cos, sin, attn_fn):
    x = tfm._attn_block(cfg, x, lp, cos, sin, attn_fn)
    return _moe_block(cfg, x, lp)


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: MoEConfig,
    *,
    attn_fn=None,
    position_offset: int = 0,
):
    """tokens [B, T] → (logits [B, T, V] float32, aux loss scalar)."""
    attn_fn = tfm.adapt_attn_fn(attn_fn, position_offset)
    x = params["embed"].astype(cfg.dtype)[tokens]
    cos, sin = tfm.rope_tables(cfg, tokens.shape[1], position_offset)

    def body(carry, lp):
        x, aux = carry
        x, layer_aux = _moe_layer(cfg, x, lp, cos, sin, attn_fn)
        return (x, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = tfm.rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(x.dtype)).astype(jnp.float32)
    return logits, aux / cfg.n_layers


def loss_fn(params: dict, tokens: jax.Array, cfg: MoEConfig, **kw) -> jax.Array:
    logits, aux = forward(params, tokens, cfg, **kw)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    return tfm.token_nll(logits, targets).mean() + cfg.router_aux_weight * aux


def make_train_step(cfg: MoEConfig, optimizer=None, attn_fn=None):
    """(train_step, init_opt_state) — jit-ready, same contract as the dense model's."""
    return tfm.make_train_step_from_loss(
        lambda params, tokens: loss_fn(params, tokens, cfg, attn_fn=attn_fn), optimizer
    )
