"""Flagship model: a Llama-style decoder-only transformer, pure JAX, mesh-shardable.

The resiliency framework's exercise workload (the reference exercises NVRx against
NeMo/Lightning Llama-3 jobs, ``tests/ptl_resiliency/func/nemo20/``). Built TPU-first:

- parameters are a plain pytree with stacked layer weights, so the layer stack runs as
  one ``lax.scan`` (single trace/compile per layer body, MXU-sized matmuls),
- bfloat16 activations / float32 params + optimizer, RoPE, GQA, SwiGLU, RMSNorm,
- shardable over the canonical (dp, tp, sp) mesh via ``parallel/mesh.py`` specs; with
  ``sp > 1`` attention runs as ring attention over the sequence axis
  (``parallel/ring_attention.py``),
- no Python control flow on data inside jit; static shapes throughout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1376
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def tiny(**kw) -> "TransformerConfig":
        base = dict(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128,
        )
        base.update(kw)
        return TransformerConfig(**base)

    @staticmethod
    def llama3_8b() -> "TransformerConfig":
        return TransformerConfig(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            d_ff=14336, max_seq_len=8192, rope_theta=500000.0,
        )


def init_params(rng: jax.Array, cfg: TransformerConfig, *, with_mlp: bool = True) -> dict:
    """Parameter pytree with layer weights stacked on a leading [L] axis.

    ``with_mlp=False`` skips the dense SwiGLU weights (the MoE family replaces
    them with expert stacks and must not materialize both)."""
    k_embed, k_layers, k_head = jax.random.split(rng, 3)
    d, h, hkv, dh, f, L = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers,
    )

    def norm_init(*shape):
        return jnp.ones(shape, jnp.float32)

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in))

    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn_norm": norm_init(L, d),
        "wq": dense_init(ks[0], (L, d, h * dh), d),
        "wk": dense_init(ks[1], (L, d, hkv * dh), d),
        "wv": dense_init(ks[2], (L, d, hkv * dh), d),
        "wo": dense_init(ks[3], (L, h * dh, d), h * dh),
        "mlp_norm": norm_init(L, d),
    }
    if with_mlp:
        layers["w_gate"] = dense_init(ks[4], (L, d, f), d)
        layers["w_up"] = dense_init(ks[5], (L, d, f), d)
        layers["w_down"] = dense_init(ks[6], (L, f, d), f)
    return {
        "embed": dense_init(k_embed, (cfg.vocab_size, d), d),
        "layers": layers,
        "final_norm": norm_init(d),
        "lm_head": dense_init(k_head, (d, cfg.vocab_size), d),
    }


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(x.dtype)


def rope_tables(cfg: TransformerConfig, seq_len: int, offset: int = 0):
    dh = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, dh, 2, jnp.float32) / dh))
    pos = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    angles = pos[:, None] * inv_freq[None, :]  # [T, dh/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, T, H, dh]; cos/sin: [T, dh/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _attention(q, k, v, causal_offset: int = 0):
    """Plain causal attention. q: [B, T, H, dh]; k/v: [B, T, Hkv, dh] with
    H % Hkv == 0 (GQA) — query heads are grouped per KV head in the einsum
    itself, so repeated K/V are never materialized in HBM."""
    dh = q.shape[-1]
    b, tq, h, _ = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    qg = q.reshape(b, tq, hkv, h // hkv, dh)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) / np.sqrt(dh)
    qpos = jnp.arange(tq)[:, None] + causal_offset
    kpos = jnp.arange(tk)[None, :]
    mask = qpos >= kpos
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhgqk,bkhd->bqhgd", probs, v).reshape(b, tq, h, dh)


def adapt_attn_fn(attn_fn, causal_offset: int = 0):
    """Resolve the layer-level attention callable from a user override.

    The attention blocks hand ``attn_fn`` GQA-shaped tensors (q ``[B, T, H, dh]``,
    k/v ``[B, T, Hkv, dh]``). The default :func:`_attention` consumes those
    directly — grouped in the einsum, repeated K/V never hit HBM. Custom fns
    (e.g. ring attention) keep their documented pre-repeated-full-heads
    contract, so they are wrapped with the repeat here, at the seam, where the
    repeat happens before any sharding decisions the custom fn makes.

    ``causal_offset`` only applies to the default dense attention; a custom fn
    owns its own position bookkeeping, so combining the two is rejected here
    rather than silently producing a mask anchored at 0."""
    if attn_fn is not None and causal_offset:
        raise ValueError(
            "position_offset is only applied to the default dense attention; "
            "a custom attn_fn must handle positions itself"
        )
    if attn_fn is None:
        return functools.partial(_attention, causal_offset=causal_offset)

    def repeated(q, k, v):
        reps = q.shape[2] // k.shape[2]
        if reps > 1:
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
        return attn_fn(q, k, v)

    return repeated


def _attn_block(cfg: TransformerConfig, x: jax.Array, lp: dict, cos, sin, attn_fn) -> jax.Array:
    """Pre-norm GQA attention with residual; shared by the dense and MoE layers."""
    b, t, d = x.shape
    h, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    y = rms_norm(x, lp["attn_norm"])
    q = (y @ lp["wq"].astype(y.dtype)).reshape(b, t, h, dh)
    k = (y @ lp["wk"].astype(y.dtype)).reshape(b, t, hkv, dh)
    v = (y @ lp["wv"].astype(y.dtype)).reshape(b, t, hkv, dh)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = attn_fn(q, k, v).reshape(b, t, h * dh)
    return x + attn @ lp["wo"].astype(attn.dtype)


def _layer(cfg: TransformerConfig, x: jax.Array, lp: dict, cos, sin, attn_fn) -> jax.Array:
    x = _attn_block(cfg, x, lp, cos, sin, attn_fn)

    # MLP block (SwiGLU)
    y = rms_norm(x, lp["mlp_norm"])
    gate = jax.nn.silu(y @ lp["w_gate"].astype(y.dtype))
    up = y @ lp["w_up"].astype(y.dtype)
    x = x + (gate * up) @ lp["w_down"].astype(y.dtype)
    return x


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    *,
    attn_fn=None,
    position_offset: int = 0,
) -> jax.Array:
    """tokens [B, T] int32 → logits [B, T, V] (float32).

    ``position_offset`` is applied to RoPE and to the DEFAULT dense attention's
    causal mask only; a custom ``attn_fn`` (e.g. ring attention) owns its own
    position bookkeeping, so combining the two is rejected (in
    :func:`adapt_attn_fn`) rather than silently producing a mask anchored
    at 0."""
    attn_fn = adapt_attn_fn(attn_fn, position_offset)
    x = params["embed"].astype(cfg.dtype)[tokens]
    cos, sin = rope_tables(cfg, tokens.shape[1], position_offset)

    def body(x, lp):
        return _layer(cfg, x, lp, cos, sin, attn_fn), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"])
    return (x @ params["lm_head"].astype(x.dtype)).astype(jnp.float32)


def token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-position next-token NLL, ``logsumexp(logits) - logits[target]``.

    Equivalent to gathering from ``log_softmax`` but never materializes the
    ``[B, T, V]`` log-prob tensor — at vocab scale that array dominates the
    step's HBM traffic (B8 x T1024 x V32000 f32 is ~1 GB each way); logsumexp
    reduces to ``[B, T]`` and the backward pass recomputes softmax fused."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - picked


def loss_fn(params: dict, tokens: jax.Array, cfg: TransformerConfig, **kw) -> jax.Array:
    """Next-token cross-entropy over tokens [B, T].

    The forward pass runs on the FULL sequence and the last position's logits are
    dropped afterwards (rather than slicing tokens first): a sequence-sharded
    batch keeps its ``T % sp == 0`` divisibility through attention, and the
    trailing slice is a local no-collective op on the logits.
    """
    logits = forward(params, tokens, cfg, **kw)[:, :-1]
    targets = tokens[:, 1:]
    return token_nll(logits, targets).mean()


def make_train_step_from_loss(bound_loss_fn, optimizer=None):
    """Shared factory behind every model family's ``make_train_step``:
    ``(train_step, init_opt_state)`` from a bound ``loss_fn(params, tokens)``.
    Changes to the training contract (optimizer default, grad transform) live here
    once."""
    import optax

    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)

    def init_opt_state(params):
        return optimizer.init(params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(bound_loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step, init_opt_state


def make_train_step(cfg: TransformerConfig, optimizer=None, attn_fn=None):
    """Returns ``(train_step, init_opt_state)`` — jit-ready pure functions.

    ``attn_fn`` overrides the dense attention (e.g.
    :func:`~tpu_resiliency.parallel.ring_attention.make_ring_attn_fn` for a
    sequence-sharded mesh)."""
    return make_train_step_from_loss(
        lambda params, tokens: loss_fn(params, tokens, cfg, attn_fn=attn_fn), optimizer
    )
