"""Report objects + straggler identification over the on-device scoring results.

The user-facing contract mirrors the reference's ``straggler/reporting.py``:
``Report`` with relative/individual per-section scores and per-rank perf scores, and
``identify_stragglers`` thresholding (default 0.75, ``reporting.py:84-151``) — but the
numbers are produced by the fused device pipeline in ``telemetry/scoring.py`` rather
than host-side loops, and the report additionally carries robust-z and EWMA columns.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from tpu_resiliency.telemetry import scoring


@dataclasses.dataclass(frozen=True)
class StragglerId:
    """One flagged rank (reference ``reporting.py`` StragglerId)."""

    rank: int
    score: float
    z: float = float("nan")
    host: Optional[str] = None

    def __str__(self) -> str:
        host = f" host={self.host}" if self.host else ""
        return f"rank={self.rank}{host} score={self.score:.3f} z={self.z:+.2f}"


@dataclasses.dataclass
class Stragglers:
    """Result of ``Report.identify_stragglers``."""

    by_perf: frozenset[StragglerId]
    by_section: dict[str, frozenset[StragglerId]]

    @property
    def any(self) -> bool:
        return bool(self.by_perf) or any(self.by_section.values())


@dataclasses.dataclass
class Report:
    """One scoring round's results, as seen by one rank.

    ``perf_scores`` / ``z_scores`` / ``ewma_scores`` cover every rank when generated
    with ``gather_on_rank0``-style global visibility (the device pipeline always has
    the global matrix, so unlike the reference there is no extra gather cost).
    """

    rank: int
    world_size: int
    iteration: int
    section_names: tuple[str, ...]
    # this rank's per-section scores
    relative_section_scores: dict[str, float]
    individual_section_scores: dict[str, float]
    # global per-rank columns (None when running local-only)
    perf_scores: Optional[dict[int, float]] = None
    z_scores: Optional[dict[int, float]] = None
    ewma_scores: Optional[dict[int, float]] = None
    # per-rank per-section relative scores, [R, S], optional global view
    global_section_scores: Optional[np.ndarray] = None
    rank_to_host: Optional[dict[int, str]] = None

    def identify_stragglers(
        self,
        perf_threshold: float = scoring.DEFAULT_THRESHOLD,
        section_threshold: float = scoring.DEFAULT_THRESHOLD,
        z_threshold: float = scoring.DEFAULT_Z_THRESHOLD,
    ) -> Stragglers:
        """Flag ranks whose perf score is below threshold OR whose robust-z is an
        outlier, and per-section slow ranks (reference ``identify_stragglers``,
        ``reporting.py:84-151``, extended with the z criterion)."""
        by_perf = set()
        if self.perf_scores:
            for r, s in self.perf_scores.items():
                z = (self.z_scores or {}).get(r, float("nan"))
                if s < perf_threshold or (not np.isnan(z) and z < -z_threshold):
                    by_perf.add(
                        StragglerId(r, s, z, (self.rank_to_host or {}).get(r))
                    )
        by_section: dict[str, frozenset] = {}
        if self.global_section_scores is not None:
            for j, name in enumerate(self.section_names):
                col = self.global_section_scores[:, j]
                flagged = {
                    StragglerId(
                        int(r),
                        float(col[r]),
                        host=(self.rank_to_host or {}).get(int(r)),
                    )
                    for r in np.nonzero(col < section_threshold)[0]
                }
                if flagged:
                    by_section[name] = frozenset(flagged)
        return Stragglers(by_perf=frozenset(by_perf), by_section=by_section)


class ReportGenerator:
    """Stateful scorer: carries EWMA and historical-min across rounds.

    Operates on the global telemetry matrix (``[R, S, W]`` windows or precomputed
    ``[R, S]`` medians+weights) and emits :class:`Report` objects. The device pipeline
    runs entirely under jit; only the final small score vectors are pulled to host to
    build the report (reference analogue: ``ReportGenerator.generate_report``,
    ``reporting.py:421``).
    """

    def __init__(
        self,
        world_size: int,
        max_signals: int,
        *,
        perf_threshold: float = scoring.DEFAULT_THRESHOLD,
        z_threshold: float = scoring.DEFAULT_Z_THRESHOLD,
        ewma_alpha: float = scoring.DEFAULT_EWMA_ALPHA,
        use_pallas: bool = False,
        rank_to_host: Optional[dict[int, str]] = None,
    ):
        import jax.numpy as jnp

        self.world_size = world_size
        self.max_signals = max_signals
        self.perf_threshold = perf_threshold
        self.z_threshold = z_threshold
        self.ewma_alpha = ewma_alpha
        self.use_pallas = use_pallas
        self.rank_to_host = rank_to_host
        self.iteration = 0
        self._ewma = jnp.ones((world_size,))
        self._hist_min = jnp.full((world_size, max_signals), jnp.inf)

    def reset(self) -> None:
        import jax.numpy as jnp

        self._ewma = jnp.ones((self.world_size,))
        self._hist_min = jnp.full((self.world_size, self.max_signals), jnp.inf)

    def _hist_slice(self, s: int):
        return self._hist_min[:, :s]

    def _carry(self, res: scoring.TelemetryScores, s: int) -> None:
        self._ewma = res.ewma
        self._hist_min = self._hist_min.at[:, :s].set(res.historical_min)
        self.iteration += 1

    def score(self, data, counts) -> scoring.TelemetryScores:
        """Run one scoring round on ``data [R,S,W]``/``counts [R,S]`` (device arrays)."""
        s = data.shape[1]
        mw = None
        if self.use_pallas:
            from tpu_resiliency.ops.scoring_pallas import fused_median_weights

            mw = fused_median_weights(data, counts)
        if mw is None:
            res = scoring.score_round_jit(
                data,
                counts,
                self._ewma,
                self._hist_slice(s),
                threshold=self.perf_threshold,
                z_threshold=self.z_threshold,
                alpha=self.ewma_alpha,
            )
        else:
            res = scoring.score_round(
                data,
                counts,
                self._ewma,
                self._hist_slice(s),
                threshold=self.perf_threshold,
                z_threshold=self.z_threshold,
                alpha=self.ewma_alpha,
                medians_and_weights=mw,
            )
        self._carry(res, s)
        return res

    def score_summary(self, medians, weights, counts) -> scoring.TelemetryScores:
        """Score precomputed per-(rank, signal) ``medians``/``weights`` summaries
        (the store-aggregated multi-host path; window reduction already done).
        One compiled program per shape (``score_summary_jit``) — eager dispatch
        here cost ~350 ms/report over a remote-dispatch backend."""
        s = medians.shape[1]
        res = scoring.score_summary_jit(
            medians,
            weights,
            counts,
            self._ewma,
            self._hist_slice(s),
            threshold=self.perf_threshold,
            z_threshold=self.z_threshold,
            alpha=self.ewma_alpha,
        )
        self._carry(res, s)
        return res

    def generate_summary_report(
        self, medians, weights, counts, section_names, *, rank: int = 0
    ) -> Report:
        res = self.score_summary(medians, weights, counts)
        return self._materialize(res, section_names, rank)

    def generate_report(
        self, data, counts, section_names, *, rank: int = 0
    ) -> Report:
        """Score and materialize a :class:`Report` for ``rank``."""
        res = self.score(data, counts)
        return self._materialize(res, section_names, rank)

    def _materialize(self, res: scoring.TelemetryScores, section_names, rank: int) -> Report:
        host = scoring.scores_to_host(res)
        section = np.asarray(host.section_scores)
        indiv = np.asarray(host.individual_section_scores)
        perf = np.asarray(host.perf)
        z = np.asarray(host.z)
        ewma = np.asarray(host.ewma)
        names = tuple(section_names)
        s = len(names)
        return Report(
            rank=rank,
            world_size=self.world_size,
            iteration=self.iteration,
            section_names=names,
            relative_section_scores={n: float(section[rank, j]) for j, n in enumerate(names)},
            individual_section_scores={n: float(indiv[rank, j]) for j, n in enumerate(names)},
            perf_scores={r: float(perf[r]) for r in range(self.world_size)},
            z_scores={r: float(z[r]) for r in range(self.world_size)},
            ewma_scores={r: float(ewma[r]) for r in range(self.world_size)},
            global_section_scores=section[:, :s],
            rank_to_host=self.rank_to_host,
        )
