"""Mesh-sharded, device-resident telemetry: the north-star ingestion + scoring path.

The reference aggregates straggler telemetry by packing host dicts into tensors and
running ``all_reduce``/``gather`` through NCCL with Python pack/unpack loops on every
report (``straggler/reporting.py:255-296,338-419``); round 1 of this framework still
gathered pickled summaries through the coordination store one rank at a time. This
module is the replacement: telemetry lives in HBM as a window-major ``[W, R, S]``
ring array **sharded over a mesh axis** (each device owns its ranks' rows), is appended to from
inside the jitted train step (donated carry — no host round-trip per step), and is
scored by the fused pipeline under ``jax.shard_map`` where the cross-rank reductions
are XLA collectives over ICI (``telemetry/scoring.py``). Host Python touches the data
exactly once per *report* — pulling the final [R]-sized score vectors to build a
:class:`~tpu_resiliency.telemetry.reporting.Report`.

Usage in a train loop::

    mt = MeshTelemetry(mesh, axis="dp", n_ranks=R, signal_names=("step", "ckpt"))
    tstate = mt.init_state()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(tstate, params, batch):
        ...
        tstate = mt.push(tstate, jnp.stack([step_ms, ckpt_ms], -1))  # in-jit
        return tstate, params, loss

    ...every report interval...
    tstate, report = mt.generate_report(tstate)   # one device->host transfer

Multi-host: every process holds the shard rows of its own local devices (standard JAX
global-array semantics), so "publishing" a host-measured timing means writing it into
the local shard of the next ``push`` values — the cross-host exchange happens inside
the compiled scoring program, not through a KV server.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np

from tpu_resiliency.telemetry import scoring
from tpu_resiliency.telemetry.reporting import Report

DEFAULT_WINDOW = 32


@dataclasses.dataclass
class TelemetryState:
    """The device-resident carry: rings + scoring state, sharded over the rank axis.

    Ring layout is ``[W, R, S]`` (window-major): one push writes the contiguous
    ``[1, R, S]`` slab at the cursor via ``dynamic_update_slice`` — O(R·S) bytes
    touched in-place on the donated buffer, where an ``[R, S, W]`` one-hot scatter
    re-materialized the whole O(R·S·W) ring every step (the round-2 push cost).
    The scorer consumes ``[R, S, W]``; the transpose happens once per *report*,
    amortized to noise."""

    data: Any  # f32 [W, R, S] timing windows, window-major
    counts: Any  # i32 [R, S] valid samples per window
    cursor: Any  # i32 [] scalar ring write position (ranks advance in lockstep)
    ewma: Any  # f32 [R] smoothed perf score, carried across reports
    hist_min: Any  # f32 [R, S] rank-historical best medians


def _register() -> None:
    import jax

    try:
        jax.tree_util.register_pytree_node(
            TelemetryState,
            lambda s: ((s.data, s.counts, s.cursor, s.ewma, s.hist_min), None),
            lambda _, c: TelemetryState(*c),
        )
    except ValueError:
        pass


_register()


class MeshTelemetry:
    """Owner of a sharded telemetry state and its compiled push/score programs.

    ``n_ranks`` is the number of telemetry rows (typically one per worker rank or one
    per device) and must divide evenly over ``mesh.shape[axis]``. Scores, EWMA, and
    historical minima carry across reports inside the state itself, so the whole
    report round is one compiled program: score → reset rings → new state.
    """

    def __init__(
        self,
        mesh,
        axis: str,
        *,
        n_ranks: Optional[int] = None,
        signal_names: Sequence[str] = ("step",),
        window: int = DEFAULT_WINDOW,
        threshold: float = scoring.DEFAULT_THRESHOLD,
        z_threshold: float = scoring.DEFAULT_Z_THRESHOLD,
        ewma_alpha: float = scoring.DEFAULT_EWMA_ALPHA,
        rank_to_host: Optional[dict[int, str]] = None,
        use_pallas: Optional[bool] = None,
    ):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis_size = mesh.shape[axis]
        self.mesh = mesh
        self.axis = axis
        self.n_ranks = int(n_ranks if n_ranks is not None else axis_size)
        if self.n_ranks % axis_size:
            raise ValueError(
                f"n_ranks={self.n_ranks} must divide over mesh axis "
                f"{axis!r} (size {axis_size})"
            )
        self.signal_names = tuple(signal_names)
        self.n_signals = len(self.signal_names)
        self.window = int(window)
        self.threshold = threshold
        self.z_threshold = z_threshold
        self.ewma_alpha = ewma_alpha
        self.rank_to_host = rank_to_host
        self.iteration = 0

        if use_pallas is None:
            # The fused Pallas window reduction beats XLA's sort lowering 2x on
            # TPU at the default window (device-true measurement, BASELINE.md);
            # other backends can't run the kernel, and the kernel tiles the
            # rank axis so incompatible per-shard rank counts fall back to the
            # shape-generic XLA path. Windows past the O(W²) crossover
            # (scoring_pallas.DEFAULT_MAX_WINDOW) auto-select the radix kernel
            # once it is device-measured/opted-in ($TPU_RESILIENCY_PALLAS_RADIX),
            # else stay on XLA.
            from tpu_resiliency.ops.scoring_pallas import pallas_supported

            use_pallas = (
                jax.default_backend() == "tpu"
                and pallas_supported(
                    self.n_ranks // axis_size,
                    window=self.window,
                    signals=self.n_signals,
                )
            )
        self.use_pallas = use_pallas
        self._row_sharding = NamedSharding(mesh, P(axis))
        self._scorer = scoring.make_sharded_scorer(
            mesh,
            axis,
            threshold=threshold,
            z_threshold=z_threshold,
            alpha=ewma_alpha,
            use_pallas=use_pallas,
        )
        self._push = jax.jit(self._push_impl, donate_argnums=(0,))
        self._score_reset = jax.jit(self._score_reset_impl, donate_argnums=(0,))
        # Report materialization must read every rank's scores from host Python, but
        # scorer outputs are sharded P(axis) — in a multi-process job each process
        # only holds its own rows and np.asarray on the rest is an error. This
        # jitted identity re-lays the score pytree out fully replicated (XLA inserts
        # the all-gather), making the report a legal single host transfer anywhere.
        replicated = NamedSharding(mesh, P())
        self._replicate = jax.jit(
            lambda s: s,
            out_shardings=scoring.TelemetryScores(*([replicated] * 7)),
        )
        self._summary_scorer = None
        self._summary_state = None  # (ewma [R], hist_min [R, S]) for the summary path

    # -- state lifecycle ---------------------------------------------------

    def init_state(self) -> TelemetryState:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        r, s, w = self.n_ranks, self.n_signals, self.window
        shard = self._row_sharding
        data_shard = NamedSharding(self.mesh, P(None, self.axis))
        replicated = NamedSharding(self.mesh, P())

        def init():
            return TelemetryState(
                data=jnp.zeros((w, r, s), jnp.float32),
                counts=jnp.zeros((r, s), jnp.int32),
                cursor=jnp.zeros((), jnp.int32),
                ewma=jnp.ones((r,), jnp.float32),
                hist_min=jnp.full((r, s), jnp.inf, jnp.float32),
            )

        out_shardings = TelemetryState(data_shard, shard, replicated, shard, shard)
        return jax.jit(init, out_shardings=out_shardings)()

    # -- in-jit ingestion --------------------------------------------------

    @staticmethod
    def _push_impl(state: TelemetryState, values) -> TelemetryState:
        import jax.numpy as jnp
        from jax import lax

        w = state.data.shape[0]
        values = jnp.asarray(values, state.data.dtype)
        idx = state.cursor % w
        # Contiguous [1, R, S] slab write at the cursor: with the donated carry this
        # lowers to an in-place dynamic-update-slice touching O(R·S) bytes; the
        # start offset is only in the unsharded window axis, so the update shards
        # over the rank axis with no collectives and no host sync.
        return TelemetryState(
            data=lax.dynamic_update_slice(state.data, values[None], (idx, 0, 0)),
            counts=jnp.minimum(state.counts + 1, w),
            cursor=state.cursor + 1,
            ewma=state.ewma,
            hist_min=state.hist_min,
        )

    def push(self, state: TelemetryState, values) -> TelemetryState:
        """Append one ``[R, S]`` sample row (one measurement per rank per signal).

        Jittable and donated — call it from inside the train step for
        device-computed signals, or standalone for host-measured timings.
        """
        return self._push(state, values)

    # -- scoring -----------------------------------------------------------

    def _score_reset_impl(self, state: TelemetryState):
        import jax.numpy as jnp

        # The scorer consumes [R, S, W]; this transpose is per-report, not per-step,
        # and stays local to each shard (the window axis is unsharded).
        data_rsw = jnp.transpose(state.data, (1, 2, 0))
        scores = self._scorer(data_rsw, state.counts, state.ewma, state.hist_min)
        new_state = TelemetryState(
            data=state.data,  # stale samples are masked by counts=0
            counts=jnp.zeros_like(state.counts),
            cursor=jnp.zeros_like(state.cursor),
            ewma=scores.ewma,
            hist_min=scores.historical_min,
        )
        return new_state, scores

    def score(self, state: TelemetryState):
        """One report round: returns ``(new_state, TelemetryScores)`` — rings reset,
        EWMA/historical-min carried, every output still sharded over the mesh."""
        self.iteration += 1
        return self._score_reset(state)

    # -- multi-host summary path ------------------------------------------

    def _build_summary_scorer(self):
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def body(medians, weights, counts, ewma, hist_min):
            dummy = jnp.zeros(medians.shape + (1,), medians.dtype)
            return scoring.score_round(
                dummy,
                counts,
                ewma,
                hist_min,
                threshold=self.threshold,
                z_threshold=self.z_threshold,
                alpha=self.ewma_alpha,
                medians_and_weights=(medians, weights),
                axis_name=self.axis,
            )

        spec = P(self.axis)
        sharded = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(spec,) * 5,
            out_specs=scoring.TelemetryScores(*([spec] * 7)),
        )
        return jax.jit(sharded)

    def score_local_summary(self, medians, weights, counts):
        """Score per-rank summaries fed process-locally — the multi-host Detector
        path with zero host gathers.

        Each process passes the ``[local_ranks, S]`` median/weight/count rows of the
        ranks it hosts; rows assemble into the global mesh-sharded array with
        ``jax.make_array_from_process_local_data`` (no cross-host transfer — each
        process donates its shard) and the cross-rank reductions run as ICI/DCN
        collectives inside the compiled scoring program. Replaces the reference's
        store/NCCL summary gather (``reporting.py:338-419``). EWMA and historical-min
        for this path are carried as sharded device arrays inside this object.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._summary_scorer is None:
            self._summary_scorer = self._build_summary_scorer()
        r, s = self.n_ranks, self.n_signals
        shard = self._row_sharding
        if self._summary_state is None:
            def init():
                return (
                    jnp.ones((r,), jnp.float32),
                    jnp.full((r, s), jnp.inf, jnp.float32),
                )

            self._summary_state = jax.jit(
                init, out_shardings=(shard, NamedSharding(self.mesh, P(self.axis)))
            )()
        ewma, hist_min = self._summary_state
        to_global = lambda x, dt: jax.make_array_from_process_local_data(  # noqa: E731
            shard, np.ascontiguousarray(x, dtype=dt)
        )
        scores = self._summary_scorer(
            to_global(medians, np.float32),
            to_global(weights, np.float32),
            to_global(counts, np.int32),
            ewma,
            hist_min,
        )
        self._summary_state = (scores.ewma, scores.historical_min)
        self.iteration += 1
        return scores

    # -- report materialization -------------------------------------------

    def generate_report(self, state: TelemetryState, *, rank: int = 0):
        """Score and build a host-side :class:`Report` (the single device→host hop).

        Returns ``(new_state, report)``.
        """
        new_state, scores = self.score(state)
        return new_state, self.materialize(scores, rank=rank)

    def report_from_summary(
        self, medians, weights, counts, *, rank: int = 0,
        signal_names: Optional[Sequence[str]] = None,
    ) -> Report:
        """Multi-host summary round: score process-local rows, build the Report.

        ``signal_names`` overrides the construction-time names (the Detector bridge
        passes the globally-agreed column list, which can be shorter than this
        object's column capacity — the tail columns carry counts=0 and score 1.0).
        """
        scores = self.score_local_summary(medians, weights, counts)
        return self.materialize(scores, rank=rank, signal_names=signal_names)

    def materialize(
        self, scores: scoring.TelemetryScores, *, rank: int = 0,
        signal_names: Optional[Sequence[str]] = None,
    ) -> Report:
        scores = self._replicate(scores)
        host = scoring.scores_to_host(scores)
        section = np.asarray(host.section_scores)
        indiv = np.asarray(host.individual_section_scores)
        perf = np.asarray(host.perf)
        z = np.asarray(host.z)
        ewma = np.asarray(host.ewma)
        names = tuple(signal_names) if signal_names is not None else self.signal_names
        return Report(
            rank=rank,
            world_size=self.n_ranks,
            iteration=self.iteration,
            section_names=names,
            relative_section_scores={
                n: float(section[rank, j]) for j, n in enumerate(names)
            },
            individual_section_scores={
                n: float(indiv[rank, j]) for j, n in enumerate(names)
            },
            perf_scores={r: float(perf[r]) for r in range(self.n_ranks)},
            z_scores={r: float(z[r]) for r in range(self.n_ranks)},
            ewma_scores={r: float(ewma[r]) for r in range(self.n_ranks)},
            global_section_scores=section[:, : len(names)],
            rank_to_host=self.rank_to_host,
        )
