"""On-device telemetry scoring: the north-star pipeline.

Re-implements the *scoring contract* of the reference's ``straggler/reporting.py`` as a
single jittable JAX pipeline over a ``[ranks, signals]`` telemetry matrix, instead of
host-side Python dict/tensor pack-unpack loops + ``all_reduce``/``gather``
(``reporting.py:196-296,338-419``):

- per-signal **relative score** = (min over ranks of the signal's median) / local median
  (reference ``reporting.py:196-217``), in (0, 1], 1.0 = fastest rank;
- **individual score** = rank-historical minimum median / current median
  (reference ``reporting.py:298``);
- per-rank **perf score** = total-time-weighted mean of relative scores over signals the
  rank observed (the reference's GPU score, ``reporting.py:219-253``);
- **robust-z** of perf scores across ranks (z = (x − median) / (1.4826·MAD)) and an
  **EWMA** over report rounds — the anomaly-scoring additions from BASELINE.json's
  north star, which the reference lacks (it only thresholds raw scores);
- **straggler mask** = perf score below threshold (reference default 0.75,
  ``reporting.py:84-151``) or robust-z below −z_threshold.

Two execution modes share this one pipeline:

- **single-program** (``axis_name=None``): the ``[R, ...]`` matrix lives on one chip
  (or is fully replicated) and the cross-rank reductions are plain axis-0 ops in one
  fused XLA program;
- **mesh-sharded** (``axis_name='rank axis'``): the matrix is sharded over a mesh axis
  and the function runs inside ``jax.shard_map`` — the same reductions become XLA
  collectives over ICI (``lax.pmin`` for the reference-min, a tiny ``all_gather`` of
  the [R] perf vector for the median/MAD), replacing the reference's host-side
  ``all_reduce``/``gather`` (``reporting.py:255-296,338-419``) with zero host hops.
  Use :func:`score_round_sharded` to apply it to mesh-sharded arrays.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

EPS = 1e-12
MAD_SCALE = 1.4826  # makes MAD a consistent sigma estimator under normality
# Perf scores live in (0, 1]; when every healthy rank scores identically the MAD
# degenerates to ~0 and float jitter (1e-7-ish) over EPS would z-flag the whole
# fleet. The floor says: deviations under ~3e-3 in score units are never outliers.
MAD_FLOOR = 1e-3
DEFAULT_THRESHOLD = 0.75  # reference identify_stragglers default (reporting.py:84)
DEFAULT_Z_THRESHOLD = 3.0
DEFAULT_EWMA_ALPHA = 0.5


def masked_median(data: jax.Array, counts: jax.Array) -> jax.Array:
    """Median over the last axis, honoring per-row valid-sample counts.

    ``data``: f32 [..., W] ring-buffer windows (insertion order irrelevant);
    ``counts``: i32 [...] number of valid samples in each window (0 ⇒ result inf).

    Invalid slots are sorted to +inf; the median of ``n`` valid samples is the mean of
    elements ``(n-1)//2`` and ``n//2`` of the sorted valid prefix.
    """
    w = data.shape[-1]
    pos = jnp.arange(w, dtype=jnp.int32)
    valid = pos < counts[..., None]
    padded = jnp.where(valid, data, jnp.inf)
    s = jnp.sort(padded, axis=-1)
    lo_idx = jnp.maximum(counts - 1, 0) // 2
    hi_idx = counts // 2
    lo = jnp.take_along_axis(s, lo_idx[..., None], axis=-1)[..., 0]
    hi = jnp.take_along_axis(s, hi_idx[..., None], axis=-1)[..., 0]
    med = 0.5 * (lo + hi)
    return jnp.where(counts > 0, med, jnp.inf)


def masked_total(data: jax.Array, counts: jax.Array) -> jax.Array:
    """Sum over the last axis honoring valid counts (the per-signal time weight)."""
    w = data.shape[-1]
    pos = jnp.arange(w, dtype=jnp.int32)
    valid = pos < counts[..., None]
    return jnp.where(valid, data, 0.0).sum(axis=-1)


def relative_scores(
    medians: jax.Array, valid: jax.Array, axis_name: Optional[str] = None
) -> jax.Array:
    """[R, S] relative scores vs the fastest rank per signal.

    The reference computes the reference-median as an all-reduce MIN over ranks of each
    signal's median (``reporting.py:255-296``); here that is a masked ``min`` along the
    rank axis — lowered to an ICI ``pmin`` collective when the rank axis is sharded
    over a mesh (``axis_name``).
    """
    ref = jnp.min(jnp.where(valid, medians, jnp.inf), axis=0, keepdims=True)
    if axis_name is not None:
        ref = lax.pmin(ref, axis_name)
    scores = ref / jnp.maximum(medians, EPS)
    # Signals nobody measured have ref=inf; signals this rank didn't measure score 1.
    scores = jnp.where(jnp.isfinite(ref), scores, 1.0)
    return jnp.clip(jnp.where(valid, scores, 1.0), 0.0, 1.0)


def individual_scores(
    medians: jax.Array, valid: jax.Array, historical_min: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Rank-local scores vs the rank's own best-ever median (reference
    ``_update_local_min_times``, ``reporting.py:298``). Returns (scores, new_min)."""
    new_min = jnp.where(valid, jnp.minimum(historical_min, medians), historical_min)
    scores = new_min / jnp.maximum(medians, EPS)
    return jnp.clip(jnp.where(valid, scores, 1.0), 0.0, 1.0), new_min


def perf_scores(section_scores: jax.Array, weights: jax.Array, valid: jax.Array) -> jax.Array:
    """[R] per-rank score: total-time-weighted mean over observed signals
    (the reference GPU score, ``reporting.py:219-253``)."""
    w = jnp.where(valid, weights, 0.0)
    denom = jnp.maximum(w.sum(axis=1), EPS)
    return (section_scores * w).sum(axis=1) / denom


def robust_z(x: jax.Array, axis_name: Optional[str] = None) -> jax.Array:
    """Median/MAD z-score along the rank axis.

    The median is not a pairwise reduction, so the sharded path all-gathers the per-
    rank perf vector — R floats over ICI, the one unavoidable full-exchange, and tiny
    (16 KB at 4096 ranks) next to the [R,S,W] telemetry it replaces on the host path.
    """
    full = x if axis_name is None else lax.all_gather(x, axis_name, tiled=True)
    med = jnp.median(full)
    mad = jnp.median(jnp.abs(full - med))
    return (x - med) / jnp.maximum(MAD_SCALE * mad, MAD_FLOOR)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TelemetryScores:
    """Result pytree of one scoring round."""

    section_scores: Any  # f32 [R, S] relative score per signal
    individual_section_scores: Any  # f32 [R, S] vs rank-historical best
    perf: Any  # f32 [R]   weighted per-rank score
    z: Any  # f32 [R]   robust-z of perf across ranks
    ewma: Any  # f32 [R]   smoothed perf score
    straggler: Any  # bool [R]
    historical_min: Any  # f32 [R, S] carried state

    def tree_flatten(self):
        return (
            (
                self.section_scores,
                self.individual_section_scores,
                self.perf,
                self.z,
                self.ewma,
                self.straggler,
                self.historical_min,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


def score_round(
    data: jax.Array,
    counts: jax.Array,
    prev_ewma: jax.Array,
    historical_min: jax.Array,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    z_threshold: float = DEFAULT_Z_THRESHOLD,
    alpha: float = DEFAULT_EWMA_ALPHA,
    medians_and_weights: Optional[tuple[jax.Array, jax.Array]] = None,
    axis_name: Optional[str] = None,
) -> TelemetryScores:
    """The fused scoring pipeline over raw telemetry windows.

    ``data``: f32 [R, S, W] per-rank per-signal timing windows;
    ``counts``: i32 [R, S] valid samples per window;
    ``prev_ewma``: f32 [R] (start with ones);
    ``historical_min``: f32 [R, S] (start with +inf).

    ``medians_and_weights`` short-circuits the reduction stage with precomputed
    ``(medians [R,S], weights [R,S])`` — the hook used by the Pallas kernel path.

    ``axis_name`` marks the rank axis as mesh-sharded: the function must then be
    called inside ``shard_map`` (see :func:`score_round_sharded`), R becomes the
    *local* shard size, and cross-rank reductions ride ICI collectives.
    """
    if medians_and_weights is None:
        medians = masked_median(data, counts)
        weights = masked_total(data, counts)
    else:
        medians, weights = medians_and_weights
    valid = counts > 0
    section = relative_scores(medians, valid, axis_name)
    indiv, new_min = individual_scores(medians, valid, historical_min)
    perf = perf_scores(section, weights, valid)
    z = robust_z(perf, axis_name)
    ewma = alpha * perf + (1.0 - alpha) * prev_ewma
    straggler = (perf < threshold) | (z < -z_threshold)
    return TelemetryScores(
        section_scores=section,
        individual_section_scores=indiv,
        perf=perf,
        z=z,
        ewma=ewma,
        straggler=straggler,
        historical_min=new_min,
    )


@functools.partial(jax.jit, static_argnames=("threshold", "z_threshold", "alpha"))
def score_round_jit(
    data,
    counts,
    prev_ewma,
    historical_min,
    threshold: float = DEFAULT_THRESHOLD,
    z_threshold: float = DEFAULT_Z_THRESHOLD,
    alpha: float = DEFAULT_EWMA_ALPHA,
):
    return score_round(
        data,
        counts,
        prev_ewma,
        historical_min,
        threshold=threshold,
        z_threshold=z_threshold,
        alpha=alpha,
    )


def scores_to_host(res: "TelemetryScores") -> "TelemetryScores":
    """ONE batched device->host transfer of a scores pytree. Report materializers
    must use this instead of per-array np.asarray: each per-array transfer costs a
    full round-trip on remote-dispatch backends (measured 335 ms vs 80 ms per
    report over the TPU tunnel)."""
    return jax.device_get(res)


@functools.partial(jax.jit, static_argnames=("threshold", "z_threshold", "alpha"))
def score_summary_jit(
    medians,
    weights,
    counts,
    prev_ewma,
    historical_min,
    threshold: float = DEFAULT_THRESHOLD,
    z_threshold: float = DEFAULT_Z_THRESHOLD,
    alpha: float = DEFAULT_EWMA_ALPHA,
):
    """One compiled program for the summary path (window reduction already done):
    eager dispatch here costs dozens of small device round-trips per report, which
    dominates report latency on remote-dispatch backends."""
    dummy = jnp.zeros(medians.shape + (1,), medians.dtype)
    return score_round(
        dummy,
        counts,
        prev_ewma,
        historical_min,
        threshold=threshold,
        z_threshold=z_threshold,
        alpha=alpha,
        medians_and_weights=(medians, weights),
    )


@functools.lru_cache(maxsize=16)
def make_sharded_scorer(
    mesh,
    axis: str,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    z_threshold: float = DEFAULT_Z_THRESHOLD,
    alpha: float = DEFAULT_EWMA_ALPHA,
    use_pallas: bool = False,
):
    """Build a jitted scoring fn over a mesh-sharded rank axis. Cached per
    (mesh, axis, thresholds) so per-round callers don't re-trace.

    Input/output arrays are sharded ``P(axis)`` on dim 0; each device holds its own
    ranks' telemetry and the cross-rank reductions lower to collectives over the mesh
    (the north-star replacement for the reference's host gather,
    ``reporting.py:255-296``). Returns ``fn(data, counts, prev_ewma, historical_min)
    -> TelemetryScores`` with every leaf still sharded ``P(axis)``.

    ``use_pallas`` swaps the window reduction (masked median + totals) for the
    fused Pallas kernel, which runs per-shard before the cross-rank collectives —
    measured 2.0x faster than the XLA sort lowering on v5e at 4096x64x32
    (device-true times, BASELINE.md "Pallas verdict").
    """
    from jax.sharding import PartitionSpec as P

    spec = P(axis)
    if use_pallas:
        from tpu_resiliency.ops.scoring_pallas import fused_median_weights

        def body(data, counts, prev_ewma, historical_min):
            mw = fused_median_weights(data, counts)
            return score_round(
                data,
                counts,
                prev_ewma,
                historical_min,
                threshold=threshold,
                z_threshold=z_threshold,
                alpha=alpha,
                medians_and_weights=mw,
                axis_name=axis,
            )

    else:
        body = functools.partial(
            score_round,
            threshold=threshold,
            z_threshold=z_threshold,
            alpha=alpha,
            axis_name=axis,
        )
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=TelemetryScores(*([spec] * 7)),
        # pallas_call outputs carry no varying-mesh-axes metadata, so the vma
        # checker cannot validate the pallas branch.
        check_vma=not use_pallas,
    )
    return jax.jit(sharded)


def score_round_sharded(
    data,
    counts,
    prev_ewma,
    historical_min,
    *,
    mesh,
    axis: str,
    threshold: float = DEFAULT_THRESHOLD,
    z_threshold: float = DEFAULT_Z_THRESHOLD,
    alpha: float = DEFAULT_EWMA_ALPHA,
) -> TelemetryScores:
    """One mesh-sharded scoring round (see :func:`make_sharded_scorer`)."""
    fn = make_sharded_scorer(
        mesh, axis, threshold=threshold, z_threshold=z_threshold, alpha=alpha
    )
    return fn(data, counts, prev_ewma, historical_min)
