"""Fixed-capacity ring buffers for timing samples — host-side and device-resident.

The host ring is the analogue of the reference's C++ ``CircularBuffer<float>`` +
``BufferPool`` feeding CUPTI kernel timings (``straggler/cupti_src/CircularBuffer.h:22-70``,
``BufferPool.h:24-38``). The device ring is the TPU-first redesign: a pytree of arrays
updated *inside* the jitted step function (donated, so updates are in-place in HBM),
letting telemetry accumulate with zero host-side Python until a report boundary
(BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

try:  # native pooled rings + C-side stats (build: python setup.py build_ext --inplace)
    from tpu_resiliency import _ringstats
except ImportError:  # pure-Python fallback below
    _ringstats = None

STAT_KEYS = ("count", "min", "max", "median", "avg", "std", "total")


class SignalRings:
    """``n_rings`` fixed-capacity rings in one block, with per-ring stats.

    The host collector behind the straggler detector: one native ``RingPool``
    (``native/ringstats.c`` — the reference's ``CircularBuffer``/``BufferPool``/
    ``computeStats`` analogue: single contiguous allocation, C-side sort/stats)
    when the extension is built, one numpy block otherwise. Consumers hold
    :class:`RingView` handles (``.view(i)``) so per-signal call sites stay simple
    while storage stays pooled.
    """

    def __init__(self, n_rings: int, capacity: int, native: Optional[bool] = None):
        if n_rings <= 0 or capacity <= 0:
            raise ValueError("n_rings and capacity must be positive")
        self.n_rings = n_rings
        self.capacity = capacity
        use_native = (_ringstats is not None) if native is None else native
        if use_native and _ringstats is None:
            raise RuntimeError("native rings requested but _ringstats is not built")
        self._pool = _ringstats.RingPool(n_rings, capacity) if use_native else None
        if self._pool is None:
            self._buf = np.zeros((n_rings, capacity), dtype=np.float64)
            self._next = np.zeros(n_rings, dtype=np.int64)
            self._count = np.zeros(n_rings, dtype=np.int64)

    @property
    def native(self) -> bool:
        return self._pool is not None

    def view(self, index: int) -> "RingView":
        if not 0 <= index < self.n_rings:
            raise IndexError(f"ring {index} out of range [0, {self.n_rings})")
        return RingView(self, index)

    # -- per-ring operations ------------------------------------------------

    def push(self, i: int, value: float) -> None:
        if self._pool is not None:
            self._pool.push(i, float(value))
            return
        self._buf[i, self._next[i]] = value
        self._next[i] = (self._next[i] + 1) % self.capacity
        self._count[i] = min(self._count[i] + 1, self.capacity)

    def extend(self, i: int, values) -> None:
        values = np.ascontiguousarray(values, dtype=np.float64).ravel()
        if self._pool is not None:
            # Buffer-protocol fast path in C: no per-sample boxing.
            self._pool.push_many(i, values)
            return
        for v in values:
            self.push(i, float(v))

    def count(self, i: int) -> int:
        if self._pool is not None:
            return self._pool.count(i)
        return int(self._count[i])

    def linearize(self, i: int) -> np.ndarray:
        """Samples oldest→newest (reference ``CircularBuffer.linearize()``)."""
        if self._pool is not None:
            return np.frombuffer(self._pool.linearize(i), dtype=np.float64).copy()
        n, head = int(self._count[i]), int(self._next[i])
        if n < self.capacity:
            return self._buf[i, :n].copy()
        return np.concatenate([self._buf[i, head:], self._buf[i, :head]])

    def stats(self, i: int) -> dict[str, float]:
        """One-pass summary: count/min/max/median/avg/std/total (reference
        ``computeStats``, ``CuptiProfiler.cpp:44-74``). Raises on an empty ring."""
        if self._pool is not None:
            return dict(zip(STAT_KEYS, self._pool.stats(i)))
        if self._count[i] == 0:
            raise ValueError("stats of an empty ring")
        arr = self.linearize(i)
        return {
            "count": int(arr.size),
            "min": float(arr.min()),
            "max": float(arr.max()),
            "median": float(np.median(arr)),
            "avg": float(arr.mean()),
            "std": float(arr.std()),
            "total": float(arr.sum()),
        }

    def reset(self, i: int) -> None:
        if self._pool is not None:
            self._pool.reset(i)
            return
        self._next[i] = 0
        self._count[i] = 0

    def reset_all(self) -> None:
        if self._pool is not None:
            self._pool.reset_all()
            return
        self._next[:] = 0
        self._count[:] = 0


class RingView:
    """One signal's handle into a :class:`SignalRings` pool."""

    __slots__ = ("_rings", "_i")

    def __init__(self, rings: SignalRings, index: int):
        self._rings = rings
        self._i = index

    @property
    def capacity(self) -> int:
        return self._rings.capacity

    @property
    def native(self) -> bool:
        return self._rings.native

    def push(self, value: float) -> None:
        self._rings.push(self._i, value)

    def extend(self, values) -> None:
        self._rings.extend(self._i, values)

    def __len__(self) -> int:
        return self._rings.count(self._i)

    def linearize(self) -> np.ndarray:
        return self._rings.linearize(self._i)

    def stats(self) -> dict[str, float]:
        return self._rings.stats(self._i)

    def reset(self) -> None:
        self._rings.reset(self._i)


class HostRingBuffer(RingView):
    """A standalone single ring (a pool of one) — the simple-case API."""

    def __init__(self, capacity: int, native: Optional[bool] = None):
        super().__init__(SignalRings(1, capacity, native=native), 0)


@dataclasses.dataclass
class DeviceRings:
    """Device-resident rings for ``n_signals`` timing streams.

    A pytree ``(data [n_signals, capacity], cursor [], counts [n_signals])`` designed to
    be carried through a jitted train step with donation:

        rings = DeviceRings.create(n_signals=..., capacity=...)
        ...
        rings = rings.push_row(step_durations)        # inside jit

    ``push_row`` writes one sample per signal (a step's timings for every signal at
    once) using a shared cursor — static shapes, no data-dependent control flow, so XLA
    keeps the whole update on device.
    """

    data: Any  # f32 [n_signals, capacity]
    cursor: Any  # i32 []
    counts: Any  # i32 [n_signals]

    @staticmethod
    def create(n_signals: int, capacity: int, dtype=None):
        import jax.numpy as jnp

        dtype = dtype or jnp.float32
        return DeviceRings(
            data=jnp.zeros((n_signals, capacity), dtype),
            cursor=jnp.zeros((), jnp.int32),
            counts=jnp.zeros((n_signals,), jnp.int32),
        )

    def push_row(self, values) -> "DeviceRings":
        import jax
        import jax.numpy as jnp

        values = jnp.asarray(values, self.data.dtype).reshape(-1, 1)
        capacity = self.data.shape[1]
        idx = self.cursor % capacity
        data = jax.lax.dynamic_update_slice(self.data, values, (0, idx))
        return DeviceRings(
            data=data,
            cursor=self.cursor + 1,
            counts=jnp.minimum(self.counts + 1, capacity),
        )

    def valid_mask(self):
        """[n_signals, capacity] bool — True where a real sample exists."""
        import jax.numpy as jnp

        pos = jnp.arange(self.data.shape[1])[None, :]
        return pos < self.counts[:, None]

    def reset(self) -> "DeviceRings":
        import jax.numpy as jnp

        return DeviceRings(
            data=self.data,  # stale data is masked out by counts
            cursor=jnp.zeros((), jnp.int32),
            counts=jnp.zeros_like(self.counts),
        )


def register_pytrees() -> None:
    import jax

    try:
        jax.tree_util.register_pytree_node(
            DeviceRings,
            lambda r: ((r.data, r.cursor, r.counts), None),
            lambda _, c: DeviceRings(*c),
        )
    except ValueError:
        pass  # already registered


register_pytrees()
