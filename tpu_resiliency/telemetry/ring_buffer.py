"""Fixed-capacity ring buffers for timing samples — host-side and device-resident.

The host ring is the analogue of the reference's C++ ``CircularBuffer<float>`` +
``BufferPool`` feeding CUPTI kernel timings (``straggler/cupti_src/CircularBuffer.h:22-70``,
``BufferPool.h:24-38``). The device ring is the TPU-first redesign: a pytree of arrays
updated *inside* the jitted step function (donated, so updates are in-place in HBM),
letting telemetry accumulate with zero host-side Python until a report boundary
(BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np


class HostRingBuffer:
    """Bounded ring of float samples with O(1) append and linearized readout."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf = np.zeros(capacity, dtype=np.float64)
        self._next = 0
        self._count = 0

    def push(self, value: float) -> None:
        self._buf[self._next] = value
        self._next = (self._next + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)

    def extend(self, values) -> None:
        for v in np.asarray(values, dtype=np.float64).ravel():
            self.push(float(v))

    def __len__(self) -> int:
        return self._count

    def linearize(self) -> np.ndarray:
        """Samples oldest→newest (reference ``CircularBuffer.linearize()``)."""
        if self._count < self.capacity:
            return self._buf[: self._count].copy()
        return np.concatenate([self._buf[self._next :], self._buf[: self._next]])

    def reset(self) -> None:
        self._next = 0
        self._count = 0


@dataclasses.dataclass
class DeviceRings:
    """Device-resident rings for ``n_signals`` timing streams.

    A pytree ``(data [n_signals, capacity], cursor [], counts [n_signals])`` designed to
    be carried through a jitted train step with donation:

        rings = DeviceRings.create(n_signals=..., capacity=...)
        ...
        rings = rings.push_row(step_durations)        # inside jit

    ``push_row`` writes one sample per signal (a step's timings for every signal at
    once) using a shared cursor — static shapes, no data-dependent control flow, so XLA
    keeps the whole update on device.
    """

    data: Any  # f32 [n_signals, capacity]
    cursor: Any  # i32 []
    counts: Any  # i32 [n_signals]

    @staticmethod
    def create(n_signals: int, capacity: int, dtype=None):
        import jax.numpy as jnp

        dtype = dtype or jnp.float32
        return DeviceRings(
            data=jnp.zeros((n_signals, capacity), dtype),
            cursor=jnp.zeros((), jnp.int32),
            counts=jnp.zeros((n_signals,), jnp.int32),
        )

    def push_row(self, values) -> "DeviceRings":
        import jax
        import jax.numpy as jnp

        values = jnp.asarray(values, self.data.dtype).reshape(-1, 1)
        capacity = self.data.shape[1]
        idx = self.cursor % capacity
        data = jax.lax.dynamic_update_slice(self.data, values, (0, idx))
        return DeviceRings(
            data=data,
            cursor=self.cursor + 1,
            counts=jnp.minimum(self.counts + 1, capacity),
        )

    def valid_mask(self):
        """[n_signals, capacity] bool — True where a real sample exists."""
        import jax.numpy as jnp

        pos = jnp.arange(self.data.shape[1])[None, :]
        return pos < self.counts[:, None]

    def reset(self) -> "DeviceRings":
        import jax.numpy as jnp

        return DeviceRings(
            data=self.data,  # stale data is masked out by counts
            cursor=jnp.zeros((), jnp.int32),
            counts=jnp.zeros_like(self.counts),
        )


def register_pytrees() -> None:
    import jax

    try:
        jax.tree_util.register_pytree_node(
            DeviceRings,
            lambda r: ((r.data, r.cursor, r.counts), None),
            lambda _, c: DeviceRings(*c),
        )
    except ValueError:
        pass  # already registered


register_pytrees()
