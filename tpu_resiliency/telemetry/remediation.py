"""Audited telemetry→action loop: turn health decisions into remediations.

ROADMAP item 5: the telemetry plane detects stragglers (``policy.py`` emits
:class:`~tpu_resiliency.telemetry.policy.HealthDecision`\\ s) and the launcher
holds warm spares (``launcher/park.py``), but until now nothing connected them —
detection ended at a report. The :class:`RemediationEngine` is the connector,
built on one rule: **no automated action without an audit trail**. Every
remediation runs inside ``remediation.decide`` / ``remediation.<action>`` spans
carrying the triggering scores, and emits a ``remediation_action`` event
(→ ``tpu_remediation_actions_total{action,outcome}``) whatever the outcome, so
an operator can replay exactly what the system did and why — the incident
engine (``launcher/incident.py``) folds these records into its causal chain.

The decision matrix (see ``docs/incidents.md``):

1. **proactive checkpoint** — always first when a ``checkpoint_fn`` is wired:
   a degrading rank may die outright next, so bank the progress while every
   rank is still alive (ride the async checkpointer; the call must be cheap).
2. **spare swap** — when ``spare_capacity_fn`` reports warm capacity, demote
   the degraded ranks (publish to the restart coordinator, where
   ``DemoteDegraded`` benches them next round) and request an in-job restart:
   the launcher's warm-spare pool absorbs the respawn cost, so the swap is the
   cheap path (reference NVRx never gets past ``trainer.should_stop``).
3. **exclude and continue** — no spare capacity: publish the degraded set so
   rank assignment reshapes around the slow ranks, and (when a
   ``monitor_client`` is wired and *this* rank is the degraded one) ask the
   launcher to exclude the node entirely (``WorkloadAction.ExcludeThisNode``).

Recoveries are audited too: a decision whose ``recovered`` set is non-empty
emits ``remediation_action{action=reinstate}`` so the end of an incident is as
visible as its start.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from tpu_resiliency.telemetry.policy import HealthDecision
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)

#: action names (the ``action`` label of ``tpu_remediation_actions_total``)
ACTION_CHECKPOINT = "checkpoint"
ACTION_SPARE_SWAP = "spare_swap"
ACTION_EXCLUDE = "exclude"
ACTION_REINSTATE = "reinstate"

OUTCOME_OK = "ok"
OUTCOME_FAILED = "failed"
OUTCOME_SKIPPED = "skipped"


class RemediationEngine:
    """A :class:`HealthVectorPolicy` sink that drives real actions.

    Wire it as ``HealthVectorPolicy(sinks=[engine])``; it consumes every
    decision whose degraded set changed. All actuators are optional callables —
    the engine degrades to exclude-and-continue (the always-available action:
    publishing the degraded set costs only a store write) when the richer
    paths aren't wired:

    - ``checkpoint_fn()``: trigger a proactive checkpoint (e.g. a closure over
      ``LocalCheckpointManager.save(..., is_async=True)``).
    - ``spare_capacity_fn() -> int``: warm spares available for a swap.
    - ``publish_degraded_fn(frozenset[int])``: hand the degraded set to the
      restart coordinator (``RestartCoordinator.set_degraded``).
    - ``request_restart_fn(reason)``: trigger the in-job restart round that
      actually performs the swap (``StoreRendezvous.request_restart`` or the
      in-process coordinator's interruption record).
    - ``monitor_client``: a :class:`~tpu_resiliency.watchdog.monitor_client.
      RankMonitorClient` used for node exclusion when *this* rank degrades.

    ``cooldown`` (seconds) bounds actuation frequency: a decision landing
    inside the cooldown window is still audited, with ``outcome=skipped`` —
    remediation must not thrash the job faster than it can recover. The
    window is evaluated once per decision (a plan is one remediation), so a
    proactive checkpoint never cools down the swap/exclude in its own plan.
    """

    def __init__(
        self,
        *,
        checkpoint_fn: Optional[Callable[[], object]] = None,
        spare_capacity_fn: Optional[Callable[[], int]] = None,
        publish_degraded_fn: Optional[Callable[[frozenset], None]] = None,
        request_restart_fn: Optional[Callable[[str], None]] = None,
        monitor_client=None,
        self_rank: Optional[int] = None,
        cooldown: float = 0.0,
        dry_run: bool = False,
    ):
        self.checkpoint_fn = checkpoint_fn
        self.spare_capacity_fn = spare_capacity_fn
        self.publish_degraded_fn = publish_degraded_fn
        self.request_restart_fn = request_restart_fn
        self.monitor_client = monitor_client
        self.self_rank = self_rank
        self.cooldown = cooldown
        self.dry_run = dry_run
        self._last_action_ts: float = float("-inf")
        #: audit trail of (action, outcome) pairs, newest last (tests/operators)
        self.history: list[tuple[str, str]] = []

    # -- the sink entry point ----------------------------------------------

    def __call__(self, decision: HealthDecision) -> None:
        try:
            self.remediate(decision)
        except Exception:
            # An actuator bug must never take down the telemetry loop.
            log.exception("remediation failed; detection loop continues")

    # -- core ---------------------------------------------------------------

    def remediate(self, decision: HealthDecision) -> list[tuple[str, str]]:
        """Run the decision matrix for one changed decision. Returns the
        ``(action, outcome)`` pairs taken (also appended to ``history``)."""
        taken: list[tuple[str, str]] = []
        if decision.recovered and not decision.newly_degraded:
            taken.append(self._reinstate(decision))
            self.history.extend(taken)
            return taken
        if not decision.newly_degraded:
            return taken
        scores = {
            str(r): round(float(s), 4)
            for r, s in (decision.scores or {}).items()
        }
        with span(
            "remediation", "remediation.decide",
            degraded=sorted(decision.degraded),
            newly=sorted(decision.newly_degraded),
            scores=scores,
        ):
            plan = self._plan(decision)
            record_event(
                "remediation", "remediation_decision",
                plan=[a for a, _ in plan],
                degraded=sorted(decision.degraded),
                newly=sorted(decision.newly_degraded),
            )
        # Cooldown is evaluated once per decision, not per action: a plan is
        # one remediation (checkpoint → swap/exclude), and stamping after the
        # first step would suppress the rest of its own plan.
        in_cooldown = (
            time.monotonic() - self._last_action_ts
        ) < self.cooldown
        for action, runner in plan:
            taken.append(
                self._execute(action, runner, decision, in_cooldown=in_cooldown)
            )
        if any(outcome == OUTCOME_OK for _, outcome in taken):
            self._last_action_ts = time.monotonic()
        self.history.extend(taken)
        return taken

    def _plan(self, decision: HealthDecision) -> list[tuple[str, Callable]]:
        """The decision matrix, resolved against the wired actuators."""
        plan: list[tuple[str, Callable]] = []
        if self.checkpoint_fn is not None:
            plan.append((ACTION_CHECKPOINT, self._do_checkpoint))
        spares = 0
        if self.spare_capacity_fn is not None:
            try:
                spares = int(self.spare_capacity_fn())
            except Exception:
                spares = 0
        if spares > 0 and self.request_restart_fn is not None:
            plan.append((ACTION_SPARE_SWAP, self._do_spare_swap))
        else:
            plan.append((ACTION_EXCLUDE, self._do_exclude))
        return plan

    def _execute(
        self,
        action: str,
        runner: Callable,
        decision: HealthDecision,
        in_cooldown: bool = False,
        reason: str = "",
    ) -> tuple[str, str]:
        ranks = sorted(decision.newly_degraded)
        why = {"reason": reason} if reason else {}
        if self.dry_run or in_cooldown:
            outcome = OUTCOME_SKIPPED
            detail = "dry_run" if self.dry_run else "cooldown"
            record_event(
                "remediation", "remediation_action", action=action,
                outcome=outcome, ranks=ranks, detail=detail, **why,
            )
            return action, outcome
        with span(
            "remediation", f"remediation.{action}", ranks=ranks,
            degraded=sorted(decision.degraded),
            scores={
                str(r): round(float((decision.scores or {}).get(r, float("nan"))), 4)
                for r in ranks
            },
        ):
            try:
                runner(decision)
                outcome, detail = OUTCOME_OK, ""
            except Exception as e:
                outcome, detail = OUTCOME_FAILED, repr(e)
                log.warning(f"remediation {action} failed: {e!r}")
            record_event(
                "remediation", "remediation_action", action=action,
                outcome=outcome, ranks=ranks,
                **({"detail": detail} if detail else {}), **why,
            )
        return action, outcome

    # -- external drive (the autoscale controller's path) --------------------

    def execute_action(
        self,
        action: str,
        ranks,
        scores: Optional[dict] = None,
        reason: str = "",
    ) -> tuple[str, str]:
        """Run ONE actuator outside a policy-driven plan, with the same
        cooldown/dry-run audit semantics (``launcher/autoscale.py`` routes
        its swap/exclude/checkpoint decisions through here so policy-driven
        and controller-driven remediations share one audit trail). Returns
        the ``(action, outcome)`` pair, also appended to ``history``."""
        runners = {
            ACTION_CHECKPOINT: self._do_checkpoint,
            ACTION_SPARE_SWAP: self._do_spare_swap,
            ACTION_EXCLUDE: self._do_exclude,
        }
        if action not in runners:
            raise ValueError(f"unknown remediation action {action!r}")
        ranks = frozenset(int(r) for r in ranks)
        decision = HealthDecision(
            degraded=ranks, newly_degraded=ranks, recovered=frozenset(),
            flagged=ranks,
            scores={int(r): float(s) for r, s in (scores or {}).items()},
        )
        in_cooldown = (
            time.monotonic() - self._last_action_ts
        ) < self.cooldown
        result = self._execute(
            action, runners[action], decision, in_cooldown=in_cooldown,
            reason=reason,
        )
        if result[1] == OUTCOME_OK:
            self._last_action_ts = time.monotonic()
        self.history.append(result)
        return result

    # -- actuators ----------------------------------------------------------

    def _do_checkpoint(self, decision: HealthDecision) -> None:
        self.checkpoint_fn()

    def _do_spare_swap(self, decision: HealthDecision) -> None:
        if self.publish_degraded_fn is not None:
            self.publish_degraded_fn(decision.degraded)
        self.request_restart_fn(
            f"remediation: swap degraded ranks {sorted(decision.newly_degraded)} "
            f"onto warm spares"
        )

    def _do_exclude(self, decision: HealthDecision) -> None:
        if self.publish_degraded_fn is not None:
            self.publish_degraded_fn(decision.degraded)
        if (
            self.monitor_client is not None
            and self.self_rank is not None
            and self.self_rank in decision.newly_degraded
        ):
            from tpu_resiliency.watchdog.data import WorkloadAction

            self.monitor_client.send_workload_control_request(
                WorkloadAction.ExcludeThisNode,
                reason=(
                    f"rank {self.self_rank} degraded; remediation engine "
                    f"excluding this node"
                ),
            )
        elif self.publish_degraded_fn is None:
            raise RuntimeError(
                "exclude: no actuator wired (need publish_degraded_fn or "
                "monitor_client for a self-degraded rank)"
            )

    def _reinstate(self, decision: HealthDecision) -> tuple[str, str]:
        ranks = sorted(decision.recovered)
        try:
            if self.publish_degraded_fn is not None:
                self.publish_degraded_fn(decision.degraded)
            outcome = OUTCOME_OK
        except Exception as e:
            outcome = OUTCOME_FAILED
            log.warning(f"reinstate publish failed: {e!r}")
        record_event(
            "remediation", "remediation_action", action=ACTION_REINSTATE,
            outcome=outcome, ranks=ranks,
        )
        return ACTION_REINSTATE, outcome
