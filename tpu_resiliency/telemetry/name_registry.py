"""Distributed-consistent section/signal name → column-index mapping.

The analogue of the reference's ``NameMapper`` (``straggler/name_mapper.py:56-81``),
which lazily all-gathers names so every rank agrees on int IDs. TPU-first redesign:
signal columns live in a fixed-capacity device matrix, and cross-rank agreement is
reached through the coordination store at report boundaries (rare, host-side) instead
of collectives — IDs are assigned by globally sorted name order, which every rank can
compute independently from the store's merged name set, with no authoritative rank.
"""

from __future__ import annotations

from typing import Iterable, Optional


class NameRegistry:
    """Fixed-capacity name→index registry with deterministic distributed merge."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._ids: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._ids)

    def __contains__(self, name: str) -> bool:
        return name in self._ids

    def get(self, name: str) -> int:
        """Index of ``name``, registering it locally if new."""
        idx = self._ids.get(name)
        if idx is None:
            if len(self._ids) >= self.capacity:
                raise ValueError(
                    f"name registry full ({self.capacity}); raise max_signals"
                )
            idx = len(self._ids)
            self._ids[name] = idx
        return idx

    def names(self) -> tuple[str, ...]:
        """Names in index order."""
        return tuple(sorted(self._ids, key=self._ids.__getitem__))

    def index_map(self) -> dict[str, int]:
        return dict(self._ids)

    def publish(self, store, key: str = "telemetry/names") -> None:
        """Publish local names into the store's merged set (idempotent union)."""
        store.set_add(key, list(self._ids))

    def merge(self, store, key: str = "telemetry/names") -> dict[int, int]:
        """Adopt the store's merged name set: existing names keep their slots, newly
        discovered names append in sorted order.

        Invariant: *per-rank column stability* — a name's index never changes on a
        given rank, so per-column carried state (EWMA, historical minima) stays valid
        across rounds. Indices need not agree across ranks: summaries travel keyed by
        name and each scoring rank builds its matrix from its own registry. Callers
        wanting within-round membership consistency barrier between ``publish`` and
        ``merge``. Returns old-index → new-index remap (identity for kept names)."""
        merged = store.set_get(key)
        new_names = sorted(n for n in merged if n not in self._ids)
        if len(self._ids) + len(new_names) > self.capacity:
            raise ValueError(
                f"name registry overflow after sync: {len(self._ids) + len(new_names)} "
                f"> {self.capacity}"
            )
        remap = {i: i for i in self._ids.values()}
        for n in new_names:
            self._ids[n] = len(self._ids)
        return remap

    def sync_via_store(self, store, key: str = "telemetry/names") -> dict[int, int]:
        """``publish`` + ``merge`` in one shot (single-rank or eventually-consistent
        use; the reference's NameMapper gather analogue, ``name_mapper.py:56-81``)."""
        self.publish(store, key)
        return self.merge(store, key)
