"""Summary-statistic selection for telemetry reports.

Analogue of the reference's ``straggler/statistics.py:19`` MIN/MAX/MED/AVG/STD/NUM enum.
"""

from __future__ import annotations

import enum

import numpy as np


class Statistic(enum.Enum):
    MIN = "min"
    MAX = "max"
    MED = "med"
    AVG = "avg"
    STD = "std"
    NUM = "num"


ALL_STATISTICS = tuple(Statistic)


def compute_stats(samples, stats=ALL_STATISTICS) -> dict[Statistic, float]:
    """Summary stats of a 1-D sample array (host-side; device path uses scoring.py)."""
    arr = np.asarray(samples, dtype=np.float64)
    out: dict[Statistic, float] = {}
    n = arr.size
    for s in stats:
        if s is Statistic.NUM:
            out[s] = float(n)
        elif n == 0:
            out[s] = float("nan")
        elif s is Statistic.MIN:
            out[s] = float(arr.min())
        elif s is Statistic.MAX:
            out[s] = float(arr.max())
        elif s is Statistic.MED:
            out[s] = float(np.median(arr))
        elif s is Statistic.AVG:
            out[s] = float(arr.mean())
        elif s is Statistic.STD:
            out[s] = float(arr.std(ddof=1)) if n > 1 else 0.0
    return out
