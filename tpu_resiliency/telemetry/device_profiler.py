"""Per-compiled-program device timing from the XLA profiler: the CUPTI equivalent.

The reference's straggler detector feeds on CUPTI per-kernel wall times captured by a
C++ activity-buffer extension (``straggler/cupti_src/CuptiProfiler.cpp:96-203``) with a
``start/stop/get_stats/reset`` contract. Per-kernel timing does not exist under XLA —
kernels are fused into whole compiled programs — so the TPU-native signal is the
**per-XLA-module device time**: the profiler's device plane records one event per
program execution (``XLA Modules`` line) with the true on-device duration
(``device_duration_ps``), no host dispatch included. This is the deliberate semantic
change SURVEY §7 calls out ("matching CUPTI fidelity"): program-level granularity,
device-exact durations.

:class:`DeviceTimeProfiler` preserves the reference contract:

- ``start()`` / ``stop()`` bracket a capture window (run a window every Nth report
  interval, like CUPTI's ``profiling_interval`` — tracing is not free);
- ``drain()`` yields the new per-program duration samples since the last drain
  (feed them to ``Detector.record_program_samples`` so programs join the scored
  telemetry matrix as ``prog/...`` signals);
- ``get_stats()`` returns per-program min/max/med/avg/std/count like the C++
  ``computeStats`` (``CuptiProfiler.cpp:44-74``); ``reset()`` clears.

Program names are stable across recompiles: the fingerprint hash suffix is stripped
(``jit_train_step(123...)`` → ``jit_train_step``). On backends without a device plane
(CPU), the capture falls back to the host trace's ``PjitFunction`` events —
host-inclusive dispatch durations, clearly a different signal, but it keeps the whole
pipeline exercisable in simulation.
"""

from __future__ import annotations

import glob
import os
import re
import shutil
import tempfile
from collections import deque
from typing import Optional

import numpy as np

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

_HASH_SUFFIX = re.compile(r"\(\d+\)$")
_PJIT = re.compile(r"^PjitFunction\((.+)\)$")
_OP_ID_SUFFIX = re.compile(r"\.\d+$")
_JIT_COMPONENT = re.compile(r"^(jit|pjit)\(.*\)$")

MAX_SAMPLES_PER_PROGRAM = 8192  # reference statsMaxLenPerKernel ring bound


def normalize_program_name(name: str) -> str:
    return _HASH_SUFFIX.sub("", name)


def op_scope_key(name: str, stats: dict) -> Optional[str]:
    """Aggregation key for one per-op trace event, or ``None`` for bookkeeping
    events. Pure so the TPU-plane mapping is testable without a TPU trace.

    Preference order:

    1. The ``tf_op`` stat — the framework op path XLA propagates from HLO
       metadata (``jax.named_scope`` contributes components). The key is the
       *scope* path: leading ``jit(...)``/``pjit(...)`` wrappers dropped, the
       trailing op component dropped, e.g. ``jit(step)/attn/dot_general`` →
       ``attn``. An unscoped op keys by its own base name.
    2. The ``hlo_op`` stat (or the event name), numeric instruction id
       stripped (``dot_general.2`` → ``dot_general``) — instruction ids are
       compile-order artifacts that would fragment signals across recompiles.
    """
    if name.startswith("end: ") or "::" in name:
        return None
    tf_op = stats.get("tf_op")
    if tf_op:
        parts = [p for p in str(tf_op).split("/") if p]
        while parts and _JIT_COMPONENT.match(parts[0]):
            parts = parts[1:]
        if len(parts) >= 2:
            return "/".join(parts[:-1])
        if parts:
            return _OP_ID_SUFFIX.sub("", parts[0])
        return None
    base = _OP_ID_SUFFIX.sub("", str(stats.get("hlo_op") or name))
    if not base or base.startswith("_"):
        return None
    return base


def extract_program_times(profile_data) -> dict[str, list[float]]:
    """Per-program device durations (seconds) from one xplane ProfileData.

    Primary source: device planes' ``XLA Modules`` line (true device time).
    Fallback when no device plane exists (CPU simulation): the host plane's
    ``PjitFunction`` events (host-inclusive dispatch time).
    """
    out: dict[str, list[float]] = {}
    saw_device_plane = False
    for plane in profile_data.planes:
        if "/device:" not in plane.name or "CUSTOM" in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Modules":
                continue
            saw_device_plane = True
            for ev in line.events:
                name = normalize_program_name(ev.name)
                out.setdefault(name, []).append(float(ev.duration_ns) * 1e-9)
    if saw_device_plane:
        return out
    for plane in profile_data.planes:
        if not plane.name.startswith("/host:"):
            continue
        for line in plane.lines:
            if line.name != "python":
                continue
            for ev in line.events:
                m = _PJIT.match(ev.name)
                if m:
                    name = f"pjit_{m.group(1)}"
                    out.setdefault(name, []).append(float(ev.duration_ns) * 1e-9)
    return out


def _event_stats(ev) -> dict:
    try:
        return dict(ev.stats)
    except Exception:
        return {}


def extract_op_times(profile_data) -> dict[str, list[float]]:
    """Per-op/scope device durations (seconds) from one xplane ProfileData —
    one granularity below :func:`extract_program_times`, the closest XLA gets
    to CUPTI's per-kernel stream (kernels themselves are fused away).

    Primary source: device planes' ``XLA Ops`` line (true device time, one
    event per HLO op execution, ``tf_op`` scope attribution when XLA carries
    it). Fallback when no device plane exists (CPU simulation): the PjRt CPU
    client's per-op thread line (host-inclusive op durations — a different
    clock, same pipeline mechanics)."""
    out: dict[str, list[float]] = {}
    saw_device_ops = False
    for plane in profile_data.planes:
        if "/device:" not in plane.name or "CUSTOM" in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            saw_device_ops = True
            for ev in line.events:
                key = op_scope_key(ev.name, _event_stats(ev))
                if key is not None:
                    out.setdefault(key, []).append(float(ev.duration_ns) * 1e-9)
    if saw_device_ops:
        return out
    for plane in profile_data.planes:
        for line in plane.lines:
            if "XLAPjRt" not in line.name:
                continue
            for ev in line.events:
                key = op_scope_key(ev.name, _event_stats(ev))
                if key is not None:
                    out.setdefault(key, []).append(float(ev.duration_ns) * 1e-9)
    return out


class DeviceTimeProfiler:
    """Windowed per-program device-time capture with the CUPTI manager contract."""

    def __init__(self, trace_root: Optional[str] = None, collect_ops: bool = False):
        self._root = trace_root
        self._window_dir: Optional[str] = None
        self._samples: dict[str, deque] = {}
        self._fresh: dict[str, list[float]] = {}
        #: opt-in per-op/scope granularity (extract_op_times) alongside the
        #: per-program default — parse cost only, no extra tracing overhead.
        self.collect_ops = collect_ops
        self._op_samples: dict[str, deque] = {}
        self._op_fresh: dict[str, list[float]] = {}
        self.active = False

    # -- capture window ------------------------------------------------------

    def start(self) -> None:
        if self.active:
            return
        import jax

        self._window_dir = tempfile.mkdtemp(prefix="devprof_", dir=self._root)
        try:
            jax.profiler.start_trace(self._window_dir)
        except Exception:
            # The process-global profiler may already be active (another window's
            # leak, or user tracing). Profiling is opportunistic observability —
            # skip the window, never break the step.
            log.warning("could not start a profiler window; skipping", exc_info=True)
            shutil.rmtree(self._window_dir, ignore_errors=True)
            self._window_dir = None
            return
        self.active = True

    def stop(self) -> None:
        """End the window and fold its per-program samples into the stats."""
        if not self.active:
            return
        import jax
        from jax.profiler import ProfileData

        jax.profiler.stop_trace()
        self.active = False
        try:
            files = glob.glob(
                os.path.join(self._window_dir, "**", "*.xplane.pb"), recursive=True
            )
            for f in files:
                data = ProfileData.from_file(f)
                times = extract_program_times(data)
                for name, secs in times.items():
                    ring = self._samples.setdefault(
                        name, deque(maxlen=MAX_SAMPLES_PER_PROGRAM)
                    )
                    ring.extend(secs)
                    self._fresh.setdefault(name, []).extend(secs)
                if self.collect_ops:
                    for name, secs in extract_op_times(data).items():
                        ring = self._op_samples.setdefault(
                            name, deque(maxlen=MAX_SAMPLES_PER_PROGRAM)
                        )
                        ring.extend(secs)
                        self._op_fresh.setdefault(name, []).extend(secs)
        except Exception:
            log.exception("device profile parse failed; window dropped")
        finally:
            if self._window_dir:
                shutil.rmtree(self._window_dir, ignore_errors=True)
                self._window_dir = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- consumption ---------------------------------------------------------

    def drain(self) -> dict[str, list[float]]:
        """New samples since the last drain (seconds per execution)."""
        fresh, self._fresh = self._fresh, {}
        return fresh

    def drain_ops(self) -> dict[str, list[float]]:
        """New per-op/scope samples since the last drain (collect_ops only);
        feed to ``Detector.record_op_samples``."""
        fresh, self._op_fresh = self._op_fresh, {}
        return fresh

    @staticmethod
    def _stats_over(samples: dict[str, deque]) -> dict[str, dict[str, float]]:
        out = {}
        for name, ring in samples.items():
            if not ring:
                continue
            arr = np.asarray(ring, dtype=np.float64)
            out[name] = {
                "min": float(arr.min()),
                "max": float(arr.max()),
                "med": float(np.median(arr)),
                "avg": float(arr.mean()),
                "std": float(arr.std()),
                "count": int(arr.size),
            }
        return out

    def get_stats(self) -> dict[str, dict[str, float]]:
        """Per-program stats over retained samples (reference ``computeStats``)."""
        return self._stats_over(self._samples)

    def get_op_stats(self) -> dict[str, dict[str, float]]:
        """Per-op/scope stats over retained samples (collect_ops only)."""
        return self._stats_over(self._op_samples)

    def reset(self) -> None:
        self._samples.clear()
        self._fresh.clear()
        self._op_samples.clear()
        self._op_fresh.clear()
