"""Per-compiled-program device timing from the XLA profiler: the CUPTI equivalent.

The reference's straggler detector feeds on CUPTI per-kernel wall times captured by a
C++ activity-buffer extension (``straggler/cupti_src/CuptiProfiler.cpp:96-203``) with a
``start/stop/get_stats/reset`` contract. Per-kernel timing does not exist under XLA —
kernels are fused into whole compiled programs — so the TPU-native signal is the
**per-XLA-module device time**: the profiler's device plane records one event per
program execution (``XLA Modules`` line) with the true on-device duration
(``device_duration_ps``), no host dispatch included. This is the deliberate semantic
change SURVEY §7 calls out ("matching CUPTI fidelity"): program-level granularity,
device-exact durations.

:class:`DeviceTimeProfiler` preserves the reference contract:

- ``start()`` / ``stop()`` bracket a capture window (run a window every Nth report
  interval, like CUPTI's ``profiling_interval`` — tracing is not free);
- ``drain()`` yields the new per-program duration samples since the last drain
  (feed them to ``Detector.record_program_samples`` so programs join the scored
  telemetry matrix as ``prog/...`` signals);
- ``get_stats()`` returns per-program min/max/med/avg/std/count like the C++
  ``computeStats`` (``CuptiProfiler.cpp:44-74``); ``reset()`` clears.

Program names are stable across recompiles: the fingerprint hash suffix is stripped
(``jit_train_step(123...)`` → ``jit_train_step``). On backends without a device plane
(CPU), the capture falls back to the host trace's ``PjitFunction`` events —
host-inclusive dispatch durations, clearly a different signal, but it keeps the whole
pipeline exercisable in simulation.
"""

from __future__ import annotations

import glob
import os
import re
import shutil
import tempfile
from collections import deque
from typing import Optional

import numpy as np

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

_HASH_SUFFIX = re.compile(r"\(\d+\)$")
_PJIT = re.compile(r"^PjitFunction\((.+)\)$")

MAX_SAMPLES_PER_PROGRAM = 8192  # reference statsMaxLenPerKernel ring bound


def normalize_program_name(name: str) -> str:
    return _HASH_SUFFIX.sub("", name)


def extract_program_times(profile_data) -> dict[str, list[float]]:
    """Per-program device durations (seconds) from one xplane ProfileData.

    Primary source: device planes' ``XLA Modules`` line (true device time).
    Fallback when no device plane exists (CPU simulation): the host plane's
    ``PjitFunction`` events (host-inclusive dispatch time).
    """
    out: dict[str, list[float]] = {}
    saw_device_plane = False
    for plane in profile_data.planes:
        if "/device:" not in plane.name or "CUSTOM" in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Modules":
                continue
            saw_device_plane = True
            for ev in line.events:
                name = normalize_program_name(ev.name)
                out.setdefault(name, []).append(float(ev.duration_ns) * 1e-9)
    if saw_device_plane:
        return out
    for plane in profile_data.planes:
        if not plane.name.startswith("/host:"):
            continue
        for line in plane.lines:
            if line.name != "python":
                continue
            for ev in line.events:
                m = _PJIT.match(ev.name)
                if m:
                    name = f"pjit_{m.group(1)}"
                    out.setdefault(name, []).append(float(ev.duration_ns) * 1e-9)
    return out


class DeviceTimeProfiler:
    """Windowed per-program device-time capture with the CUPTI manager contract."""

    def __init__(self, trace_root: Optional[str] = None):
        self._root = trace_root
        self._window_dir: Optional[str] = None
        self._samples: dict[str, deque] = {}
        self._fresh: dict[str, list[float]] = {}
        self.active = False

    # -- capture window ------------------------------------------------------

    def start(self) -> None:
        if self.active:
            return
        import jax

        self._window_dir = tempfile.mkdtemp(prefix="devprof_", dir=self._root)
        try:
            jax.profiler.start_trace(self._window_dir)
        except Exception:
            # The process-global profiler may already be active (another window's
            # leak, or user tracing). Profiling is opportunistic observability —
            # skip the window, never break the step.
            log.warning("could not start a profiler window; skipping", exc_info=True)
            shutil.rmtree(self._window_dir, ignore_errors=True)
            self._window_dir = None
            return
        self.active = True

    def stop(self) -> None:
        """End the window and fold its per-program samples into the stats."""
        if not self.active:
            return
        import jax
        from jax.profiler import ProfileData

        jax.profiler.stop_trace()
        self.active = False
        try:
            files = glob.glob(
                os.path.join(self._window_dir, "**", "*.xplane.pb"), recursive=True
            )
            for f in files:
                times = extract_program_times(ProfileData.from_file(f))
                for name, secs in times.items():
                    ring = self._samples.setdefault(
                        name, deque(maxlen=MAX_SAMPLES_PER_PROGRAM)
                    )
                    ring.extend(secs)
                    self._fresh.setdefault(name, []).extend(secs)
        except Exception:
            log.exception("device profile parse failed; window dropped")
        finally:
            if self._window_dir:
                shutil.rmtree(self._window_dir, ignore_errors=True)
                self._window_dir = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- consumption ---------------------------------------------------------

    def drain(self) -> dict[str, list[float]]:
        """New samples since the last drain (seconds per execution)."""
        fresh, self._fresh = self._fresh, {}
        return fresh

    def get_stats(self) -> dict[str, dict[str, float]]:
        """Per-program stats over retained samples (reference ``computeStats``)."""
        out = {}
        for name, ring in self._samples.items():
            if not ring:
                continue
            arr = np.asarray(ring, dtype=np.float64)
            out[name] = {
                "min": float(arr.min()),
                "max": float(arr.max()),
                "med": float(np.median(arr)),
                "avg": float(arr.mean()),
                "std": float(arr.std()),
                "count": int(arr.size),
            }
        return out

    def reset(self) -> None:
        self._samples.clear()
        self._fresh.clear()
