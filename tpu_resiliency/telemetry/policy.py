"""Health-vector policy: turn telemetry scores into restart/replication decisions.

BASELINE target 5 names "local-ckpt replication driven by on-device health vector";
the reference's closest coupling is the straggler callback setting
``trainer.should_stop`` (``ptl_resiliency/straggler_det_callback.py:91-98``). This
module closes the loop tighter, without killing anything that still works:

- a :class:`HealthVectorPolicy` watches successive reports and promotes ranks flagged
  ``patience`` consecutive rounds into a *degraded* set (with hysteresis: one clean
  round clears the streak, ``recovery`` clean rounds clears degraded status);
- the degraded set is published to the restart coordinator, where rank reassignment
  (``inprocess/rank_assignment.DemoteDegraded``) turns degraded-but-alive ranks into
  INACTIVE spares on the next restart round — the job sheds a slow rank without
  waiting for it to die;
- checkpoint retrieval avoids degraded holders (``ExchangePlan.build(avoid=...)``) so
  recovery never waits on the slowest disk/NIC in the clique when a healthy mirror
  exists;
- optionally, a rank that sees *itself* degraded asks the launcher to exclude its
  node (``WorkloadControlRequest(ExcludeThisNode)`` — the reference's workload-ctrl
  path, ``_ft_rendezvous.py:785-804``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from tpu_resiliency.telemetry import scoring
from tpu_resiliency.telemetry.reporting import Report
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class HealthDecision:
    """Outcome of one policy observation."""

    degraded: frozenset[int]  # ranks currently held degraded
    newly_degraded: frozenset[int]  # transitions this round
    recovered: frozenset[int]  # ranks cleared this round
    flagged: frozenset[int]  # raw flags this round (pre-hysteresis)
    #: the per-rank perf scores behind this decision — carried so downstream
    #: consumers (``remediation.py`` spans, incident artifacts) can show WHY a
    #: rank was demoted without re-reading the report
    scores: Optional[dict[int, float]] = None

    @property
    def changed(self) -> bool:
        return bool(self.newly_degraded or self.recovered)


class HealthVectorPolicy:
    """Streak-based promotion of per-round straggler flags into decisions.

    ``patience``: consecutive flagged reports before a rank is degraded (a single
    noisy round must not demote anyone). ``recovery``: consecutive clean reports
    before a degraded rank is reinstated. Sinks receive the :class:`HealthDecision`
    whenever the degraded set changes.
    """

    def __init__(
        self,
        *,
        patience: int = 2,
        recovery: int = 3,
        perf_threshold: float = scoring.DEFAULT_THRESHOLD,
        z_threshold: float = scoring.DEFAULT_Z_THRESHOLD,
        sinks: Optional[list[Callable[[HealthDecision], None]]] = None,
    ):
        if patience < 1 or recovery < 1:
            raise ValueError("patience and recovery must be >= 1")
        self.patience = patience
        self.recovery = recovery
        self.perf_threshold = perf_threshold
        self.z_threshold = z_threshold
        self.sinks = list(sinks or [])
        self._flag_streak: dict[int, int] = {}
        self._clean_streak: dict[int, int] = {}
        self._degraded: set[int] = set()
        #: the most recent decision (changed or not) — embedders that poll
        #: instead of sinking (the autoscale controller's view assembly, the
        #: /autoscale document) read the current verdict here
        self.last_decision: Optional[HealthDecision] = None

    @property
    def degraded(self) -> frozenset[int]:
        return frozenset(self._degraded)

    def observe(self, report: Report) -> HealthDecision:
        stragglers = report.identify_stragglers(
            perf_threshold=self.perf_threshold,
            section_threshold=self.perf_threshold,
            z_threshold=self.z_threshold,
        )
        flagged = {sid.rank for sid in stragglers.by_perf}
        known = set(report.perf_scores or {})
        newly, recovered = set(), set()
        for r in known:
            if r in flagged:
                self._flag_streak[r] = self._flag_streak.get(r, 0) + 1
                self._clean_streak[r] = 0
                if r not in self._degraded and self._flag_streak[r] >= self.patience:
                    self._degraded.add(r)
                    newly.add(r)
            else:
                self._flag_streak[r] = 0
                self._clean_streak[r] = self._clean_streak.get(r, 0) + 1
                if r in self._degraded and self._clean_streak[r] >= self.recovery:
                    self._degraded.discard(r)
                    recovered.add(r)
        decision = HealthDecision(
            degraded=frozenset(self._degraded),
            newly_degraded=frozenset(newly),
            recovered=frozenset(recovered),
            flagged=frozenset(flagged),
            scores={r: float(s) for r, s in (report.perf_scores or {}).items()},
        )
        self.last_decision = decision
        if decision.changed:
            record_event(
                "telemetry", "degraded_set",
                degraded=sorted(decision.degraded),
                newly=sorted(decision.newly_degraded),
                recovered=sorted(decision.recovered),
                scores={
                    str(r): round(float(s), 4)
                    for r, s in (report.perf_scores or {}).items()
                },
            )
            log.warning(
                f"health vector: degraded={sorted(decision.degraded)} "
                f"(+{sorted(newly)} -{sorted(recovered)})"
            )
            for sink in self.sinks:
                try:
                    sink(decision)
                except Exception:
                    log.exception("health-policy sink failed")
        return decision

    def note_restart(self) -> None:
        """A restart round happened: in-flight streak evidence is void.

        Ranks were reassigned, respawned, or benched — a pre-restart clean
        streak must not count toward reinstating a degraded rank (the respawned
        incarnation has proven nothing yet), and a pre-restart flag streak must
        not demote a rank on its first post-restart wobble. Degraded *status*
        persists: hysteresis restarts, the verdict does not."""
        self._flag_streak.clear()
        self._clean_streak.clear()
        if self._degraded:
            record_event(
                "telemetry", "degraded_set",
                degraded=sorted(self._degraded), newly=[], recovered=[],
                reason="restart: streaks reset, degraded set carried",
            )


# -- stock sinks -----------------------------------------------------------


def coordinator_sink(coord) -> Callable[[HealthDecision], None]:
    """Publish the degraded set to a restart coordinator
    (:class:`~tpu_resiliency.inprocess.coordination.RestartCoordinator`), where
    ``DemoteDegraded`` rank assignment picks it up on the next restart round."""

    def sink(decision: HealthDecision) -> None:
        coord.set_degraded(decision.degraded)

    return sink


def exclude_self_sink(monitor_client, rank: int) -> Callable[[HealthDecision], None]:
    """When *this* rank is degraded, request node exclusion from the launcher
    (reference ``WorkloadAction.ExcludeThisNode``)."""
    from tpu_resiliency.watchdog.data import WorkloadAction

    def sink(decision: HealthDecision) -> None:
        if rank in decision.newly_degraded:
            monitor_client.send_workload_control_request(
                WorkloadAction.ExcludeThisNode,
                reason=f"rank {rank} degraded by health-vector policy",
            )

    return sink
