from tpu_resiliency.telemetry.detector import CallableId, Detector
from tpu_resiliency.telemetry.interval_tracker import ReportIntervalTracker
from tpu_resiliency.telemetry.name_registry import NameRegistry
from tpu_resiliency.telemetry.reporting import Report, ReportGenerator, StragglerId, Stragglers
from tpu_resiliency.telemetry.ring_buffer import DeviceRings, HostRingBuffer
from tpu_resiliency.telemetry.scoring import (
    TelemetryScores,
    make_sharded_scorer,
    masked_median,
    masked_total,
    robust_z,
    score_round,
    score_round_jit,
    score_round_sharded,
)
from tpu_resiliency.telemetry.policy import (
    HealthDecision,
    HealthVectorPolicy,
    coordinator_sink,
    exclude_self_sink,
)
from tpu_resiliency.telemetry.sharded import MeshTelemetry, TelemetryState
from tpu_resiliency.telemetry.statistics import ALL_STATISTICS, Statistic, compute_stats

__all__ = [
    "CallableId",
    "Detector",
    "ReportIntervalTracker",
    "NameRegistry",
    "Report",
    "ReportGenerator",
    "StragglerId",
    "Stragglers",
    "DeviceRings",
    "HostRingBuffer",
    "TelemetryScores",
    "MeshTelemetry",
    "TelemetryState",
    "HealthDecision",
    "HealthVectorPolicy",
    "coordinator_sink",
    "exclude_self_sink",
    "make_sharded_scorer",
    "masked_median",
    "masked_total",
    "robust_z",
    "score_round",
    "score_round_jit",
    "score_round_sharded",
    "Statistic",
    "ALL_STATISTICS",
    "compute_stats",
]
