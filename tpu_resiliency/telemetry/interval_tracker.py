"""Report-interval estimation from observed step time.

Analogue of the reference's ``ReportIntervalTracker`` (``straggler/interval_tracker.py:44-84``):
measure the median step wall-time over the first N iterations, derive how many
iterations fit in ``report_time_interval`` seconds, and make all ranks agree by taking
the MAX across ranks (reference uses an all-reduce; here the merge goes through the
coordination store since it happens exactly once per job).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

ESTIMATION_ITERS = 16


class ReportIntervalTracker:
    def __init__(
        self,
        report_time_interval: float,
        store=None,
        world_size: int = 1,
        rank: int = 0,
        key: str = "telemetry/report_interval",
    ):
        self.report_time_interval = report_time_interval
        self.store = store
        self.world_size = world_size
        self.rank = rank
        self.key = key
        self.iteration = 0
        self.interval: Optional[int] = None
        self._step_times: list[float] = []
        self._last_ts: Optional[float] = None

    def _local_estimate(self) -> int:
        med = float(np.median(self._step_times)) if self._step_times else 1.0
        return max(1, round(self.report_time_interval / max(med, 1e-9)))

    def iter_increase(self) -> None:
        """Call once per training iteration until the interval locks in."""
        if self.interval is not None:
            self.iteration += 1
            return
        now = time.monotonic()
        if self._last_ts is not None:
            self._step_times.append(now - self._last_ts)
        self._last_ts = now
        self.iteration += 1
        if len(self._step_times) >= ESTIMATION_ITERS:
            est = self._local_estimate()
            if self.store is not None and self.world_size > 1:
                # All ranks must agree; merge by MAX like the reference's all-reduce.
                self.store.set_add(self.key, [est])
                self.store.barrier(f"{self.key}/sync", self.rank, self.world_size, 60.0)
                est = max(self.store.set_get(self.key))
            self.interval = est

    def is_interval_elapsed(self) -> bool:
        return self.interval is not None and self.iteration % self.interval == 0
