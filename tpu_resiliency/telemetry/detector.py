"""User-facing straggler-detection API: timed sections + callable wrapping + reports.

The API surface mirrors the reference's ``straggler.Detector`` class-singleton
(``straggler/straggler.py:86-408``): ``initialize`` / ``detection_section`` /
``wrap_callables`` / ``generate_report`` / ``generate_report_if_interval_elapsed`` /
``shutdown``. Differences, by TPU design:

- **Device timing semantics.** CUPTI per-kernel wall times don't exist under XLA —
  kernels are fused into whole compiled programs. The device-side signal here is the
  *blocked section time*: a section (or wrapped callable) can observe the jax arrays it
  produced, and every ``profiling_interval``-th entry the section blocks on them with
  ``jax.block_until_ready``, yielding true device-inclusive duration. Host-only wall
  time is recorded for every entry (the reference's CPU sections,
  ``straggler.py:288-349``). This semantic change is deliberate — see SURVEY.md §7
  "Matching CUPTI fidelity".
- **Aggregation.** Cross-rank aggregation happens through the coordination store at
  report boundaries (host control plane, rare), then the global ``[R, S]`` summary
  matrix is scored by the on-device pipeline (``telemetry/scoring.py``). In
  single-process simulations the matrix is scored directly with zero host transfers.
"""

from __future__ import annotations

import dataclasses
import time
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Optional

import numpy as np

from tpu_resiliency.exceptions import ResiliencyError
from tpu_resiliency.telemetry.interval_tracker import ReportIntervalTracker
from tpu_resiliency.telemetry.name_registry import NameRegistry
from tpu_resiliency.telemetry.reporting import Report, ReportGenerator
from tpu_resiliency.telemetry.ring_buffer import RingView, SignalRings
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SECTION_PREFIX = "sec/"
DEVICE_PREFIX = "dev/"
PROGRAM_PREFIX = "prog/"
OP_PREFIX = "op/"


@dataclasses.dataclass(frozen=True)
class CallableId:
    """Identifies a method to wrap (reference ``straggler.py:34``)."""

    obj: Any
    name: str

    @property
    def display_name(self) -> str:
        owner = getattr(self.obj, "__name__", None) or type(self.obj).__name__
        return f"{owner}.{self.name}"


class _Section:
    """Yielded by ``detection_section``; lets user code register device outputs."""

    __slots__ = ("_observed",)

    def __init__(self):
        self._observed: list = []

    def observe(self, value):
        """Register jax arrays produced in this section for device-time blocking."""
        self._observed.append(value)
        return value


class Detector:
    """Class-level singleton, like the reference (``straggler/straggler.py:86``)."""

    initialized: bool = False
    rank: int = 0
    world_size: int = 1
    store = None
    profiling_interval: int = 1
    gather_on_rank0: bool = True
    scores_to_compute: tuple = ("relative_perf_scores", "individual_perf_scores")
    window: int = 128
    max_signals: int = 64

    _registry: Optional[NameRegistry] = None
    _signal_rings: Optional[SignalRings] = None
    _rings: dict = {}
    _entry_counts: dict = {}
    _interval_tracker: Optional[ReportIntervalTracker] = None
    _generator: Optional[ReportGenerator] = None
    _wrapped: list = []
    _use_pallas: bool = False
    _node_name: Optional[str] = None
    _mesh_telemetry = None  # Optional[MeshTelemetry]: the zero-gather report path

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def initialize(
        cls,
        scores_to_compute: Iterable[str] = ("relative_perf_scores", "individual_perf_scores"),
        gather_on_rank0: bool = True,
        profiling_interval: int = 1,
        report_time_interval: float = 60.0,
        *,
        rank: int = 0,
        world_size: int = 1,
        store=None,
        window: int = 128,
        max_signals: int = 64,
        use_pallas: bool = False,
        node_name: Optional[str] = None,
        device_telemetry=None,
    ) -> None:
        """``device_telemetry``: a :class:`~tpu_resiliency.telemetry.sharded.MeshTelemetry`
        whose rank axis spans the job (one row per Detector rank). When set — and the
        job runs one JAX process per rank (``jax.process_count() == world_size``) —
        ``generate_report`` skips the store summary gather entirely: the store carries
        only the name-column agreement, and per-rank summaries travel as shards of a
        mesh array reduced by ICI/DCN collectives (the north-star path)."""
        if cls.initialized:
            raise ResiliencyError("Detector already initialized")
        cls.initialized = True
        cls._mesh_telemetry = device_telemetry
        cls.scores_to_compute = tuple(scores_to_compute)
        cls.gather_on_rank0 = gather_on_rank0
        cls.profiling_interval = max(1, profiling_interval)
        cls.rank = rank
        cls.world_size = world_size
        cls.store = store
        cls.window = window
        cls.max_signals = max_signals
        cls._use_pallas = use_pallas
        cls._node_name = node_name
        cls._registry = NameRegistry(max_signals)
        # One pooled collector for every signal (single contiguous native block
        # when built); ring index == the registry's column id, so names and
        # storage stay aligned.
        cls._signal_rings = SignalRings(max_signals, window)
        cls._rings = {}
        cls._entry_counts = {}
        cls._wrapped = []
        cls._interval_tracker = ReportIntervalTracker(
            report_time_interval, store=store, world_size=world_size, rank=rank
        )
        cls._generator = ReportGenerator(
            world_size=world_size, max_signals=max_signals, use_pallas=use_pallas
        )

    @classmethod
    def shutdown(cls) -> None:
        for obj, name, orig in cls._wrapped:
            setattr(obj, name, orig)
        cls._wrapped = []
        cls._rings = {}
        cls._signal_rings = None
        cls._entry_counts = {}
        cls._registry = None
        cls._generator = None
        cls._interval_tracker = None
        cls._mesh_telemetry = None
        cls.store = None
        cls.initialized = False

    # -- recording ---------------------------------------------------------

    @classmethod
    def _ring(cls, signal: str) -> RingView:
        ring = cls._rings.get(signal)
        if ring is None:
            col = cls._registry.get(signal)  # reserve the column
            ring = cls._rings[signal] = cls._signal_rings.view(col)
        return ring

    @classmethod
    def _record(cls, signal: str, seconds: float) -> None:
        cls._ring(signal).push(seconds)

    @classmethod
    @contextmanager
    def detection_section(cls, name: str, profile_device: bool = True):
        """Time a block of code; optionally block on observed device outputs.

        Reference: ``detection_section`` ctx manager (``straggler.py:288-349``).
        """
        if not cls.initialized:
            raise ResiliencyError("Detector.initialize() must be called first")
        count = cls._entry_counts.get(name, 0)
        cls._entry_counts[name] = count + 1
        profile_now = profile_device and (count % cls.profiling_interval == 0)
        section = _Section()
        start = time.perf_counter_ns()
        try:
            yield section
        finally:
            host_elapsed = (time.perf_counter_ns() - start) * 1e-9
            cls._record(SECTION_PREFIX + name, host_elapsed)
            if profile_now and section._observed:
                import jax

                jax.block_until_ready(section._observed)
                dev_elapsed = (time.perf_counter_ns() - start) * 1e-9
                cls._record(DEVICE_PREFIX + name, dev_elapsed)

    @classmethod
    def wrap_callables(cls, callable_ids: Iterable[CallableId], profile_device: bool = True):
        """Monkey-patch methods into detection sections (reference ``straggler.py:368-400``).

        Wrapped callables auto-observe any jax arrays in their return value, so every
        ``profiling_interval``-th call records a device-inclusive duration.
        """
        for cid in callable_ids:
            orig = getattr(cid.obj, cid.name)
            section_name = cid.display_name

            def make_wrapper(orig_fn, sname):
                def wrapper(*args, **kwargs):
                    with cls.detection_section(sname, profile_device=profile_device) as sec:
                        out = orig_fn(*args, **kwargs)
                        if profile_device:
                            sec.observe(out)
                        return out

                wrapper.__name__ = getattr(orig_fn, "__name__", sname)
                wrapper.__wrapped__ = orig_fn
                return wrapper

            setattr(cid.obj, cid.name, make_wrapper(orig, section_name))
            cls._wrapped.append((cid.obj, cid.name, orig))

    @classmethod
    def record_program_samples(cls, samples: dict[str, list[float]]) -> None:
        """Feed per-compiled-program device times (``DeviceTimeProfiler.drain()``)
        into the scored matrix as ``prog/...`` signals — the CUPTI-kernel-summaries
        analogue (reference ``straggler.py:198-226`` kernel summaries)."""
        cls._record_samples(PROGRAM_PREFIX, samples)

    @classmethod
    def record_op_samples(cls, samples: dict[str, list[float]]) -> None:
        """Feed per-op/scope device times (``DeviceTimeProfiler.drain_ops()``,
        ``collect_ops=True``) into the scored matrix as ``op/...`` signals —
        one granularity below ``prog/...``, the closest XLA analogue of the
        reference's per-kernel CUPTI stream (``CuptiProfiler.cpp:168-203``;
        kernels themselves are fused away under XLA)."""
        cls._record_samples(OP_PREFIX, samples)

    @classmethod
    def _record_samples(cls, prefix: str, samples: dict[str, list[float]]) -> None:
        if not cls.initialized:
            raise ResiliencyError("Detector.initialize() must be called first")
        for name, secs in samples.items():
            ring = cls._ring(prefix + name)
            for sec in secs:
                ring.push(sec)

    # -- summaries ---------------------------------------------------------

    @classmethod
    def local_summary(cls) -> dict[str, dict[str, float | int]]:
        """Per-signal {median, total, count} from the host rings (one C-side pass
        per ring when the native collector is built)."""
        out = {}
        for name, ring in cls._rings.items():
            if len(ring):
                st = ring.stats()
                out[name] = {
                    "median": st["median"],
                    "total": st["total"],
                    "count": int(st["count"]),
                }
        return out

    @classmethod
    def _reset_rings(cls) -> None:
        for ring in cls._rings.values():
            ring.reset()
        # entry counts persist: profiling cadence continues across reports

    # -- report generation -------------------------------------------------

    COLUMNS_KEY = "telemetry/columns"

    @classmethod
    def _sync_columns(cls) -> tuple[str, ...]:
        """Agree on a global, append-only signal→column order via store CAS.

        Per-rank registries assign indices in first-use order, which differs across
        ranks; the mesh summary path aligns columns *positionally* in a sharded
        array, so it needs one authoritative order. A CAS loop appends locally-new
        names (sorted) to a single store tuple; every rank then adopts the same
        list. Append-only ⇒ per-column carried state (EWMA / historical min) in the
        MeshTelemetry stays valid across rounds and late joiners.
        """
        local = set(cls._rings)
        while True:
            cur = cls.store.try_get(cls.COLUMNS_KEY)
            cur_t = tuple(cur) if cur else ()
            missing = sorted(local - set(cur_t))
            if not missing:
                break
            ok, _ = cls.store.compare_set(cls.COLUMNS_KEY, cur, cur_t + tuple(missing))
            if ok:
                break
        cls.store.barrier("telemetry/columns_sync", cls.rank, cls.world_size, 300.0)
        return tuple(cls.store.get(cls.COLUMNS_KEY, timeout=60.0))

    @classmethod
    def _generate_mesh_report(cls, local: dict) -> Optional[Report]:
        """The zero-gather report path: store for column names only, summaries ride
        the mesh (``MeshTelemetry.score_local_summary``)."""
        mt = cls._mesh_telemetry
        names = cls._sync_columns()
        cap = mt.n_signals
        if len(names) > cap:
            # A report round must never take training down. The agreed column list
            # is identical on every rank (store CAS), so every rank makes this same
            # decision for this and all future rounds: drop to the store path.
            log.warning(
                f"{len(names)} signals exceed MeshTelemetry capacity {cap}; "
                "falling back to the store summary path permanently (raise the "
                "mesh signal capacity, or record fewer dynamic signals)"
            )
            cls._mesh_telemetry = None
            return None  # caller retries via the store path
        med = np.full((1, cap), np.inf, dtype=np.float32)
        wgt = np.zeros((1, cap), dtype=np.float32)
        cnt = np.zeros((1, cap), dtype=np.int32)
        col = {n: j for j, n in enumerate(names)}
        for n, st in local.items():
            j = col.get(n)
            if j is None:
                continue
            med[0, j] = st["median"]
            wgt[0, j] = st["total"]
            cnt[0, j] = st["count"]
        report = mt.report_from_summary(
            med, wgt, cnt, rank=cls.rank, signal_names=names
        )
        cls._reset_rings()
        if cls.gather_on_rank0 and cls.rank != 0:
            return None
        return report

    @classmethod
    def generate_report(cls) -> Optional[Report]:
        """Aggregate summaries across ranks and run the device scoring round.

        Multi-rank: every rank publishes its summary to the store, joins a barrier,
        then scores the global summary matrix on device (every rank gets the global
        view; ``gather_on_rank0`` only controls whether non-zero ranks build the full
        Report or return None, for API parity with the reference).
        Reference: ``generate_report`` (``straggler.py:228-245``).
        """
        if not cls.initialized:
            raise ResiliencyError("Detector.initialize() must be called first")
        import jax
        import jax.numpy as jnp

        local = cls.local_summary()
        if (
            cls._mesh_telemetry is not None
            and cls.store is not None
            and cls.world_size > 1
            and jax.process_count() == cls.world_size
        ):
            report = cls._generate_mesh_report(local)
            if cls._mesh_telemetry is not None:
                return report
            # Capacity fallback tripped mid-round: continue into the store path.
        if cls.store is not None and cls.world_size > 1:
            round_idx = cls._generator.iteration
            ns = f"telemetry/round/{round_idx}"
            cls._registry.publish(cls.store, key=f"{ns}/names")
            cls.store.set(f"{ns}/summary/{cls.rank}", local)
            cls.store.barrier(f"{ns}/publish", cls.rank, cls.world_size, 300.0)
            cls._registry.merge(cls.store, key=f"{ns}/names")
            # One batched fetch, not O(world) sequential round-trips; the barrier
            # above guarantees every rank's summary is present. (prefix_get keys
            # come back relative to the store *view*, so index by full key.)
            raw = cls.store.prefix_get(f"{ns}/summary/")
            summaries = [
                raw.get(f"{ns}/summary/{r}", {}) for r in range(cls.world_size)
            ]
            if cls.rank == 0 and round_idx > 0:
                # Everyone is past round round_idx-1 (they joined this round's
                # barrier), so its namespace is garbage; without this the store
                # grows for the job's lifetime. Trailing '/' keeps round 1 from
                # matching round 10.
                cls.store.prefix_clear(f"telemetry/round/{round_idx - 1}/")
        else:
            summaries = [local]

        names = cls._registry.names()
        s = len(names)
        if s == 0:
            return None
        r_world = max(cls.world_size, 1)
        medians = np.full((r_world, s), np.inf, dtype=np.float32)
        weights = np.zeros((r_world, s), dtype=np.float32)
        counts = np.zeros((r_world, s), dtype=np.int32)
        col = {n: j for j, n in enumerate(names)}
        for r, summary in enumerate(summaries):
            for n, st in summary.items():
                j = col.get(n)
                if j is None:
                    continue
                medians[r, j] = st["median"]
                weights[r, j] = st["total"]
                counts[r, j] = st["count"]

        report = cls._generator.generate_summary_report(
            jnp.asarray(medians), jnp.asarray(weights), jnp.asarray(counts), names,
            rank=cls.rank,
        )
        cls._reset_rings()
        if cls.gather_on_rank0 and cls.rank != 0:
            return None
        return report

    @classmethod
    def generate_report_if_interval_elapsed(cls) -> Optional[Report]:
        """Per-iteration hook (reference ``straggler.py:247-262``)."""
        cls._interval_tracker.iter_increase()
        if not cls._interval_tracker.is_interval_elapsed():
            return None
        return cls.generate_report()
