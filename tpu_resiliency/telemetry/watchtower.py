"""The SLO watchtower: metric-trajectory rules raising graded early warnings.

Everything upstream of this module detects faults *after* they trip a timeout
(the hang monitor, the health checks); the watchtower looks **forward**: it
retains short metric histories in bounded rings (``utils/timeseries.py``) and
evaluates declarative :class:`AlertRule`\\ s over them — goodput-SLO burn
rate, step-time anomaly (the pre-hang straggler early warning), store p95
regression, byte-flow residue, checkpoint-coverage staleness. Firing and
resolving emit ``alert_fired`` / ``alert_resolved`` events through the
standard bridge (→ ``tpu_alerts_total{rule,severity}`` /
``tpu_alerts_active``), and live state is served at ``GET /alerts``
(``tpu-alerts-1``, folded into ``/snapshot`` so fleetd aggregates it free).

Determinism contract (what makes ``tpu-alerts`` offline replay byte-exact):
the watchtower runs on **stream time**, never wall clock. Rings are fed by
direct per-kind taps that mirror the metrics bridge's derivations (per-pid
step chains under the shared ``step_gap_max_s`` cap, store-stats delta
discipline) without its shadow-registry cost — the refresh hot path pays
roughly the ledgers' own feed price, gated by the slow-marked <5% perf test
— and rule evaluation happens at deterministic stream-clock boundaries:
``observe()`` evaluates every elapsed ``eval_interval`` boundary *before*
ingesting the record that crossed it. Feed the same records in the same order
and you get the identical (rule, fire_ts, resolve_ts) sequence — which is
exactly how the offline replay reproduces a live run from its events JSONL.
The timer thread (:meth:`Watchtower.start`) only *pumps* the feed (tails the
events file via the injected poll function); it never advances the clock.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
from typing import Callable, List, Optional, Tuple

from tpu_resiliency.utils import events as tpu_events
from tpu_resiliency.utils.metrics import (
    MetricsRegistry,
    MetricsSink,
    flatten_event,
    step_gap_max_s,
)
from tpu_resiliency.utils.timeseries import (
    SeriesStore,
    mean_over_time,
    quantile_over_time,
    robust_zscore,
)

ALERTS_SCHEMA = "tpu-alerts-1"

#: JSON rule-override file: ``{"<rule>": {"severity": ..., "for_s": ...,
#: "disabled": ..., <param>: ...}}`` — overrides built-in rule parameters
#: without code.
ALERT_RULES_ENV = "TPU_RESILIENCY_ALERT_RULES"

#: Severity grades, most urgent first (the fleet feed's sort order).
SEVERITY_RANK = {"page": 0, "warn": 1, "info": 2}


@dataclasses.dataclass
class AlertRule:
    """One declarative rule: an expression over ring queries + hold-down.

    ``check(store, now, params)`` returns a human detail string while the
    condition holds and ``None`` while it doesn't; the engine owns the
    ok → pending (``for_s`` hold-down) → firing → resolved state machine.
    A crashing ``check`` degrades to an ``error`` field on the rule's row in
    the ``/alerts`` document — never an engine failure.
    """

    name: str
    check: Callable[[SeriesStore, float, dict], Optional[str]]
    severity: str = "warn"
    for_s: float = 0.0
    labels: dict = dataclasses.field(default_factory=dict)
    params: dict = dataclasses.field(default_factory=dict)


# -- built-in rule expressions ----------------------------------------------

def _check_goodput_burn(store: SeriesStore, now: float, p: dict):
    """Multiwindow SLO burn: error budget consumption over a fast AND a slow
    window of ``tpu_goodput_ratio`` (the classic page-on-fast, confirm-on-slow
    shape — a blip burns the fast window only, a real regression burns both).
    """
    budget = 1.0 - p["slo"]
    if budget <= 0:
        return None
    fast = store.query("tpu_goodput_ratio", start=now - p["fast_window_s"], end=now)
    slow = store.query("tpu_goodput_ratio", start=now - p["slow_window_s"], end=now)
    mf, ms = mean_over_time(fast), mean_over_time(slow)
    if mf is None or ms is None:
        return None
    burn_fast = (1.0 - mf) / budget
    burn_slow = (1.0 - ms) / budget
    if burn_fast >= p["fast_burn"] and burn_slow >= p["slow_burn"]:
        return (
            f"goodput SLO {p['slo']} burning: {burn_fast:.2f}x budget over "
            f"{p['fast_window_s']:g}s, {burn_slow:.2f}x over "
            f"{p['slow_window_s']:g}s"
        )
    return None


def _check_step_anomaly(store: SeriesStore, now: float, p: dict):
    """EWMA+MAD z-score over ``tpu_step_seconds``: the newest ``recent``
    steps must ALL sit ``z_max`` robust sigmas above the window median — a
    straggler slows steps minutes before the hang monitor's verdict, and this
    is the early warning that buys the controller that lead time."""
    s = store.query("tpu_step_seconds", start=now - p["window_s"], end=now)
    recent = int(p["recent"])
    if len(s) < int(p["min_samples"]) + recent:
        return None
    baseline, tail = s[:-recent], s[-recent:]
    zs = [robust_zscore(v, baseline) for _, v in tail]
    if any(z is None for z in zs):
        return None
    if min(zs) >= p["z_max"]:
        return (
            f"step time anomalous: last {recent} steps >= {p['z_max']:g} "
            f"robust sigmas over the {p['window_s']:g}s window "
            f"(z={max(zs):.1f}, step={tail[-1][1]:.3f}s)"
        )
    return None


def _check_store_p95(store: SeriesStore, now: float, p: dict):
    """Store op-latency regression: p95 of the recent mean-handle-latency
    samples (derived from ``store_stats`` deltas, the ``/storez`` op stats'
    stream twin) vs the p95 of the preceding baseline window."""
    recent = store.query(
        "tpu_store_mean_latency", start=now - p["window_s"], end=now
    )
    base = store.query(
        "tpu_store_mean_latency",
        start=now - p["baseline_window_s"], end=now - p["window_s"],
    )
    if len(recent) < int(p["min_samples"]) or len(base) < int(p["min_samples"]):
        return None
    r95 = quantile_over_time(recent, 0.95)
    b95 = quantile_over_time(base, 0.95)
    if b95 is None or b95 <= 0 or r95 is None:
        return None
    if r95 >= p["factor"] * b95 and r95 >= p["floor_s"]:
        return (
            f"store p95 regressed: {r95 * 1e6:.0f}us vs baseline "
            f"{b95 * 1e6:.0f}us (>= {p['factor']:g}x)"
        )
    return None


def _check_byteflow_residue(store: SeriesStore, now: float, p: dict):
    """Byte-flow ledger residue: the accounted ratio (the >= 0.95 acceptance
    gate, live) dropping under the floor means wire traffic the ledger can no
    longer attribute — an instrumentation gap, not a byte-economy win."""
    s = store.query(
        "tpu_byteflow_accounted_ratio", start=now - p["window_s"], end=now
    )
    if not s:
        return None
    ratio = s[-1][1]
    if ratio < p["min_ratio"]:
        return (
            f"byteflow residue: accounted_ratio {ratio:.3f} < "
            f"{p['min_ratio']:g} (unattributed wire bytes)"
        )
    return None


def _check_ckpt_staleness(store: SeriesStore, now: float, p: dict):
    """Checkpoint-coverage staleness: training steps are flowing but no
    durable save has landed within ``max_age_s`` — every additional step is
    uncovered work a restart would replay."""
    steps = store.query("tpu_step_seconds", start=now - p["window_s"], end=now)
    if not steps:
        return None  # idle job: nothing at risk
    saves = store.query("tpu_ckpt_saves", end=now)
    ref = saves[-1][0] if saves else steps[0][0]
    age = now - ref
    if age > p["max_age_s"]:
        return (
            f"checkpoint coverage stale: {age:.0f}s since last durable save "
            f"(> {p['max_age_s']:g}s) with steps still flowing"
        )
    return None


#: name → (check, severity, for_s, params) — the shipped rule table.
BUILTIN_RULES = {
    "goodput_burn": (_check_goodput_burn, "page", 0.0, {
        "slo": 0.90, "fast_window_s": 60.0, "slow_window_s": 600.0,
        "fast_burn": 2.0, "slow_burn": 1.0,
    }),
    "step_anomaly": (_check_step_anomaly, "page", 0.0, {
        "window_s": 600.0, "recent": 3, "min_samples": 8, "z_max": 6.0,
    }),
    "store_p95_regression": (_check_store_p95, "warn", 10.0, {
        "window_s": 60.0, "baseline_window_s": 600.0, "min_samples": 3,
        "factor": 3.0, "floor_s": 0.0005,
    }),
    "byteflow_residue": (_check_byteflow_residue, "warn", 30.0, {
        "window_s": 600.0, "min_ratio": 0.95,
    }),
    "ckpt_staleness": (_check_ckpt_staleness, "warn", 0.0, {
        "window_s": 600.0, "max_age_s": 1800.0,
    }),
}


def load_rule_overrides(
    path: Optional[str] = None,
) -> Tuple[dict, Optional[str]]:
    """Read the ``$TPU_RESILIENCY_ALERT_RULES`` JSON override file.

    Returns ``(overrides, error)`` — a bad file yields an empty override set
    plus the error string (surfaced on the ``/alerts`` document), never an
    exception: alert config must not take down telemetry.
    """
    path = path if path is not None else os.environ.get(ALERT_RULES_ENV)
    if not path:
        return {}, None
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("override document must be a JSON object")
        return doc, None
    except (OSError, ValueError) as e:
        return {}, f"{path}: {e}"


def default_rules(overrides: Optional[dict] = None) -> List[AlertRule]:
    """The built-in rule table, with per-rule overrides applied.

    Override shape per rule name: ``severity`` / ``for_s`` / ``labels`` /
    ``disabled`` adjust the envelope; any other key overrides that rule's
    expression parameter. Unknown rule names and unknown parameter keys are
    ignored (forward compatibility beats a hard failure here).
    """
    overrides = overrides or {}
    rules = []
    for name, (check, severity, for_s, params) in BUILTIN_RULES.items():
        ov = overrides.get(name)
        ov = dict(ov) if isinstance(ov, dict) else {}
        if ov.pop("disabled", False):
            continue
        merged = dict(params)
        severity = str(ov.pop("severity", severity))
        for_s = float(ov.pop("for_s", for_s))
        labels = ov.pop("labels", None)
        merged.update({
            k: v for k, v in ov.items() if k in params
        })
        rules.append(AlertRule(
            name=name, check=check, severity=severity, for_s=for_s,
            labels=dict(labels) if isinstance(labels, dict) else {},
            params=merged,
        ))
    return rules


class Watchtower:
    """The rule engine: rings + stream clock + alert state machine.

    Feed it flat event records (JSONL dicts or flattened Events) through
    :meth:`observe` — from the telemetry server's events tail live, from a
    file replay offline, from an in-process :class:`WatchtowerSink` in tests.
    """

    def __init__(
        self,
        rules: Optional[List[AlertRule]] = None,
        *,
        eval_interval: float = 5.0,
        ring_capacity: int = 512,
        emit: Optional[Callable[[str, dict], None]] = None,
        history_limit: int = 256,
        job: Optional[str] = None,
    ):
        if rules is None:
            overrides, err = load_rule_overrides()
            rules = default_rules(overrides)
            self.config_error = err
        else:
            self.config_error = None
        self.rules = list(rules)
        self.eval_interval = float(eval_interval)
        self.job = job
        self.store = SeriesStore(capacity=ring_capacity)
        self._emit = emit if emit is not None else self._default_emit
        self._tap_steps: dict = {}   # pid -> (ts, iteration) step-chain state
        self._tap_ckpt = 0           # cumulative ckpt_saved count
        self._tap_store_ops = 0.0    # store_stats deltas pending a sample
        self._tap_store_secs = 0.0
        self._states = {
            r.name: {
                "state": "ok", "since": None, "fire_ts": None,
                "detail": None, "error": None, "fired_total": 0,
            }
            for r in self.rules
        }
        self._history: collections.deque = collections.deque(maxlen=history_limit)
        self._hwm: Optional[float] = None
        self._next_eval: Optional[float] = None
        self._evals = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @staticmethod
    def _default_emit(kind: str, payload: dict) -> None:
        tpu_events.record("watchtower", kind, **payload)

    # -- feed --------------------------------------------------------------

    def observe(self, rec: dict) -> List[dict]:
        """Ingest one record; returns the alert transitions it caused.

        Clock discipline: every ``eval_interval`` boundary the record's ``ts``
        has passed is evaluated BEFORE the record lands in the rings, so ring
        contents at each boundary are a pure function of record order — the
        replay-parity invariant. A pathological stream gap (> 256 boundaries)
        snaps the clock forward rather than looping; the snap depends only on
        the stream, so replays still agree.
        """
        if not isinstance(rec, dict):
            return []
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            return []
        transitions: List[dict] = []
        with self._lock:
            if self._next_eval is None:
                self._next_eval = ts + self.eval_interval
            guard = 0
            while ts >= self._next_eval and guard < 256:
                transitions.extend(self._evaluate_locked(self._next_eval))
                self._next_eval += self.eval_interval
                guard += 1
            if ts >= self._next_eval:
                self._next_eval = ts + self.eval_interval
            self._hwm = ts if self._hwm is None else max(self._hwm, ts)
            self._ingest_locked(rec, ts)
        for tr in transitions:
            try:
                self._emit(tr["kind"], {k: v for k, v in tr.items() if k != "kind"})
            except Exception:
                pass  # observability, not control flow
        return transitions

    def observe_many(self, records) -> List[dict]:
        out = []
        for rec in records:
            out.extend(self.observe(rec))
        return out

    def _ingest_locked(self, rec: dict, ts: float) -> None:
        # Direct taps on the handful of kinds the rules window over — the
        # SAME derivations the metrics bridge performs (per-pid step chains
        # under the shared gap cap, store-stats delta discipline), inlined so
        # the refresh hot path stays cheap: this runs per record, a full
        # ``observe_record`` into a shadow registry measured ~10x the
        # ledgers' own feed cost. Gauges sample straight from the record to
        # stay on stream time (the registry's gauges stamp wall clock).
        kind = rec.get("kind")
        if kind == "iteration_start":
            # A step = strictly-consecutive iteration within the gap cap;
            # repeats (in-process restart) and long gaps are downtime.
            it = rec.get("iteration")
            if isinstance(it, int):
                pid = rec.get("pid")
                prev = self._tap_steps.get(pid)
                if (
                    prev is not None and it == prev[1] + 1
                    and 0 < ts - prev[0] <= step_gap_max_s()
                ):
                    self.store.observe("tpu_step_seconds", ts, ts - prev[0])
                self._tap_steps[pid] = (ts, it)
        elif kind == "goodput_update":
            if isinstance(rec.get("ratio"), (int, float)):
                self.store.observe("tpu_goodput_ratio", ts, rec["ratio"])
        elif kind == "byteflow_update":
            if isinstance(rec.get("accounted_ratio"), (int, float)):
                self.store.observe(
                    "tpu_byteflow_accounted_ratio", ts, rec["accounted_ratio"]
                )
        elif kind == "ckpt_saved":
            # Cumulative save count at save ts (counter semantics: rate()
            # over the ring gives saves/s; last() gives the freshness tap).
            self._tap_ckpt += 1
            self.store.observe("tpu_ckpt_saves", ts, float(self._tap_ckpt))
        elif kind == "store_stats":
            # The store emits movement-since-last-emit deltas; seconds from
            # an ops-less emit stay pending until ops arrive, matching the
            # cumulative-counter diff the metrics bridge would see.
            ops = rec.get("ops")
            if isinstance(ops, dict):
                self._tap_store_ops += sum(
                    n for n in ops.values()
                    if isinstance(n, (int, float)) and n > 0
                )
            secs = rec.get("op_seconds")
            if isinstance(secs, dict):
                self._tap_store_secs += sum(
                    s for s in secs.values()
                    if isinstance(s, (int, float)) and s > 0
                )
            if self._tap_store_ops > 0:
                self.store.observe(
                    "tpu_store_mean_latency", ts,
                    max(0.0, self._tap_store_secs) / self._tap_store_ops,
                )
                self._tap_store_ops = 0.0
                self._tap_store_secs = 0.0

    # -- evaluation --------------------------------------------------------

    def _evaluate_locked(self, now: float) -> List[dict]:
        self._evals += 1
        out: List[dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            try:
                detail = rule.check(self.store, now, rule.params)
                st["error"] = None
            except Exception as e:
                # A crashing rule degrades to an error row on /alerts — the
                # other rules, the engine, and the endpoint keep working.
                st["error"] = repr(e)
                continue
            if detail is not None:
                if st["state"] == "ok":
                    st.update(state="pending", since=now, detail=detail)
                if st["state"] == "pending" and now - st["since"] >= rule.for_s:
                    st.update(state="firing", fire_ts=now, detail=detail)
                    st["fired_total"] += 1
                    out.append(self._transition_locked(
                        "alert_fired", rule, st, now,
                    ))
                elif st["state"] == "firing":
                    st["detail"] = detail
            else:
                if st["state"] == "firing":
                    out.append(self._transition_locked(
                        "alert_resolved", rule, st, now,
                    ))
                st.update(state="ok", since=None, fire_ts=None, detail=None)
        return out

    def _transition_locked(
        self, kind: str, rule: AlertRule, st: dict, now: float
    ) -> dict:
        tr = {
            "kind": kind, "rule": rule.name, "severity": rule.severity,
            "for_s": rule.for_s, "fire_ts": st["fire_ts"], "detail": st["detail"],
        }
        if rule.labels:
            tr["labels"] = dict(rule.labels)
        if kind == "alert_resolved":
            tr["resolve_ts"] = now
            tr["duration_s"] = round(now - st["fire_ts"], 6)
        self._history.append(dict(tr))
        return tr

    # -- serving -----------------------------------------------------------

    def active_alerts(self) -> List[dict]:
        """Currently-firing alerts, severity-ranked — the ``ControllerView``
        input that lets a page-grade early warning bias the autoscale
        decision ahead of the hang verdict."""
        with self._lock:
            rows = [
                {
                    "rule": r.name, "severity": r.severity,
                    "fire_ts": st["fire_ts"], "for_s": r.for_s,
                    "detail": st["detail"], "labels": dict(r.labels),
                }
                for r in self.rules
                for st in (self._states[r.name],)
                if st["state"] == "firing"
            ]
        rows.sort(key=lambda a: (SEVERITY_RANK.get(a["severity"], 9), a["rule"]))
        return rows

    def status(self) -> dict:
        """The ``GET /alerts`` document (``tpu-alerts-1``)."""
        with self._lock:
            rules = [
                {
                    "name": r.name, "severity": r.severity, "for_s": r.for_s,
                    "state": st["state"], "since": st["since"],
                    "fire_ts": st["fire_ts"], "detail": st["detail"],
                    "error": st["error"], "fired_total": st["fired_total"],
                    "params": dict(r.params),
                }
                for r in self.rules
                for st in (self._states[r.name],)
            ]
            doc = {
                "schema": ALERTS_SCHEMA,
                "clock": {
                    "hwm": self._hwm, "next_eval": self._next_eval,
                    "eval_interval": self.eval_interval, "evals": self._evals,
                },
                "rules": rules,
                "history": list(self._history)[-50:],
                "rings": self.store.sizes(),
            }
        if self.job is not None:
            doc["job"] = self.job
        if self.config_error:
            doc["config_error"] = self.config_error
        doc["active"] = self.active_alerts()
        return doc

    # -- the timer thread --------------------------------------------------

    def start(
        self,
        poll_fn: Optional[Callable[[], object]] = None,
        interval: float = 2.0,
    ) -> None:
        """Pump the feed on a timer: ``poll_fn`` (typically the telemetry
        server's ``refresh``, which tails the events file into
        :meth:`observe`) runs every ``interval`` seconds so alerts fire and
        resolve even when nobody is scraping. The thread never advances the
        stream clock itself — determinism lives in :meth:`observe`."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(interval):
                if poll_fn is not None:
                    try:
                        poll_fn()
                    except Exception:
                        pass  # the next tick retries

        self._thread = threading.Thread(
            target=run, name="watchtower", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


class WatchtowerSink(MetricsSink):
    """``events.add_sink`` bridge feeding a :class:`Watchtower` in-process.

    Flattens Events exactly like :class:`MetricsSink` (the shared
    ``flatten_event``, including the ``p_``-rename of envelope-colliding
    payload keys) so the sink-fed live path and a JSONL replay see the SAME
    record shapes — the live/post-hoc parity contract.
    """

    def __init__(self, watchtower: Watchtower, registry=None):
        super().__init__(
            registry=registry if registry is not None else MetricsRegistry()
        )
        self.watchtower = watchtower

    def __call__(self, event) -> None:
        self.watchtower.observe(flatten_event(event))


def replay(
    records,
    rules: Optional[List[AlertRule]] = None,
    *,
    eval_interval: float = 5.0,
    ring_capacity: int = 512,
) -> Tuple[Watchtower, List[dict]]:
    """Run the engine over a finished stream; returns ``(tower, sequence)``.

    The sequence is every transition in stream order — what ``tpu-alerts``
    renders offline and what the chaos campaign byte-compares against the
    ``alert_fired`` / ``alert_resolved`` events the live run recorded.
    Recorded alert events in the input stream are inert here (they only feed
    the private registry's event counter), so replaying a live stream does
    not double-fire.
    """
    sequence: List[dict] = []
    tower = Watchtower(
        rules=rules, eval_interval=eval_interval, ring_capacity=ring_capacity,
        emit=lambda kind, payload: sequence.append({"kind": kind, **payload}),
    )
    tower.observe_many(records)
    return tower, sequence
