"""Sharded coordination-store clique: one keyspace over N server processes.

One :class:`~tpu_resiliency.platform.store.KVServer` is a single-threaded
event loop — by design (no locks, parked continuations instead of blocked
threads), and measured flat in *connection* count, but its op throughput is
one core's dict-op rate. At 4096 ranks every subsystem's traffic (rendezvous
CAS, barrier storms, heartbeat touches, metrics pushes, reshard
holder-gathers) funnels through that one loop and queue wait dominates —
``BENCH_store_baseline.json``'s 37 µs → 3.3 ms p50 curve from 1 → 64 clients
is that funnel.

This module scales the plane *horizontally* without touching the wire
protocol or the server: a **clique** of ordinary ``KVServer`` processes plus
a client-side deterministic key→shard map. :class:`ShardedKVClient` exposes
the exact :class:`~tpu_resiliency.platform.store.KVClient` surface;
single-key ops route by ``crc32(key) % nshards`` (stable across processes
and Python runs — never ``hash()``, which is salted per process), and the
prefix/scan ops fan out to every shard and merge. Three properties make the
layering safe with zero server changes:

- **Barriers and parks are shard-local by construction**: a barrier name, a
  watched key, and a parked ``get`` all hash to exactly one shard, so the
  server-side wait/notify machinery never spans shards.
- **The at-most-once dedup ladder is per shard for free**: each shard is
  served by its own underlying ``KVClient``, whose ``req_id`` nonces and
  retry budget apply against that shard's dedup LRU; a retry can only replay
  against the shard that saw the original.
- **Circuit breakers are per endpoint already** (keyed ``(host, port)`` in
  ``platform/store.py``), so one dead shard fails fast without poisoning the
  others' budgets.

A 1-shard clique degenerates to today's layout exactly — same keys, same
server, one persistent connection — which is the version-skew contract
``tests/platform/test_store_skew.py`` pins.

Discovery: the launcher exports ``$TPU_RESILIENCY_STORE_SHARDS`` as a
comma-separated ``host:port`` list (shard order IS the hash order — every
client must see the identical list); :func:`connect_store` honors it and
falls back to the classic single-endpoint env pair.

**HA (successor replication).** With ``replicate=True`` (launcher
``--store-replicate`` → ``$TPU_RESILIENCY_STORE_REPLICATE``) every key is
written to its primary shard ``h = crc32(key) % N`` *and* to the successor
``(h + 1) % N``. The double-write is safe precisely because of the existing
machinery: idempotent ops replay harmlessly, and non-idempotent ops
(``add``, ``barrier``) ride each shard's own req_id dedup LRU — the replica
copy is an independent dedup'd call, not a replayed frame. On shard
transport failure (retry budget exhausted → circuit breaker open) the
client fails over reads, watch-parks, barriers, and dedup'd mutations to
the successor, emitting ``store_failover`` events →
``tpu_store_failover_total{shard,outcome}``. Barrier arrivals are mirrored
(a non-blocking replica join precedes every primary join), so a shard
SIGKILLed mid-round strands nobody: stragglers fail over and the
successor's mirrored count releases the round exactly once per joiner.
A 1-shard clique with replication enabled degenerates exactly: successor ==
primary, so every mirror branch is skipped (zero double-writes).

**Live resharding (epoch protocol).** A clique changes size — or replaces a
dead shard with a fresh ``KVServer`` — through an epoch'd shard map CAS'd
under the raw :data:`EPOCH_KEY` on shard 0 (mirrored to its successor and
to the new map's shard 0). :func:`reshard_clique` bumps the epoch with
``prev`` set (the dual-route window), migrates the value keyspace by
concurrent prefix scan, then settles (``prev: None``). Clients never poll:
they probe the epoch key only when an op exhausts both primary and
successor, adopt any newer map, and retry once. During the window writes go
to the new map *and* write-through to the old primary, reads fall back to
the old map on a miss, and barriers stay on the old map — so old-map and
new-map clients interoperate until settle. A client that cannot find a
usable newer map fails closed with the original transport error (or a
descriptive :class:`StoreError` when the epoch document is malformed).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import zlib
from typing import Any, Iterable, Optional

from tpu_resiliency.exceptions import (
    BarrierOverflow,
    BarrierTimeout,
    StoreError,
    StoreTransportError,
)
from tpu_resiliency.platform.store import (
    AUTH_KEY_ENV,
    KVClient,
    KVServer,
    StoreView,
    breaker_open,
    store_answers,
)
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SHARDS_ENV = "TPU_RESILIENCY_STORE_SHARDS"

#: "1"/"true"/"on" turns on successor replication for every clique client
#: built from the environment (the launcher's ``--store-replicate`` export).
REPLICATE_ENV = "TPU_RESILIENCY_STORE_REPLICATE"

#: Reserved raw key on shard 0 where a clique's spawner publishes the full
#: endpoint list. A client handed only the classic ``host:port`` endpoint
#: (another agent, a diagnostic tool) probes it once and, if present,
#: reconnects as a sharded client — late joiners cannot split the keyspace
#: by talking to shard 0 alone.
CLIQUE_KEY = "store-clique/endpoints"

#: Reserved raw key carrying the CAS'd epoch'd shard map (live resharding).
#: Anchored on the *old* map's shard 0, mirrored to its successor and to the
#: new map's shard 0 — reachable from either side of a transition.
EPOCH_KEY = "store-clique/epoch"

#: keyspace-hash identity carried in every aggregated stats doc — a client
#: and a doc reader disagreeing on the hash would mis-attribute per-shard load
SHARD_HASH = "crc32"


def shard_of(key: str, nshards: int) -> int:
    """Deterministic key→shard index. ``crc32`` of the UTF-8 key: stable
    across processes, runs, and machines (``hash()`` is per-process salted
    and would scatter one job's clients across disagreeing maps)."""
    if nshards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8", "surrogatepass")) % nshards


def successor_of(shard: int, nshards: int) -> int:
    """The replica shard for a key whose primary is ``shard`` — the next
    shard on the hash ring. Degenerates to the primary itself at N=1, which
    is what makes 1-shard replication an exact no-op."""
    if nshards <= 1:
        return 0
    return (shard + 1) % nshards


def replicate_from_env() -> bool:
    return os.environ.get(REPLICATE_ENV, "").strip().lower() in ("1", "true", "on")


def parse_endpoints(spec: str) -> list[tuple[str, int]]:
    """``"host:port,host:port"`` → ``[(host, port), ...]`` (shard order)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port_s = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port_s)))
    if not out:
        raise ValueError(f"no endpoints in shard spec {spec!r}")
    return out


def format_endpoints(endpoints: Iterable[tuple[str, int]]) -> str:
    return ",".join(f"{h}:{p}" for h, p in endpoints)


class ShardedKVClient:
    """Drop-in :class:`KVClient` over a store clique.

    Single-key ops route by :func:`shard_of`; prefix/scan/census ops fan out
    to every shard CONCURRENTLY (a small persistent pool, one worker per
    shard) and merge — shards hold disjoint keys, so the merged result is
    identical whichever shard answers first, and a serial fan-out was paying
    ``nshards`` sequential round trips on every reshard holder-gather and
    census (the PR-14 headroom note). Determinism is preserved: results
    merge in shard order, and when several shards fail the FIRST shard's
    error (by shard index) surfaces, after that shard's own retry budget and
    breaker — exactly the serial contract. Thread-safe to the same degree as
    ``KVClient`` (each underlying client locks its own persistent socket).
    """

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
        replicate: bool | None = None,
    ):
        if not endpoints:
            raise ValueError("ShardedKVClient needs at least one endpoint")
        self.endpoints = [tuple(e) for e in endpoints]
        self.default_timeout = timeout
        self._connect_retries = connect_retries
        self._retry_budget = retry_budget
        #: successor replication (module doc): None defers to the launcher's
        #: $TPU_RESILIENCY_STORE_REPLICATE export.
        self._replicate = replicate_from_env() if replicate is None else bool(replicate)
        # HA bookkeeping: client-side failover tallies per failed shard
        # (folded into store_stats → merge_stats_docs so degraded-mode ops
        # land under the successor's row instead of vanishing), and the last
        # released generation per barrier name (the failover join's "already
        # released on the replica?" baseline).
        self._ha_lock = threading.Lock()
        self._failover_counts: dict[int, dict[str, int]] = {}
        self._barrier_gen: dict[str, int] = {}
        # Epoch'd shard map (live resharding): adopted lazily — probed only
        # when an op exhausts both primary and successor, never on a timer.
        self._epoch = 0
        self._epoch_checked_at = 0.0
        self._prev_client: Optional["ShardedKVClient"] = None
        # Set on clients built to speak a PREVIOUS map (dual-route window):
        # they must never adopt epochs themselves, or a write-through whose
        # old-map shard is dead chains prev→prev→prev adoption without bound.
        self._epoch_frozen = False
        # Per-shard clients are built LAZILY on first use: a clique client
        # must stay constructible while one shard is down (diagnostics
        # against a degraded clique, ops that never touch the dead shard).
        # The failure surfaces on the op that actually needs the shard —
        # after that shard's own connect ladder/breaker — and a later op
        # retries construction, so a restarted shard is picked up in place.
        self._shards: list[Optional[KVClient]] = [None] * len(self.endpoints)
        self._shards_lock = threading.Lock()
        self._fan_pool = None  # lazy; one worker per shard
        self._closed = False
        # Single-endpoint compatibility surface (diagnostics, logs).
        self.host, self.port = self.endpoints[0]
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        self.auth_key = auth_key

    @property
    def nshards(self) -> int:
        return len(self._shards)

    def _shard(self, i: int) -> KVClient:
        s = self._shards[i]
        if s is not None:
            return s
        with self._shards_lock:
            if self._closed:
                raise StoreError("store client is closed")
            s = self._shards[i]
            if s is None:
                h, p = self.endpoints[i]
                s = self._shards[i] = KVClient(
                    h, p, timeout=self.default_timeout,
                    connect_retries=self._connect_retries,
                    auth_key=self.auth_key, retry_budget=self._retry_budget,
                )
        return s

    def _for(self, key: str) -> KVClient:
        return self._shard(shard_of(key, len(self._shards)))

    def _live_shards(self) -> list[KVClient]:
        return [self._shard(i) for i in range(len(self.endpoints))]

    # -- HA routing (successor replication + failover) ---------------------

    def _route(self, key: str) -> tuple[int, int]:
        """(primary, successor) shard indices for ``key``. Successor equals
        primary when replication is off or the clique has one shard — every
        mirror/failover branch below keys off that equality."""
        n = len(self._shards)
        p = shard_of(key, n)
        if not self._replicate:
            return p, p
        return p, successor_of(p, n)

    def _emit_failover(self, shard: int, op: str, outcome: str) -> None:
        with self._ha_lock:
            per = self._failover_counts.setdefault(shard, {})
            per[outcome] = per.get(outcome, 0) + 1
        try:
            h, p = self.endpoints[shard]
            record_event(
                "store", "store_failover", shard=shard, op=op,
                outcome=outcome, endpoint=f"{h}:{p}",
                successor=successor_of(shard, len(self._shards)),
            )
        except Exception:
            pass

    def _breaker_tripped(self, shard: int) -> bool:
        h, p = self.endpoints[shard]
        return breaker_open(h, p)

    def _ha_read(self, key: str, op: str, fn):
        """Run ``fn(shard_client)`` against the key's primary, failing over
        to the successor replica on transport failure (or straight to it when
        the primary's breaker is already open). On total exhaustion, probe
        for a newer clique epoch once and retry on the adopted map."""
        for attempt in (0, 1):
            p, s = self._route(key)
            if s != p and self._breaker_tripped(p) and not self._breaker_tripped(s):
                self._emit_failover(p, op, "read")
                return fn(self._shard(s))
            try:
                return fn(self._shard(p))
            except StoreTransportError:
                if s != p:
                    self._emit_failover(p, op, "read")
                    try:
                        return fn(self._shard(s))
                    except StoreTransportError:
                        pass
                if attempt == 0 and self._maybe_adopt_epoch():
                    continue
                raise

    def _ha_write(self, key: str, op: str, fn):
        """Apply ``fn`` to the key's primary (successor failover on transport
        failure) and mirror it to the successor replica. ``fn`` runs as a
        fresh call per shard, so non-idempotent ops (``add``) get their own
        req_id against each shard's dedup LRU — the mirror is an independent
        dedup'd call, never a replayed frame."""
        for attempt in (0, 1):
            p, s = self._route(key)
            primary_dead = s != p and self._breaker_tripped(p) and not self._breaker_tripped(s)
            if not primary_dead:
                try:
                    r = fn(self._shard(p))
                except StoreTransportError:
                    primary_dead = s != p
                    if not primary_dead:
                        if attempt == 0 and self._maybe_adopt_epoch():
                            continue
                        raise
            if primary_dead:
                # The successor copy IS the write now; the primary picks the
                # key back up at the next epoch transition (reshard/replace).
                self._emit_failover(p, op, "mutate")
                try:
                    r = fn(self._shard(s))
                except StoreTransportError:
                    if attempt == 0 and self._maybe_adopt_epoch():
                        continue
                    raise
                self._write_through_prev(op, fn)
                return r
            if s != p:
                if self._breaker_tripped(s):
                    # Dead successor: skip the mirror outright instead of
                    # paying the retry ladder on every write until the
                    # breaker's next half-open probe.
                    self._emit_failover(s, op, "replica_skipped")
                else:
                    try:
                        fn(self._shard(s))
                    except StoreError:
                        # Replica temporarily behind: degrade the mirror,
                        # never the caller's (primary-acknowledged) write.
                        self._emit_failover(s, op, "replica_skipped")
            self._write_through_prev(op, fn)
            return r

    def _write_through_prev(self, op: str, fn) -> None:
        """Dual-route window (mid-reshard): a new-map write also lands on the
        previous map so pre-epoch clients keep reading fresh values until the
        transition settles. Contained — the old map may be half torn down."""
        prev = self._prev_client
        if prev is None:
            return
        try:
            fn(prev)
        except StoreError:
            pass

    def _prev_try_get(self, key: str, sentinel):
        """Dual-route read fallback: a key not yet migrated to the new map is
        still live on the previous one."""
        prev = self._prev_client
        if prev is None:
            return sentinel
        try:
            return prev.try_get(key, sentinel)
        except StoreError:
            return sentinel

    # -- epoch'd shard map (live resharding) -------------------------------

    def _epoch_anchors(self) -> list[int]:
        """Shard indices the epoch document is probed on: shard 0 and (when
        replicating) its successor — the two places a transition's author
        anchored it relative to *this* client's map."""
        n = len(self._shards)
        return [0] if (n == 1 or not self._replicate) else [0, successor_of(0, n)]

    def _read_epoch_doc(self) -> Optional[dict]:
        for i in self._epoch_anchors():
            try:
                doc = self._shard(i).try_get(EPOCH_KEY)
            except StoreError:
                continue
            if doc is not None:
                return doc
        return None

    def _maybe_adopt_epoch(self, min_interval: float = 1.0) -> bool:
        """Probe for a newer clique epoch and adopt it: rebuild the shard
        list, hold the previous map for dual-routing while the transition is
        unsettled (``prev`` present), drop it once settled. Called only from
        transport-failure exhaustion paths (rate-limited), so the healthy
        path never pays an epoch round trip. True ⇒ the caller should
        re-resolve routing and retry its op once.

        Fail-closed contract: a *malformed* epoch document (the clique moved
        to a map this client cannot parse) raises a descriptive
        :class:`StoreError`; an absent/unreachable document returns False and
        the caller re-raises its original transport error."""
        if self._epoch_frozen:
            return False
        now = time.monotonic()
        with self._ha_lock:
            if now - self._epoch_checked_at < min_interval:
                return False
            self._epoch_checked_at = now
        doc = self._read_epoch_doc()
        if doc is None:
            return False
        if not isinstance(doc, dict) or not isinstance(doc.get("epoch"), int) \
                or not doc.get("endpoints"):
            raise StoreError(
                f"clique epoch document under {EPOCH_KEY!r} is malformed "
                f"({doc!r}): the clique resharded to a map this client "
                f"cannot follow — reconnect via the launcher's current "
                f"shard spec"
            )
        settled = not doc.get("prev")
        with self._ha_lock:
            if doc["epoch"] < self._epoch or (
                doc["epoch"] == self._epoch
                and not (settled and self._prev_client is not None)
            ):
                return False
            new_eps = [tuple(e) for e in doc["endpoints"]]
            changed = new_eps != self.endpoints
            old_shards, old_pool = [], None
            if changed:
                old_shards, self._shards = self._shards, [None] * len(new_eps)
                old_pool, self._fan_pool = self._fan_pool, None
                old_prev, self._prev_client = self._prev_client, None
                self.endpoints = new_eps
                self.host, self.port = self.endpoints[0]
                if not settled:
                    # Dual-route window: keep one plain (non-replicating)
                    # client on the previous map for fallbacks/write-through.
                    self._prev_client = ShardedKVClient(
                        [tuple(e) for e in doc["prev"]],
                        timeout=self.default_timeout,
                        connect_retries=1, auth_key=self.auth_key,
                        retry_budget=0.0, replicate=False,
                    )
                    self._prev_client._epoch_frozen = True
            elif settled and self._prev_client is not None:
                old_prev, self._prev_client = self._prev_client, None
            else:
                old_prev = None
            self._epoch = doc["epoch"]
            if "replicate" in doc:
                self._replicate = bool(doc["replicate"])
        for c in [*old_shards, old_prev]:
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass
        if old_pool is not None:
            old_pool.shutdown(wait=False)
        record_event(
            "store", "shard_epoch", epoch=doc["epoch"],
            nshards=len(doc["endpoints"]),
            outcome="adopted" if changed else "settled",
        )
        log.info(
            f"adopted clique epoch {doc['epoch']}: "
            f"{format_endpoints(self.endpoints)}"
            + ("" if settled else " (dual-route window)")
        )
        return True

    def _fan_out(self, fn, contain: bool = False) -> list:
        """Run ``fn(shard_client)`` on every shard concurrently; results in
        shard order. With ``contain=False`` the lowest-indexed shard's
        exception propagates (the serial-era contract); ``contain=True``
        returns the exception object in that shard's slot instead (the
        stats path degrades rows, never the document)."""
        def run(i: int):
            # Shard construction happens INSIDE the task: a dead shard's
            # connect ladder neither blocks the other shards' ops nor (when
            # contained) escapes its own slot.
            return fn(self._shard(i))

        if len(self.endpoints) == 1:
            try:
                return [run(0)]
            except Exception as e:
                if contain:
                    return [e]
                raise
        with self._shards_lock:
            if self._fan_pool is None:
                if self._closed:
                    raise StoreError("store client is closed")
                import concurrent.futures as cf

                self._fan_pool = cf.ThreadPoolExecutor(
                    max_workers=len(self.endpoints),
                    thread_name_prefix="store-fan",
                )
            pool = self._fan_pool
        futs = [pool.submit(run, i) for i in range(len(self.endpoints))]
        results: list = []
        first_err: Optional[BaseException] = None
        for f in futs:
            try:
                results.append(f.result())
            except Exception as e:
                if contain:
                    results.append(e)
                else:
                    results.append(None)
                    if first_err is None:
                        first_err = e
        if first_err is not None:
            raise first_err
        return results

    def close(self) -> None:
        with self._shards_lock:
            self._closed = True
            shards, self._shards = self._shards, [None] * len(self.endpoints)
            pool, self._fan_pool = self._fan_pool, None
        prev, self._prev_client = self._prev_client, None
        if pool is not None:
            pool.shutdown(wait=False)
        for s in [*shards, prev]:
            if s is None:
                continue
            try:
                s.close()
            except Exception:
                pass

    # -- keyed ops (route by hash; replicated + failover per module doc) ---

    _MISS = object()  # dual-route miss sentinel

    def set(self, key: str, value: Any) -> None:
        self._ha_write(key, "set", lambda s: s.set(key, value))

    def get(self, key: str, timeout: float | None = None) -> Any:
        if self._prev_client is not None:
            # Dual-route window: a not-yet-migrated key would park the
            # blocking get on the new map while its value sits on the old.
            v = self._ha_read(
                key, "get", lambda s: s.try_get(key, self._MISS)
            )
            if v is self._MISS:
                v = self._prev_try_get(key, self._MISS)
            if v is not self._MISS:
                return v
        return self._ha_read(key, "get", lambda s: s.get(key, timeout))

    def try_get(self, key: str, default: Any = None) -> Any:
        v = self._ha_read(key, "try_get", lambda s: s.try_get(key, self._MISS))
        if v is self._MISS:
            v = self._prev_try_get(key, self._MISS)
        return default if v is self._MISS else v

    def delete(self, key: str) -> bool:
        return self._ha_write(key, "delete", lambda s: s.delete(key))

    def add(self, key: str, amount: int = 1) -> int:
        # Non-idempotent, but each shard call carries its own req_id against
        # that shard's dedup LRU — the mirror keeps the replica's total in
        # lockstep so a failover read of the counter is exact.
        return self._ha_write(key, "add", lambda s: s.add(key, amount))

    def compare_set(self, key: str, expected: Any, desired: Any) -> tuple[bool, Any]:
        # CAS linearizes on the primary; the replica converges via an
        # unconditional set of the winning value (losers don't mirror), so a
        # failed-over CAS chain resumes from (at worst) a recent committed
        # value and the state machine's own CAS semantics re-converge.
        for attempt in (0, 1):
            p, s = self._route(key)
            primary_dead = s != p and self._breaker_tripped(p) and not self._breaker_tripped(s)
            target = s if primary_dead else p
            if primary_dead:
                self._emit_failover(p, "cas", "mutate")
            try:
                ok, cur = self._shard(target).compare_set(key, expected, desired)
            except StoreTransportError:
                if not primary_dead and s != p:
                    self._emit_failover(p, "cas", "mutate")
                    try:
                        ok, cur = self._shard(s).compare_set(key, expected, desired)
                    except StoreTransportError:
                        if attempt == 0 and self._maybe_adopt_epoch():
                            continue
                        raise
                elif attempt == 0 and self._maybe_adopt_epoch():
                    continue
                else:
                    raise
            else:
                if ok and s != p and target == p:
                    if self._breaker_tripped(s):
                        self._emit_failover(s, "cas", "replica_skipped")
                    else:
                        try:
                            self._shard(s).set(key, desired)
                        except StoreError:
                            self._emit_failover(s, "cas", "replica_skipped")
            if ok:
                self._write_through_prev("cas", lambda c: c.set(key, desired))
            return ok, cur

    def get_versioned(self, key: str) -> tuple[Any, int]:
        return self._ha_read(key, "get_versioned", lambda s: s.get_versioned(key))

    def wait_changed(
        self, key: str, seen_version: int, timeout: float
    ) -> tuple[bool, Any, int]:
        # Watch-parks fail over too. Version clocks are per shard, so after
        # a failover the seen_version from the dead primary almost certainly
        # mismatches the replica's — the park wakes immediately (spurious but
        # safe: every caller re-reads state for truth on wake).
        return self._ha_read(
            key, "wait_changed", lambda s: s.wait_changed(key, seen_version, timeout)
        )

    def touch(self, key: str) -> None:
        self._ha_write(key, "touch", lambda s: s.touch(key))

    def list_append(self, key: str, value: Any) -> None:
        # Dedup'd per shard like add; both copies append once per call.
        self._ha_write(key, "list_append", lambda s: s.list_append(key, value))

    def list_get(self, key: str) -> list:
        return self._ha_read(key, "list_get", lambda s: s.list_get(key))

    def list_clear(self, key: str) -> None:
        self._ha_write(key, "list_clear", lambda s: s.list_clear(key))

    def set_add(self, key: str, values: Iterable) -> int:
        values = list(values)
        return self._ha_write(key, "set_add", lambda s: s.set_add(key, values))

    def set_get(self, key: str) -> set:
        return self._ha_read(key, "set_get", lambda s: s.set_get(key))

    def barrier_join(
        self,
        name: str,
        rank: int,
        world_size: int,
        timeout: float,
        wait: bool = True,
        on_behalf: bool = False,
    ) -> Optional[int]:
        # A barrier name hashes to ONE shard, so arrivals, parks, proxy joins
        # and the dedup of retried joins all stay on that shard's loop. With
        # replication, every arrival is FIRST mirrored to the successor as a
        # non-blocking join (idempotent re-registration server-side), so a
        # primary SIGKILLed mid-round leaves a complete arrival ledger on the
        # replica: stragglers fail over and the round releases there —
        # exactly once per joiner, because each client returns from exactly
        # one blocking join (primary or replica, never both).
        p, s = self._route(name)
        mirrored = False
        if s != p:
            if self._breaker_tripped(s) and not self._breaker_tripped(p):
                self._emit_failover(s, "barrier", "replica_skipped")
            else:
                try:
                    self._shard(s).barrier_join(
                        name, rank, world_size, timeout, wait=False,
                        on_behalf=on_behalf,
                    )
                    mirrored = True
                except StoreError:
                    self._emit_failover(s, "barrier", "replica_skipped")
        if not (s != p and self._breaker_tripped(p) and not self._breaker_tripped(s)):
            try:
                gen = self._shard(p).barrier_join(
                    name, rank, world_size, timeout, wait, on_behalf
                )
                if gen is not None:
                    with self._ha_lock:
                        self._barrier_gen[name] = gen
                return gen
            except StoreTransportError:
                if s == p:
                    raise
        self._emit_failover(p, "barrier", "barrier")
        return self._failover_barrier_join(
            s, name, rank, world_size, timeout, wait, on_behalf, mirrored
        )

    def _failover_barrier_join(
        self, s: int, name: str, rank: int, world_size: int,
        timeout: float, wait: bool, on_behalf: bool, mirrored: bool,
    ) -> Optional[int]:
        """Complete a barrier join on the successor after the primary died.

        Replica states, all resolved without double-firing or phantom rounds:
        the mirrored round already released there (generation advanced past
        our baseline, or our mirrored arrival was consumed by a release we
        never saw → return that generation); our mirror registration is
        still among the arrivals (only the release is missing → wait for the
        generation, NEVER re-join: a release racing the status read clears
        ``arrived``, and a blind re-join would then seed a phantom round and
        park forever); or the mirror was skipped (plain join, with "joined
        twice" overflow downgraded to a release wait)."""
        with self._ha_lock:
            base = self._barrier_gen.get(name)
        c = self._shard(s)
        st = c.barrier_status(name)
        gen = (st or {}).get("generation", 0)
        arrived = (st or {}).get("arrived") or ()
        if base is not None and gen > base:
            with self._ha_lock:
                self._barrier_gen[name] = gen
            return gen if wait else None
        if mirrored and st is not None:
            if rank in arrived:
                # The mirror IS our arrival; it only lacks the release.
                if not wait:
                    return None
                return self._await_barrier_release(c, name, gen, timeout)
            if gen > (base or 0):
                # Not among the arrivals and the generation moved: the
                # release that cleared us is the one that counted us.
                with self._ha_lock:
                    self._barrier_gen[name] = gen
                return gen if wait else None
            # Anomalous (registration vanished with no release — e.g. a
            # barrier_del raced us): fall through to a real join.
        try:
            gen = c.barrier_join(name, rank, world_size, timeout, wait, on_behalf)
            if gen is not None:
                with self._ha_lock:
                    self._barrier_gen[name] = gen
            return gen
        except BarrierOverflow:
            # "Joined twice": our arrival is already on the books.
            if not wait:
                return None
            return self._await_barrier_release(c, name, gen, timeout)

    def _await_barrier_release(
        self, c: KVClient, name: str, base: int, timeout: float
    ) -> int:
        """Wait for barrier ``name``'s generation to advance past ``base`` on
        shard client ``c`` — the already-arrived half of a blocking join."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            st = c.barrier_status(name)
            gen = (st or {}).get("generation", 0)
            if gen > base:
                with self._ha_lock:
                    self._barrier_gen[name] = gen
                return gen
            if time.monotonic() >= deadline:
                raise BarrierTimeout(
                    f"failover barrier wait timed out on successor: {name}"
                )
            time.sleep(0.05)

    def barrier_status(self, name: str) -> Optional[dict]:
        return self._ha_read(name, "barrier_status", lambda s: s.barrier_status(name))

    def barrier_del(self, name: str) -> bool:
        return self._ha_write(name, "barrier_del", lambda s: s.barrier_del(name))

    # -- fan-out ops (merge across shards) ---------------------------------

    def _fan_out_ha(self, op: str, fn) -> list:
        """Fan out with dead-shard absorption: when replicating, a shard
        that fails on transport is dropped from the merge *iff* its successor
        answered — the successor's slot holds the dead shard's replicated
        keyspace, so the merged result is still complete. Results arrive in
        shard order with absorbed slots as ``None``."""
        n = len(self.endpoints)
        if not self._replicate or n == 1:
            return self._fan_out(fn)
        results = self._fan_out(fn, contain=True)
        first_err: Optional[BaseException] = None
        out: list = []
        for i, r in enumerate(results):
            if isinstance(r, BaseException):
                succ = successor_of(i, n)
                if isinstance(r, StoreTransportError) and not isinstance(
                    results[succ], BaseException
                ):
                    self._emit_failover(i, op, "absorbed")
                    out.append(None)
                    continue
                if first_err is None:
                    first_err = r
                out.append(None)
                continue
            out.append(r)
        if first_err is not None:
            raise first_err
        return out

    def _merge_keyed(self, op: str, fn) -> dict:
        """Merge dict-shaped fan-out results. Under replication a key exists
        on two shards; the primary's copy wins (the replica may be one
        skipped mirror behind), and absorbed shards contribute through their
        successor's slot."""
        parts = self._fan_out_ha(op, fn)
        n = len(self.endpoints)
        if not self._replicate or n == 1:
            out: dict = {}
            for part in parts:
                out.update(part)  # shards hold disjoint keys
            return out
        out = {}
        for i, part in enumerate(parts):
            if part is None:
                continue
            for k, v in part.items():
                if shard_of(k, n) == i:
                    out[k] = v  # primary copy is authoritative
                else:
                    out.setdefault(k, v)
        return out

    def ping(self) -> bool:
        return all(self._fan_out(lambda s: s.ping()))

    def check(self, keys: Iterable[str]) -> bool:
        by_shard: dict[int, list[str]] = {}
        for k in keys:
            by_shard.setdefault(shard_of(k, len(self._shards)), []).append(k)
        if not by_shard:
            return True
        import concurrent.futures as cf

        def check_batch(i: int, ks: list[str]) -> bool:
            try:
                return self._shard(i).check(ks)
            except StoreTransportError:
                succ = successor_of(i, len(self._shards))
                if succ == i or not self._replicate:
                    raise
                self._emit_failover(i, "check", "read")
                return self._shard(succ).check(ks)

        if len(by_shard) == 1:
            ((i, ks),) = by_shard.items()
            return check_batch(i, ks)
        with cf.ThreadPoolExecutor(max_workers=len(by_shard)) as pool:
            futs = [
                pool.submit(check_batch, i, ks)
                for i, ks in sorted(by_shard.items())
            ]
            return all(f.result() for f in futs)

    def prefix_get(self, prefix: str) -> dict[str, Any]:
        out = self._merge_keyed("prefix_get", lambda s: s.prefix_get(prefix))
        prev = self._prev_client
        if prev is not None:
            try:
                for k, v in prev.prefix_get(prefix).items():
                    out.setdefault(k, v)  # not-yet-migrated keys
            except StoreError:
                pass
        return out

    def prefix_clear(self, prefix: str) -> int:
        # Replicas live under the same names, so the all-shards fan-out
        # clears both copies; the count under replication is copies removed.
        n = sum(
            r for r in self._fan_out_ha(
                "prefix_clear", lambda s: s.prefix_clear(prefix)
            ) if r is not None
        )
        self._write_through_prev("prefix_clear", lambda c: c.prefix_clear(prefix))
        return n

    def stale_keys(self, prefix: str, max_age: float) -> dict[str, float]:
        return self._merge_keyed(
            "stale_keys", lambda s: s.stale_keys(prefix, max_age)
        )

    def num_keys(self) -> int:
        if self._replicate and len(self.endpoints) > 1:
            return len(self.keys())  # replicas would double-count
        return sum(self._fan_out(lambda s: s.num_keys()))

    def keys(self, prefix: str = "") -> list[str]:
        out: set[str] = set()
        for part in self._fan_out_ha("keys", lambda s: s.keys(prefix)):
            if part is not None:
                out.update(part)  # replicas dedupe by name
        return sorted(out)

    def barrier_names(self) -> list[str]:
        out: set[str] = set()
        for part in self._fan_out_ha("barrier_names", lambda s: s.barrier_names()):
            if part is not None:
                out.update(part)
        return sorted(out)

    def barrier_census(self, prefix: str = "") -> dict[str, dict]:
        return self._merge_keyed(
            "barrier_census", lambda s: s.barrier_census(prefix)
        )

    def store_stats(self) -> dict:
        """One aggregated ``tpu-store-stats-1`` document for the whole clique
        (op/byte/conn totals summed, quantiles worst-shard — see
        :func:`tpu_resiliency.utils.opstats.merge_stats_docs`), with the shard
        map and a per-shard summary table folded in. A single-shard clique
        returns the shard's own document plus the (degenerate) shard map, so
        readers see one schema either way."""
        from tpu_resiliency.utils.opstats import merge_stats_docs

        def one(s: KVClient) -> dict:
            try:
                return s.store_stats()
            except StoreError as e:
                # One sick shard degrades its row, never the whole document.
                return {"enabled": False, "error": repr(e)}

        docs = []
        for (h, p), doc in zip(self.endpoints, self._fan_out(one, contain=True)):
            if isinstance(doc, BaseException):
                doc = {"enabled": False, "error": repr(doc)}
            doc["endpoint"] = f"{h}:{p}"
            docs.append(doc)
        n = len(self._shards)
        with self._ha_lock:
            failover_ops = {
                i: sum(per.values()) for i, per in self._failover_counts.items()
            }
        merged = merge_stats_docs(
            docs,
            successor_map={i: successor_of(i, n) for i in range(n)}
            if self._replicate else None,
            failover_ops=failover_ops or None,
        )
        merged["shard_map"] = {
            "nshards": n,
            "hash": SHARD_HASH,
            "endpoints": [f"{h}:{p}" for h, p in self.endpoints],
            "replicate": self._replicate,
            "epoch": self._epoch,
        }
        return merged


class CliqueStore(StoreView):
    """A :class:`StoreView` that owns a :class:`ShardedKVClient` — the
    sharded sibling of :class:`~tpu_resiliency.platform.store.CoordStore`."""

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        prefix: str = "",
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
        replicate: bool | None = None,
    ):
        client = ShardedKVClient(
            endpoints, timeout=timeout, connect_retries=connect_retries,
            auth_key=auth_key, retry_budget=retry_budget, replicate=replicate,
        )
        super().__init__(client, prefix)

    def close(self) -> None:
        self.client.close()


def reshard_clique(
    client: ShardedKVClient,
    new_endpoints,
    *,
    settle: bool = True,
    scan_prefix: str = "",
) -> dict:
    """Transition a live clique to a new shard map — grow, shrink, or replace
    a dead shard with a fresh :class:`KVServer` — without a barrier ever
    failing. The epoch protocol, in order:

    1. **Publish** the next epoch document (CAS on the old map's shard 0,
       raw :data:`EPOCH_KEY`; mirrored by plain set to the old shard 0's
       successor and the new map's shard 0) with ``prev`` set — the
       dual-route window opens. ``client`` adopts it immediately.
    2. **Migrate** the value keyspace by concurrent prefix scan of the old
       map's reachable shards (a dead shard's keyspace comes from its
       successor replica — that's what replication bought), rewriting every
       key through the new map's routing (primary + successor). Coordination
       state that is round-scoped (barriers, lists/sets in flight) is not
       copied: during the window those ops stay on the old map, and rounds
       opened after settle live natively on the new map.
    3. **Settle** (``prev: None``): dual-routing ends; old-map clients that
       lose a shard after this adopt the new map on their next failure.
       Republish :data:`CLIQUE_KEY` on the new shard 0 so late joiners probe
       straight into the new map.

    Returns the settled (or migrating, with ``settle=False``) epoch doc with
    a ``migrated`` key count folded in. The caller owns the new servers'
    lifecycle; with ``settle=False`` the caller finishes by calling this
    again with the same endpoints (idempotent: same-epoch settle)."""
    new_eps = [
        tuple(e) for e in (
            parse_endpoints(new_endpoints)
            if isinstance(new_endpoints, str) else new_endpoints
        )
    ]
    if not new_eps:
        raise ValueError("reshard_clique needs at least one endpoint")
    cur = client._read_epoch_doc()
    cur_epoch = cur["epoch"] if isinstance(cur, dict) else 0
    old_eps = list(client.endpoints)
    resuming = (
        isinstance(cur, dict) and cur.get("prev")
        and [list(e) for e in new_eps] == cur.get("endpoints")
    )
    if resuming:
        # Finishing a window opened by an earlier ``settle=False`` pass:
        # same epoch, same endpoints — re-migrate and settle, don't chain a
        # fresh epoch.
        doc = {k: cur[k] for k in ("epoch", "endpoints", "prev", "replicate")
               if k in cur}
        old_eps = [tuple(e) for e in cur["prev"]]
    else:
        doc = {
            "epoch": cur_epoch + 1,
            "endpoints": [list(e) for e in new_eps],
            "prev": [list(e) for e in old_eps],
            "replicate": client._replicate,
        }

    def direct(ep) -> KVClient:
        return KVClient(
            ep[0], ep[1], timeout=10.0, connect_retries=1,
            auth_key=client.auth_key, retry_budget=0.0,
        )

    def publish(d: dict, expected) -> None:
        # CAS anchor: the OLD map's shard 0 (concurrent-reshard detection
        # lives where every pre-transition client can see it). When that
        # shard is the casualty being replaced, fall through to the new
        # map's shard 0 — a recovery write, force-set when the new anchor
        # never saw the chain. Mirrors (plain set) land everywhere any
        # client's epoch probe looks: old successor-of-0, new shard 0, new
        # successor-of-0.
        anchors = [old_eps[0]]
        if tuple(new_eps[0]) != tuple(old_eps[0]):
            anchors.append(new_eps[0])
        published = False
        last_err: Optional[BaseException] = None
        for ai, ep in enumerate(anchors):
            try:
                a = direct(ep)
                try:
                    ok, now_cur = a.compare_set(EPOCH_KEY, expected, d)
                    if not ok and now_cur == d:
                        ok = True  # idempotent republish (retried settle)
                    if not ok and ai > 0 and (
                        now_cur is None
                        or (isinstance(now_cur, dict)
                            and now_cur.get("epoch", 0) < d["epoch"])
                    ):
                        a.set(EPOCH_KEY, d)  # new anchor never saw the chain
                        ok = True
                    if not ok:
                        raise StoreError(
                            f"concurrent reshard detected (epoch key moved "
                            f"to {now_cur!r})"
                        )
                finally:
                    a.close()
                published = True
                break
            except StoreTransportError as e:
                last_err = e
        if not published:
            raise StoreError(
                f"reshard could not publish epoch {d['epoch']}: no anchor "
                f"shard reachable"
            ) from last_err
        mirrors: list[tuple[str, int]] = []
        for ep in (
            old_eps[successor_of(0, len(old_eps))] if len(old_eps) > 1 else None,
            new_eps[0],
            new_eps[successor_of(0, len(new_eps))] if len(new_eps) > 1 else None,
        ):
            if ep is not None and tuple(ep) != tuple(old_eps[0]) \
                    and tuple(ep) not in mirrors:
                mirrors.append(tuple(ep))
        for ep in mirrors:
            try:
                m = direct(ep)
                try:
                    m.set(EPOCH_KEY, d)
                finally:
                    m.close()
            except StoreError:
                pass

    publish(doc, cur)
    record_event(
        "store", "shard_epoch", epoch=doc["epoch"], nshards=len(new_eps),
        outcome="migrating", prev_nshards=len(old_eps),
    )
    client._maybe_adopt_epoch(min_interval=0.0)
    # Migrate through the adopted client: its prefix_get absorbs a dead old
    # shard via the successor replica, and its set() writes land replicated
    # on the new map AND write-through to the old primary (dual-route).
    snapshot = client.prefix_get(scan_prefix)
    migrated = 0
    for k, v in snapshot.items():
        if k == EPOCH_KEY or k == CLIQUE_KEY:
            continue
        client.set(k, v)
        migrated += 1
    if settle:
        settled = dict(doc)
        settled["prev"] = None
        publish(settled, doc)
        try:
            c0 = KVClient(
                *new_eps[0], timeout=10.0, connect_retries=1,
                auth_key=client.auth_key, retry_budget=0.0,
            )
            try:
                c0.set(CLIQUE_KEY, format_endpoints(new_eps))
            finally:
                c0.close()
        except StoreError:
            pass
        record_event(
            "store", "shard_epoch", epoch=doc["epoch"], nshards=len(new_eps),
            outcome="settled", migrated=migrated,
        )
        client._maybe_adopt_epoch(min_interval=0.0)
        doc = settled
    out = dict(doc)
    out["migrated"] = migrated
    return out


def endpoints_from_env() -> Optional[list[tuple[str, int]]]:
    """The clique advertised by ``$TPU_RESILIENCY_STORE_SHARDS`` (the
    launcher's export), or ``None`` when unset/single-endpoint-classic."""
    spec = os.environ.get(SHARDS_ENV, "").strip()
    if not spec:
        return None
    return parse_endpoints(spec)


def connect_store(
    host: str,
    port: int,
    prefix: str = "",
    *,
    shards: str = "",
    timeout: float = 300.0,
    connect_retries: int = 60,
    auth_key: str | None = None,
    retry_budget: float = 8.0,
    replicate: bool | None = None,
):
    """Store-client factory every plane shares: a ``shards`` spec (argument,
    else ``$TPU_RESILIENCY_STORE_SHARDS``) yields a :class:`CliqueStore`;
    otherwise the classic single-endpoint
    :class:`~tpu_resiliency.platform.store.CoordStore`. Components that take
    ``(host, port)`` today migrate by calling this instead of the
    constructor — no signature churn. ``replicate=None`` defers to the
    launcher's ``$TPU_RESILIENCY_STORE_REPLICATE`` export."""
    from tpu_resiliency.platform.store import CoordStore

    eps = parse_endpoints(shards) if shards else endpoints_from_env()
    if eps and len(eps) > 1:
        return CliqueStore(
            eps, prefix=prefix, timeout=timeout,
            connect_retries=connect_retries, auth_key=auth_key,
            retry_budget=retry_budget, replicate=replicate,
        )
    if eps:  # single-shard clique spec: classic layout at that endpoint
        host, port = eps[0]
    return CoordStore(
        host, port, prefix=prefix, timeout=timeout,
        connect_retries=connect_retries, auth_key=auth_key,
        retry_budget=retry_budget,
    )


def probe_clique_spec(
    host: str, port: int, auth_key: str | None = None, timeout: float = 2.0
) -> str:
    """One cheap round trip against a live endpoint: the clique spec its
    spawner published under :data:`CLIQUE_KEY`, or ``""`` (plain store,
    pre-shard server, or any failure — callers fall back to classic mode)."""
    try:
        c = KVClient(
            host, port, timeout=timeout, connect_retries=1,
            auth_key=auth_key, retry_budget=0.0,
        )
    except StoreError:
        return ""
    try:
        spec = c.try_get(CLIQUE_KEY, "")
        return spec if isinstance(spec, str) else ""
    except StoreError:
        return ""
    finally:
        c.close()


class LocalClique:
    """N in-process :class:`KVServer` loops — the test/chaos harness shape
    (each server still owns its own selector thread and state; only the
    bench's subprocess clique buys real per-core parallelism)."""

    def __init__(self, nshards: int, host: str = "127.0.0.1", **server_kw):
        self.servers = [
            KVServer(host=host, port=0, **server_kw) for _ in range(nshards)
        ]
        self.endpoints = [(host, s.port) for s in self.servers]

    @property
    def spec(self) -> str:
        return format_endpoints(self.endpoints)

    def client(self, prefix: str = "", **kw) -> CliqueStore:
        return CliqueStore(self.endpoints, prefix=prefix, **kw)

    def close(self) -> None:
        for s in self.servers:
            try:
                s.close()
            except Exception:
                pass


class SpawnedClique:
    """N ``KVServer`` *processes* (``python -m tpu_resiliency.platform.store``)
    — the deployment shape: each shard's event loop owns a core. Used by the
    launcher's ``--store-shards`` and the scale bench. Shard 0 may bind a
    fixed port (the job's rendezvous endpoint); the rest take ephemeral ports
    read back from the child's banner line."""

    def __init__(
        self,
        nshards: int,
        host: str = "127.0.0.1",
        first_port: int = 0,
        spawn_timeout: float = 20.0,
        advertise_host: str | None = None,
    ):
        # ``host`` is the BIND address (0.0.0.0 for authenticated multi-host
        # cliques); ``advertise_host`` is what lands in the published spec —
        # the address peers dial. Liveness probes always go over loopback
        # (we spawned the children on this machine).
        self.procs: list[subprocess.Popen] = []
        self.endpoints: list[tuple[str, int]] = []
        adv = advertise_host or ("127.0.0.1" if host in ("127.0.0.1", "") else host)
        if adv == "0.0.0.0":
            adv = "127.0.0.1"
        env = dict(os.environ)
        try:
            for i in range(nshards):
                port = first_port if i == 0 else 0
                p = subprocess.Popen(
                    [sys.executable, "-m", "tpu_resiliency.platform.store",
                     f"{host}:{port}"],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, env=env,
                )
                self.procs.append(p)
                banner = p.stdout.readline().strip()
                # "store serving on HOST:PORT"
                try:
                    bound = int(banner.rsplit(":", 1)[1])
                except (IndexError, ValueError):
                    raise StoreError(
                        f"store shard {i} failed to start (banner {banner!r})"
                    )
                self.endpoints.append((adv, bound))
            deadline = time.monotonic() + spawn_timeout
            for _, bound in self.endpoints:
                while not store_answers("127.0.0.1", bound, timeout=1.0):
                    if time.monotonic() >= deadline:
                        raise StoreError(
                            f"store shard 127.0.0.1:{bound} never answered ping"
                        )
                    time.sleep(0.05)
        except BaseException:
            self.close()
            raise

    @property
    def spec(self) -> str:
        return format_endpoints(self.endpoints)

    @property
    def port(self) -> int:
        return self.endpoints[0][1]

    def client(self, prefix: str = "", **kw) -> CliqueStore:
        return CliqueStore(self.endpoints, prefix=prefix, **kw)

    def close(self, join: bool = True, timeout: float = 5.0) -> None:
        for p in self.procs:
            try:
                p.terminate()
            except OSError:
                pass
        if join:
            for p in self.procs:
                try:
                    p.wait(timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout)

    def respawn_shard(self, shard: int, spawn_timeout: float = 20.0) -> tuple[str, int]:
        """Replace one (dead) shard process with a fresh ``KVServer`` on an
        ephemeral port; returns the new endpoint. The caller still owns the
        epoch transition — pair with :func:`reshard_clique` to route the
        keyspace onto the replacement."""
        old = self.procs[shard]
        try:
            if old.poll() is None:
                old.terminate()
            old.wait(spawn_timeout)
        except (OSError, subprocess.TimeoutExpired):
            try:
                old.kill()
            except OSError:
                pass
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_resiliency.platform.store",
             "127.0.0.1:0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=dict(os.environ),
        )
        banner = p.stdout.readline().strip()
        try:
            bound = int(banner.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            p.kill()
            raise StoreError(
                f"replacement for shard {shard} failed to start "
                f"(banner {banner!r})"
            )
        deadline = time.monotonic() + spawn_timeout
        while not store_answers("127.0.0.1", bound, timeout=1.0):
            if time.monotonic() >= deadline:
                p.kill()
                raise StoreError(
                    f"replacement shard 127.0.0.1:{bound} never answered ping"
                )
            time.sleep(0.05)
        adv = self.endpoints[shard][0]
        self.procs[shard] = p
        self.endpoints[shard] = (adv, bound)
        return (adv, bound)


class AutoReshardSupervisor:
    """Automatic shard respawn: the launcher-side watcher that turns the
    operator runbook (notice a dead shard, spawn a replacement, run
    ``reshard_clique``) into a closed loop.

    Polls each shard of a job-hosted :class:`SpawnedClique` — a shard is a
    respawn candidate when its *process* has exited or its client-side
    circuit breaker is open AND a direct liveness probe fails (the breaker
    alone can reflect a transient blip; the probe confirms the shard is
    really gone). A candidate that stays dead past ``grace`` seconds is
    replaced: :meth:`SpawnedClique.respawn_shard` spawns the new server and
    :func:`reshard_clique` migrates the keyspace onto the healed map. Every
    attempt is audited as a ``store_auto_reshard`` event
    (``outcome=ok|failed``); the operator-initiated path is untouched."""

    def __init__(
        self,
        clique: SpawnedClique,
        client: ShardedKVClient,
        *,
        interval: float = 1.0,
        grace: float = 3.0,
    ):
        self.clique = clique
        self.client = client
        self.interval = interval
        self.grace = grace
        self._dead_since: dict[int, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: successful automatic reshards (observable for tests/telemetry)
        self.reshards = 0

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="store-auto-reshard"
            )
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:  # supervision must outlive any one probe
                log.warning(f"store auto-reshard tick failed: {e!r}")

    def _shard_dead(self, shard: int) -> bool:
        if self.clique.procs[shard].poll() is not None:
            return True
        host, port = self.clique.endpoints[shard]
        if not breaker_open(host, port):
            return False
        return not store_answers("127.0.0.1", port, timeout=1.0)

    def _tick(self) -> None:
        now = time.monotonic()
        for shard in range(len(self.clique.endpoints)):
            if not self._shard_dead(shard):
                self._dead_since.pop(shard, None)
                continue
            since = self._dead_since.setdefault(shard, now)
            if now - since < self.grace:
                continue
            self._respawn(shard)
            self._dead_since.pop(shard, None)

    def _respawn(self, shard: int) -> None:
        old = self.clique.endpoints[shard]
        try:
            new_ep = self.clique.respawn_shard(shard)
            doc = reshard_clique(self.client, list(self.clique.endpoints))
        except (StoreError, OSError) as e:
            log.warning(
                f"store auto-reshard of shard {shard} "
                f"({old[0]}:{old[1]}) failed: {e!r}"
            )
            record_event(
                "store", "store_auto_reshard", shard=shard,
                old=f"{old[0]}:{old[1]}", outcome="failed", error=repr(e),
            )
            return
        self.reshards += 1
        log.info(
            f"store auto-reshard: shard {shard} {old[0]}:{old[1]} -> "
            f"{new_ep[0]}:{new_ep[1]} (epoch {doc.get('epoch')})"
        )
        record_event(
            "store", "store_auto_reshard", shard=shard,
            old=f"{old[0]}:{old[1]}", new=f"{new_ep[0]}:{new_ep[1]}",
            epoch=doc.get("epoch"), outcome="ok",
        )
