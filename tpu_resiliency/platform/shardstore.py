"""Sharded coordination-store clique: one keyspace over N server processes.

One :class:`~tpu_resiliency.platform.store.KVServer` is a single-threaded
event loop — by design (no locks, parked continuations instead of blocked
threads), and measured flat in *connection* count, but its op throughput is
one core's dict-op rate. At 4096 ranks every subsystem's traffic (rendezvous
CAS, barrier storms, heartbeat touches, metrics pushes, reshard
holder-gathers) funnels through that one loop and queue wait dominates —
``BENCH_store_baseline.json``'s 37 µs → 3.3 ms p50 curve from 1 → 64 clients
is that funnel.

This module scales the plane *horizontally* without touching the wire
protocol or the server: a **clique** of ordinary ``KVServer`` processes plus
a client-side deterministic key→shard map. :class:`ShardedKVClient` exposes
the exact :class:`~tpu_resiliency.platform.store.KVClient` surface;
single-key ops route by ``crc32(key) % nshards`` (stable across processes
and Python runs — never ``hash()``, which is salted per process), and the
prefix/scan ops fan out to every shard and merge. Three properties make the
layering safe with zero server changes:

- **Barriers and parks are shard-local by construction**: a barrier name, a
  watched key, and a parked ``get`` all hash to exactly one shard, so the
  server-side wait/notify machinery never spans shards.
- **The at-most-once dedup ladder is per shard for free**: each shard is
  served by its own underlying ``KVClient``, whose ``req_id`` nonces and
  retry budget apply against that shard's dedup LRU; a retry can only replay
  against the shard that saw the original.
- **Circuit breakers are per endpoint already** (keyed ``(host, port)`` in
  ``platform/store.py``), so one dead shard fails fast without poisoning the
  others' budgets.

A 1-shard clique degenerates to today's layout exactly — same keys, same
server, one persistent connection — which is the version-skew contract
``tests/platform/test_store_skew.py`` pins.

Discovery: the launcher exports ``$TPU_RESILIENCY_STORE_SHARDS`` as a
comma-separated ``host:port`` list (shard order IS the hash order — every
client must see the identical list); :func:`connect_store` honors it and
falls back to the classic single-endpoint env pair.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import zlib
from typing import Any, Iterable, Optional

from tpu_resiliency.exceptions import StoreError
from tpu_resiliency.platform.store import (
    AUTH_KEY_ENV,
    KVClient,
    KVServer,
    StoreView,
    store_answers,
)
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SHARDS_ENV = "TPU_RESILIENCY_STORE_SHARDS"

#: Reserved raw key on shard 0 where a clique's spawner publishes the full
#: endpoint list. A client handed only the classic ``host:port`` endpoint
#: (another agent, a diagnostic tool) probes it once and, if present,
#: reconnects as a sharded client — late joiners cannot split the keyspace
#: by talking to shard 0 alone.
CLIQUE_KEY = "store-clique/endpoints"

#: keyspace-hash identity carried in every aggregated stats doc — a client
#: and a doc reader disagreeing on the hash would mis-attribute per-shard load
SHARD_HASH = "crc32"


def shard_of(key: str, nshards: int) -> int:
    """Deterministic key→shard index. ``crc32`` of the UTF-8 key: stable
    across processes, runs, and machines (``hash()`` is per-process salted
    and would scatter one job's clients across disagreeing maps)."""
    if nshards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8", "surrogatepass")) % nshards


def parse_endpoints(spec: str) -> list[tuple[str, int]]:
    """``"host:port,host:port"`` → ``[(host, port), ...]`` (shard order)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port_s = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port_s)))
    if not out:
        raise ValueError(f"no endpoints in shard spec {spec!r}")
    return out


def format_endpoints(endpoints: Iterable[tuple[str, int]]) -> str:
    return ",".join(f"{h}:{p}" for h, p in endpoints)


class ShardedKVClient:
    """Drop-in :class:`KVClient` over a store clique.

    Single-key ops route by :func:`shard_of`; prefix/scan/census ops fan out
    to every shard CONCURRENTLY (a small persistent pool, one worker per
    shard) and merge — shards hold disjoint keys, so the merged result is
    identical whichever shard answers first, and a serial fan-out was paying
    ``nshards`` sequential round trips on every reshard holder-gather and
    census (the PR-14 headroom note). Determinism is preserved: results
    merge in shard order, and when several shards fail the FIRST shard's
    error (by shard index) surfaces, after that shard's own retry budget and
    breaker — exactly the serial contract. Thread-safe to the same degree as
    ``KVClient`` (each underlying client locks its own persistent socket).
    """

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
    ):
        if not endpoints:
            raise ValueError("ShardedKVClient needs at least one endpoint")
        self.endpoints = [tuple(e) for e in endpoints]
        self.default_timeout = timeout
        self._connect_retries = connect_retries
        self._retry_budget = retry_budget
        # Per-shard clients are built LAZILY on first use: a clique client
        # must stay constructible while one shard is down (diagnostics
        # against a degraded clique, ops that never touch the dead shard).
        # The failure surfaces on the op that actually needs the shard —
        # after that shard's own connect ladder/breaker — and a later op
        # retries construction, so a restarted shard is picked up in place.
        self._shards: list[Optional[KVClient]] = [None] * len(self.endpoints)
        self._shards_lock = threading.Lock()
        self._fan_pool = None  # lazy; one worker per shard
        self._closed = False
        # Single-endpoint compatibility surface (diagnostics, logs).
        self.host, self.port = self.endpoints[0]
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        self.auth_key = auth_key

    @property
    def nshards(self) -> int:
        return len(self._shards)

    def _shard(self, i: int) -> KVClient:
        s = self._shards[i]
        if s is not None:
            return s
        with self._shards_lock:
            if self._closed:
                raise StoreError("store client is closed")
            s = self._shards[i]
            if s is None:
                h, p = self.endpoints[i]
                s = self._shards[i] = KVClient(
                    h, p, timeout=self.default_timeout,
                    connect_retries=self._connect_retries,
                    auth_key=self.auth_key, retry_budget=self._retry_budget,
                )
        return s

    def _for(self, key: str) -> KVClient:
        return self._shard(shard_of(key, len(self._shards)))

    def _live_shards(self) -> list[KVClient]:
        return [self._shard(i) for i in range(len(self.endpoints))]

    def _fan_out(self, fn, contain: bool = False) -> list:
        """Run ``fn(shard_client)`` on every shard concurrently; results in
        shard order. With ``contain=False`` the lowest-indexed shard's
        exception propagates (the serial-era contract); ``contain=True``
        returns the exception object in that shard's slot instead (the
        stats path degrades rows, never the document)."""
        def run(i: int):
            # Shard construction happens INSIDE the task: a dead shard's
            # connect ladder neither blocks the other shards' ops nor (when
            # contained) escapes its own slot.
            return fn(self._shard(i))

        if len(self.endpoints) == 1:
            try:
                return [run(0)]
            except Exception as e:
                if contain:
                    return [e]
                raise
        with self._shards_lock:
            if self._fan_pool is None:
                if self._closed:
                    raise StoreError("store client is closed")
                import concurrent.futures as cf

                self._fan_pool = cf.ThreadPoolExecutor(
                    max_workers=len(self.endpoints),
                    thread_name_prefix="store-fan",
                )
            pool = self._fan_pool
        futs = [pool.submit(run, i) for i in range(len(self.endpoints))]
        results: list = []
        first_err: Optional[BaseException] = None
        for f in futs:
            try:
                results.append(f.result())
            except Exception as e:
                if contain:
                    results.append(e)
                else:
                    results.append(None)
                    if first_err is None:
                        first_err = e
        if first_err is not None:
            raise first_err
        return results

    def close(self) -> None:
        with self._shards_lock:
            self._closed = True
            shards, self._shards = self._shards, [None] * len(self.endpoints)
            pool, self._fan_pool = self._fan_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for s in shards:
            if s is None:
                continue
            try:
                s.close()
            except Exception:
                pass

    # -- keyed ops (route by hash) ----------------------------------------

    def set(self, key: str, value: Any) -> None:
        self._for(key).set(key, value)

    def get(self, key: str, timeout: float | None = None) -> Any:
        return self._for(key).get(key, timeout)

    def try_get(self, key: str, default: Any = None) -> Any:
        return self._for(key).try_get(key, default)

    def delete(self, key: str) -> bool:
        return self._for(key).delete(key)

    def add(self, key: str, amount: int = 1) -> int:
        return self._for(key).add(key, amount)

    def compare_set(self, key: str, expected: Any, desired: Any) -> tuple[bool, Any]:
        return self._for(key).compare_set(key, expected, desired)

    def get_versioned(self, key: str) -> tuple[Any, int]:
        return self._for(key).get_versioned(key)

    def wait_changed(
        self, key: str, seen_version: int, timeout: float
    ) -> tuple[bool, Any, int]:
        return self._for(key).wait_changed(key, seen_version, timeout)

    def touch(self, key: str) -> None:
        self._for(key).touch(key)

    def list_append(self, key: str, value: Any) -> None:
        self._for(key).list_append(key, value)

    def list_get(self, key: str) -> list:
        return self._for(key).list_get(key)

    def list_clear(self, key: str) -> None:
        self._for(key).list_clear(key)

    def set_add(self, key: str, values: Iterable) -> int:
        return self._for(key).set_add(key, values)

    def set_get(self, key: str) -> set:
        return self._for(key).set_get(key)

    def barrier_join(
        self,
        name: str,
        rank: int,
        world_size: int,
        timeout: float,
        wait: bool = True,
        on_behalf: bool = False,
    ) -> Optional[int]:
        # A barrier name hashes to ONE shard, so arrivals, parks, proxy joins
        # and the dedup of retried joins all stay on that shard's loop.
        return self._for(name).barrier_join(
            name, rank, world_size, timeout, wait, on_behalf
        )

    def barrier_status(self, name: str) -> Optional[dict]:
        return self._for(name).barrier_status(name)

    def barrier_del(self, name: str) -> bool:
        return self._for(name).barrier_del(name)

    # -- fan-out ops (merge across shards) ---------------------------------

    def ping(self) -> bool:
        return all(self._fan_out(lambda s: s.ping()))

    def check(self, keys: Iterable[str]) -> bool:
        by_shard: dict[int, list[str]] = {}
        for k in keys:
            by_shard.setdefault(shard_of(k, len(self._shards)), []).append(k)
        if not by_shard:
            return True
        import concurrent.futures as cf

        if len(by_shard) == 1:
            ((i, ks),) = by_shard.items()
            return self._shard(i).check(ks)
        with cf.ThreadPoolExecutor(max_workers=len(by_shard)) as pool:
            futs = [
                pool.submit(self._shard(i).check, ks)
                for i, ks in sorted(by_shard.items())
            ]
            return all(f.result() for f in futs)

    def prefix_get(self, prefix: str) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for part in self._fan_out(lambda s: s.prefix_get(prefix)):
            out.update(part)  # shards hold disjoint keys
        return out

    def prefix_clear(self, prefix: str) -> int:
        return sum(self._fan_out(lambda s: s.prefix_clear(prefix)))

    def stale_keys(self, prefix: str, max_age: float) -> dict[str, float]:
        out: dict[str, float] = {}
        for part in self._fan_out(lambda s: s.stale_keys(prefix, max_age)):
            out.update(part)
        return out

    def num_keys(self) -> int:
        return sum(self._fan_out(lambda s: s.num_keys()))

    def keys(self, prefix: str = "") -> list[str]:
        out: list[str] = []
        for part in self._fan_out(lambda s: s.keys(prefix)):
            out.extend(part)
        return sorted(out)

    def barrier_names(self) -> list[str]:
        out: list[str] = []
        for part in self._fan_out(lambda s: s.barrier_names()):
            out.extend(part)
        return sorted(out)

    def barrier_census(self, prefix: str = "") -> dict[str, dict]:
        out: dict[str, dict] = {}
        for part in self._fan_out(lambda s: s.barrier_census(prefix)):
            out.update(part)
        return out

    def store_stats(self) -> dict:
        """One aggregated ``tpu-store-stats-1`` document for the whole clique
        (op/byte/conn totals summed, quantiles worst-shard — see
        :func:`tpu_resiliency.utils.opstats.merge_stats_docs`), with the shard
        map and a per-shard summary table folded in. A single-shard clique
        returns the shard's own document plus the (degenerate) shard map, so
        readers see one schema either way."""
        from tpu_resiliency.utils.opstats import merge_stats_docs

        def one(s: KVClient) -> dict:
            try:
                return s.store_stats()
            except StoreError as e:
                # One sick shard degrades its row, never the whole document.
                return {"enabled": False, "error": repr(e)}

        docs = []
        for (h, p), doc in zip(self.endpoints, self._fan_out(one, contain=True)):
            if isinstance(doc, BaseException):
                doc = {"enabled": False, "error": repr(doc)}
            doc["endpoint"] = f"{h}:{p}"
            docs.append(doc)
        merged = merge_stats_docs(docs)
        merged["shard_map"] = {
            "nshards": len(self._shards),
            "hash": SHARD_HASH,
            "endpoints": [f"{h}:{p}" for h, p in self.endpoints],
        }
        return merged


class CliqueStore(StoreView):
    """A :class:`StoreView` that owns a :class:`ShardedKVClient` — the
    sharded sibling of :class:`~tpu_resiliency.platform.store.CoordStore`."""

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        prefix: str = "",
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
    ):
        client = ShardedKVClient(
            endpoints, timeout=timeout, connect_retries=connect_retries,
            auth_key=auth_key, retry_budget=retry_budget,
        )
        super().__init__(client, prefix)

    def close(self) -> None:
        self.client.close()


def endpoints_from_env() -> Optional[list[tuple[str, int]]]:
    """The clique advertised by ``$TPU_RESILIENCY_STORE_SHARDS`` (the
    launcher's export), or ``None`` when unset/single-endpoint-classic."""
    spec = os.environ.get(SHARDS_ENV, "").strip()
    if not spec:
        return None
    return parse_endpoints(spec)


def connect_store(
    host: str,
    port: int,
    prefix: str = "",
    *,
    shards: str = "",
    timeout: float = 300.0,
    connect_retries: int = 60,
    auth_key: str | None = None,
    retry_budget: float = 8.0,
):
    """Store-client factory every plane shares: a ``shards`` spec (argument,
    else ``$TPU_RESILIENCY_STORE_SHARDS``) yields a :class:`CliqueStore`;
    otherwise the classic single-endpoint
    :class:`~tpu_resiliency.platform.store.CoordStore`. Components that take
    ``(host, port)`` today migrate by calling this instead of the
    constructor — no signature churn."""
    from tpu_resiliency.platform.store import CoordStore

    eps = parse_endpoints(shards) if shards else endpoints_from_env()
    if eps and len(eps) > 1:
        return CliqueStore(
            eps, prefix=prefix, timeout=timeout,
            connect_retries=connect_retries, auth_key=auth_key,
            retry_budget=retry_budget,
        )
    if eps:  # single-shard clique spec: classic layout at that endpoint
        host, port = eps[0]
    return CoordStore(
        host, port, prefix=prefix, timeout=timeout,
        connect_retries=connect_retries, auth_key=auth_key,
        retry_budget=retry_budget,
    )


def probe_clique_spec(
    host: str, port: int, auth_key: str | None = None, timeout: float = 2.0
) -> str:
    """One cheap round trip against a live endpoint: the clique spec its
    spawner published under :data:`CLIQUE_KEY`, or ``""`` (plain store,
    pre-shard server, or any failure — callers fall back to classic mode)."""
    try:
        c = KVClient(
            host, port, timeout=timeout, connect_retries=1,
            auth_key=auth_key, retry_budget=0.0,
        )
    except StoreError:
        return ""
    try:
        spec = c.try_get(CLIQUE_KEY, "")
        return spec if isinstance(spec, str) else ""
    except StoreError:
        return ""
    finally:
        c.close()


class LocalClique:
    """N in-process :class:`KVServer` loops — the test/chaos harness shape
    (each server still owns its own selector thread and state; only the
    bench's subprocess clique buys real per-core parallelism)."""

    def __init__(self, nshards: int, host: str = "127.0.0.1", **server_kw):
        self.servers = [
            KVServer(host=host, port=0, **server_kw) for _ in range(nshards)
        ]
        self.endpoints = [(host, s.port) for s in self.servers]

    @property
    def spec(self) -> str:
        return format_endpoints(self.endpoints)

    def client(self, prefix: str = "", **kw) -> CliqueStore:
        return CliqueStore(self.endpoints, prefix=prefix, **kw)

    def close(self) -> None:
        for s in self.servers:
            try:
                s.close()
            except Exception:
                pass


class SpawnedClique:
    """N ``KVServer`` *processes* (``python -m tpu_resiliency.platform.store``)
    — the deployment shape: each shard's event loop owns a core. Used by the
    launcher's ``--store-shards`` and the scale bench. Shard 0 may bind a
    fixed port (the job's rendezvous endpoint); the rest take ephemeral ports
    read back from the child's banner line."""

    def __init__(
        self,
        nshards: int,
        host: str = "127.0.0.1",
        first_port: int = 0,
        spawn_timeout: float = 20.0,
        advertise_host: str | None = None,
    ):
        # ``host`` is the BIND address (0.0.0.0 for authenticated multi-host
        # cliques); ``advertise_host`` is what lands in the published spec —
        # the address peers dial. Liveness probes always go over loopback
        # (we spawned the children on this machine).
        self.procs: list[subprocess.Popen] = []
        self.endpoints: list[tuple[str, int]] = []
        adv = advertise_host or ("127.0.0.1" if host in ("127.0.0.1", "") else host)
        if adv == "0.0.0.0":
            adv = "127.0.0.1"
        env = dict(os.environ)
        try:
            for i in range(nshards):
                port = first_port if i == 0 else 0
                p = subprocess.Popen(
                    [sys.executable, "-m", "tpu_resiliency.platform.store",
                     f"{host}:{port}"],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, env=env,
                )
                self.procs.append(p)
                banner = p.stdout.readline().strip()
                # "store serving on HOST:PORT"
                try:
                    bound = int(banner.rsplit(":", 1)[1])
                except (IndexError, ValueError):
                    raise StoreError(
                        f"store shard {i} failed to start (banner {banner!r})"
                    )
                self.endpoints.append((adv, bound))
            deadline = time.monotonic() + spawn_timeout
            for _, bound in self.endpoints:
                while not store_answers("127.0.0.1", bound, timeout=1.0):
                    if time.monotonic() >= deadline:
                        raise StoreError(
                            f"store shard 127.0.0.1:{bound} never answered ping"
                        )
                    time.sleep(0.05)
        except BaseException:
            self.close()
            raise

    @property
    def spec(self) -> str:
        return format_endpoints(self.endpoints)

    @property
    def port(self) -> int:
        return self.endpoints[0][1]

    def client(self, prefix: str = "", **kw) -> CliqueStore:
        return CliqueStore(self.endpoints, prefix=prefix, **kw)

    def close(self, join: bool = True, timeout: float = 5.0) -> None:
        for p in self.procs:
            try:
                p.terminate()
            except OSError:
                pass
        if join:
            for p in self.procs:
                try:
                    p.wait(timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout)
