"""Local inter-process messaging over Unix domain sockets.

The analogue of the reference's AF_UNIX pickled IPC (``fault_tolerance/utils.py:121-179``
sync + asyncio helpers, and ``fault_tolerance/ipc_connector.py:30`` one-way queue with a
receiver thread). Used between a worker rank and its per-host monitor, and between ranks
and the launcher — never for tensor data.

Framing is shared with the TCP store protocol (``platform/framing.py``). Unix sockets are
filesystem-permission-protected, so no auth handshake is needed here.
"""

from __future__ import annotations

import asyncio
import functools
import os
import socket
import threading
import time
from typing import Any, Callable, Optional

from tpu_resiliency.platform import chaos, framing
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

_MAX_FRAME = 256 * 1024 * 1024

# Environment variables carrying socket paths from launcher to workers; analogue of
# FT_RANK_MONITOR_IPC_SOCKET / FT_LAUNCHER_IPC_SOCKET (reference ``data.py:27-30``).
MONITOR_SOCKET_ENV = "TPU_FT_MONITOR_IPC_SOCKET"
LAUNCHER_SOCKET_ENV = "TPU_FT_LAUNCHER_IPC_SOCKET"

write_object = framing.send_obj
read_object = functools.partial(framing.recv_obj, max_frame=_MAX_FRAME)
read_object_stream = functools.partial(framing.read_obj_stream, max_frame=_MAX_FRAME)
write_object_stream = framing.write_obj_stream


def connect(
    path: str, timeout: float = 30.0, cancel: Optional[threading.Event] = None
) -> socket.socket:
    """Connect to a UDS server, retrying within ``timeout``.

    Retry matters even when the caller has seen the socket file: the file
    appears at the server's bind(), and a loaded machine can deschedule the
    server between bind() and listen() — a one-shot connect then dies on
    ECONNREFUSED for a server that is milliseconds from ready (observed as a
    1-in-4 suite flake under 2x concurrency). FileNotFoundError is retried
    for the same reason one step earlier (file not yet created).

    ``cancel``: optional event checked every iteration — a caller shutting
    down mid-retry (worker teardown racing monitor startup) aborts the loop
    promptly with ``ConnectionAbortedError`` instead of sleeping out the
    remaining budget against a server that will never appear."""
    deadline = time.monotonic() + timeout
    while True:
        if cancel is not None and cancel.is_set():
            raise ConnectionAbortedError(f"ipc connect to {path!r} cancelled")
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # Remaining budget, not the full timeout: a blocking connect on the
        # final attempt must not stretch the caller's deadline to ~2x.
        sock.settimeout(max(0.001, deadline - time.monotonic()))
        try:
            chaos.check_connect("ipc", peer=path)
            sock.connect(path)
            # The clipped timeout governed only the connect attempt; the
            # returned socket keeps the caller's full I/O timeout (a late
            # connect must not bequeath a milliseconds recv budget).
            sock.settimeout(timeout)
            return chaos.wrap(sock, "ipc", peer=path)
        except (ConnectionRefusedError, FileNotFoundError, BlockingIOError):
            # BlockingIOError: Linux AF_UNIX connect returns EAGAIN when the
            # listener's accept backlog is full — same transient class.
            sock.close()
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


class IpcReceiver:
    """One-way message sink: listens on a UDS path, queues every received object.

    Analogue of the reference's ``IpcConnector`` (``fault_tolerance/ipc_connector.py:30``):
    the launcher listens here for ``WorkloadControlRequest``-style messages from ranks.
    """

    def __init__(self, path: str, on_message: Optional[Callable[[Any], None]] = None):
        self.path = path
        self._on_message = on_message
        self._messages: list[Any] = []
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self.path)
        self._server.listen(64)
        self._thread = threading.Thread(target=self._loop, name="ipc-receiver", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        assert self._server is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            if chaos.check_accept("ipc"):
                conn.close()  # injected EOF-on-accept; sender sees a clean close
                continue
            conn = chaos.wrap(conn, "ipc")
            threading.Thread(
                target=self._drain_conn, args=(conn,), name="ipc-receiver-conn", daemon=True
            ).start()

    def _drain_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                obj = read_object(conn)
                with self._lock:
                    self._messages.append(obj)
                if self._on_message is not None:
                    try:
                        self._on_message(obj)
                    except Exception:
                        log.exception("ipc on_message callback failed")
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def fetch(self) -> list[Any]:
        """Return and clear all queued messages."""
        with self._lock:
            msgs, self._messages = self._messages, []
        return msgs

    def peek(self) -> list[Any]:
        with self._lock:
            return list(self._messages)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass


def send_to(path: str, obj: Any, timeout: float = 30.0) -> None:
    """Fire-and-forget a single object at a UDS listener."""
    sock = connect(path, timeout=timeout)
    try:
        write_object(sock, obj)
    finally:
        sock.close()
