"""Coordination key-value store with first-class distributed barriers.

This is the control-plane substrate of the framework — the TPU-native re-design of the
reference's ``torch.distributed.TCPStore`` + ``StoreMixin`` barrier protocol
(``inprocess/store.py:48-368``) and of the c10d store used by its rendezvous. Unlike the
reference, which builds barriers *client-side* out of add/get primitives (and needs careful
key hygiene, overflow checks, and "monitor completes the barrier for dead ranks" tricks),
this store implements barriers, sets, lists and heartbeats as *server-side* operations:

- reentrant generation-counted barriers (the reference's ``reentrant_barrier``,
  ``iteration_barrier``, ``termination_barrier``; ``store.py:180-311``),
- joining a barrier **on behalf of another rank** without blocking — how a monitor process
  completes barriers for a dead main process (reference ``monitor_process.py:260-282``),
- interruption records and terminated-rank sets (``store.py`` record APIs),
- per-rank heartbeat timestamps with prefix scans (``sibling_monitor.py:26-57``).

Security: frames are pickled, so deserialization is code execution. The server therefore
binds loopback-only unless an ``auth_key`` is provided, in which case every connection
must complete an HMAC-SHA256 challenge/response before any frame is processed (the
analogue of the reference's ``AuthkeyMsg`` handshake, ``fault_tolerance/data.py:141``).
The launcher generates the key and hands it to workers via ``TPU_RESILIENCY_STORE_KEY``.

Concurrency: each client keeps one persistent socket for fast non-blocking ops; any
operation that may block server-side for more than a few seconds (barrier joins, waiting
``get``\\ s) runs on its own one-shot connection so heartbeats and other control traffic
are never starved behind it. A transport error invalidates the persistent socket (framing
can no longer be trusted); the next call transparently reconnects.

Rank 0 hosts the server in-process, exactly as the reference's rank 0 hosts the TCPStore
(``inprocess/store.py:311,345-353``). This store carries only small control messages
(bytes–KBs at restart boundaries); per-step telemetry rides the ICI mesh as JAX
collectives instead (see ``telemetry``).
"""

from __future__ import annotations

import collections
import dataclasses
import errno
import hashlib
import hmac
import itertools
import os
import secrets
import selectors
import socket
import threading
import time
from typing import Any, Callable, Iterable, Optional

from tpu_resiliency.exceptions import (
    BarrierOverflow,
    BarrierTimeout,
    StoreError,
    StoreShutdownError,
    StoreTimeoutError,
    StoreTransportError,
)
from tpu_resiliency.platform import chaos, framing
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

AUTH_KEY_ENV = "TPU_RESILIENCY_STORE_KEY"

#: Serving-backend identity reported by ``store_stats`` (``backend`` field).
#: The thread-per-connection ancestor predates the field, so readers map a
#: missing field to ``"threaded"`` — version-skew stays one `.get()` away.
BACKEND = "epoll"

# Ops whose server-side wait can exceed this run on a dedicated one-shot connection so
# they never hold the persistent socket's lock across a long block.
_BLOCKING_THRESHOLD_S = 5.0

_LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1", "")

#: Ops whose server-side effect is safe to apply twice: the client transparently
#: reconnect-and-retries these on a transport failure (a lost *response* just
#: repeats the read/overwrite). Mutations here are last-writer-wins (set/touch)
#: or set-union (set_add) — reapplication is a no-op.
_IDEMPOTENT_OPS = frozenset({
    "ping", "get", "getv", "check", "set", "delete", "touch", "stale",
    "prefix_get", "prefix_clear", "num_keys", "keys", "barriers",
    "wait_changed", "list_get", "list_clear", "set_get", "set_add",
    "barrier_status", "barrier_del", "barrier_census", "store_stats",
})

#: Ops where a blind retry double-applies (increment, append, CAS, barrier
#: arrival): the client mints a per-call ``req_id`` nonce and the server dedups
#: (bounded LRU), giving at-most-once application under the same retry loop.
_NONIDEMPOTENT_OPS = frozenset({"add", "cas", "list_append", "barrier"})
assert not (_IDEMPOTENT_OPS & _NONIDEMPOTENT_OPS)

#: Server-side request-dedup LRU capacity. Sized for in-flight retries, not
#: history: an entry is only ever consulted within one client call's retry
#: budget (seconds), and each entry is a small response dict.
_DEDUP_MAX = 4096


def _retry_event(op: str, outcome: str) -> None:
    """One ``store_retry`` record per retry decision (→
    ``tpu_store_retries_total{op,outcome}`` via the events→metrics bridge).
    Retries only happen on transport faults, so the volume is per-fault, not
    per-op."""
    record_event("store", "store_retry", op=op, outcome=outcome)


#: Process-wide circuit breakers, keyed by (host, port): the monotonic instant
#: until which calls to that endpoint fail fast instead of burning a retry
#: budget. An agent holds several clients to one store (rendezvous, jobs
#: registry, restart watcher); when the store host legitimately exits, ONE of
#: them paying one budget is diagnosis enough — teardown must not serialize
#: N × retry_budget of sleeps. Shared state, not per-client, for that reason.
_breakers: dict[tuple[str, int], float] = {}
#: Consecutive trips per endpoint since the last success. Each re-trip doubles
#: the cooldown (capped): an endpoint that stays dead gets probed with
#: exponentially decaying frequency instead of costing one full retry budget
#: per cooldown window — under HA failover routing that re-probe IS the
#: steady-state degraded tail, so its frequency is the p95.
_breaker_streaks: dict[tuple[str, int], int] = {}
_breakers_lock = threading.Lock()
_BREAKER_COOLDOWN_CAP = 30.0


def _breaker_open(host: str, port: int) -> bool:
    with _breakers_lock:
        return time.monotonic() < _breakers.get((host, port), 0.0)


def _breaker_trip(host: str, port: int, cooldown: float) -> None:
    with _breakers_lock:
        streak = _breaker_streaks.get((host, port), 0) + 1
        _breaker_streaks[(host, port)] = streak
        eff = min(cooldown * (2 ** min(streak - 1, 16)),
                  max(cooldown, _BREAKER_COOLDOWN_CAP))
        _breakers[(host, port)] = time.monotonic() + eff


def _breaker_clear(host: str, port: int) -> None:
    with _breakers_lock:
        _breakers.pop((host, port), None)
        _breaker_streaks.pop((host, port), None)


def breaker_open(host: str, port: int) -> bool:
    """Public read-only view of the endpoint circuit breaker. The HA clique
    client (``platform/shardstore.py``) routes around a shard whose breaker
    is open — straight to the successor replica — instead of paying even the
    fail-fast round trip on every op while the shard is down."""
    return _breaker_open(host, port)


def _hmac(key: str, nonce: bytes) -> bytes:
    return hmac.new(key.encode(), nonce, hashlib.sha256).digest()


def _client_hello(sock: socket.socket, auth_key: str | None) -> None:
    """Client side of the server hello (+ optional HMAC challenge). The ONE
    definition of the wire handshake shared by the persistent client and the
    liveness probe — a protocol change updated in only one place would make
    ``store_answers`` silently report every live store as dead."""
    hello = framing.recv_obj(sock, max_frame=1024)
    if not isinstance(hello, dict) or "auth" not in hello:
        raise StoreError("malformed store hello")
    if hello["auth"]:
        if not auth_key:
            raise StoreError(
                f"store requires authentication; set ${AUTH_KEY_ENV} or pass auth_key"
            )
        framing.send_obj(sock, {"mac": _hmac(auth_key, hello["nonce"])})


@dataclasses.dataclass
class _Barrier:
    generation: int = 0
    arrived: set = dataclasses.field(default_factory=set)
    world_size: int = 0
    #: ranks marked absent by on-behalf (proxy) joins — sticky across generations:
    #: a dead rank stays dead for every subsequent round of this barrier name, so
    #: watchers need not — but may, idempotently — re-proxy each round. Reset when a
    #: round opens with a different world size (elastic membership change).
    absent: set = dataclasses.field(default_factory=set)
    #: world size of the last round that opened, for detecting elastic changes
    last_world: int = 0
    #: per-rank arrival instants of the in-progress round (server monotonic) —
    #: the ``barrier_census`` waiter-age source; cleared on release
    arrived_at: dict = dataclasses.field(default_factory=dict)
    #: when the in-progress round opened (first join); 0 between rounds
    opened_at: float = 0.0


@dataclasses.dataclass
class _Park:
    """A blocking request parked on the event loop under a wait key: re-checked
    when that key is notified by a mutation (``ready`` returns the response once
    satisfied) and expired at ``deadline`` (responding ``{"status": "timeout"}``)."""

    ready: Callable[[], Optional[dict]]
    deadline: float
    wait_key: tuple


class _Conn:
    """Per-connection state on the event loop: incremental frame parser, pending
    write buffer, auth state, and at most one parked request (the client protocol
    is strictly request/response per socket)."""

    __slots__ = ("sock", "rbuf", "wbuf", "awaiting_mac", "nonce", "park",
                 "auth_deadline", "recv_ts", "frame_bytes")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.awaiting_mac = False
        self.nonce: bytes = b""
        self.park: Optional[_Park] = None
        self.auth_deadline: float = 0.0
        #: op-telemetry stamps: when the request's bytes landed on the socket
        #: (queue wait = dispatch - recv_ts) and the parsed frame's wire size
        self.recv_ts: float = 0.0
        self.frame_bytes: int = 0


class KVServer:
    """Event-loop TCP server holding the coordination state.

    One instance per job, hosted by the coordinator (rank 0 or the launcher). A
    single selector thread owns all state — no locks, no thread-per-connection:
    every operation is a pure in-memory mutation, and operations that must wait
    (``get`` with a timeout, blocking barrier joins) are *parked* as continuations
    re-evaluated after each mutation instead of parking a thread in a condition
    wait. Thousands of persistent connections therefore cost file descriptors, not
    stacks, and the op rate is bounded by one core's dict-op throughput rather than
    lock convoys.

    **Scale model (measured — ``tests/platform/test_store_scale.py``):** on one
    modest host, 1024 → 4096 live persistent clients: connect storm 0.14 → 0.37 s,
    ~26k small ops/s *flat in client count* (idle connections cost nothing per
    op — parked-deadline scans touch only parked requests), full-world barrier
    release 0.05 → 0.30 s, batched world-size prefix scan 1.3 → 4.2 ms. Every hot
    path batches (``prefix_get``, server-side ``stale_keys`` scans, per-round
    namespace GC) so per-tick traffic is O(1) requests per rank, not per key.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        auth_key: str | None = None,
        auth_timeout: float = 30.0,
        stats_enabled: bool = True,
        stats_interval: float = 10.0,
    ):
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        if host not in _LOOPBACK_HOSTS and not auth_key:
            raise ValueError(
                f"refusing to bind KVServer on non-loopback {host!r} without an auth_key "
                f"(frames are pickled; unauthenticated exposure is remote code execution). "
                f"Pass auth_key= or set ${AUTH_KEY_ENV}."
            )
        self.auth_key = auth_key
        self.auth_timeout = auth_timeout
        self._data: dict[str, Any] = {}
        #: key → value of the GLOBAL mutation clock at the key's last write
        #: (set/add/cas/touch bump it): ``wait_changed`` parks against it so
        #: clients can watch a key for ANY change — including back to a
        #: previously-seen value — without polling. Deletion drops the entry
        #: (version reverts to 0, itself a visible change, and the global
        #: clock makes a later re-create differ from every earlier version),
        #: so the table's size is bounded by live keys. One blind spot by
        #: design: a create+delete pair completing entirely between a
        #: watcher's reads looks like "never existed".
        self._versions: dict[str, int] = {}
        self._version_clock = 0
        self._lists: dict[str, list] = {}
        self._sets: dict[str, set] = {}
        self._barriers: dict[str, _Barrier] = {}
        self._stale_cache: dict[tuple[str, float], tuple[float, dict]] = {}
        #: request-dedup LRU: req_id → ("resp", response_dict) once the
        #: response exists, or ("barrier", (name, gen)) while a blocking join
        #: that already *applied* its arrival is still parked. Gives retried
        #: non-idempotent ops (add/cas/list_append/barrier) at-most-once
        #: application across reconnects: apply + cache happen atomically on
        #: the single loop thread, so a retry either replays the cached
        #: response or finds nothing applied at all.
        self._dedup: collections.OrderedDict[str, tuple] = collections.OrderedDict()
        self._shutdown = threading.Event()
        #: op telemetry (utils/opstats.py): loop-thread-owned, lock-free. A
        #: collector exception disables stats for the server's lifetime and
        #: degrades the store_stats document — never the op path.
        self._opstats = None
        self._stats_error: Optional[str] = None
        self.stats_interval = stats_interval
        self._last_stats_emit = time.monotonic()
        #: countdown to the next sampled (clocked) op; starts at 1 so the
        #: very first op is sampled and a short-lived store gets quantiles.
        #: The reload is jittered (LCG) — a fixed stride aliases with
        #: periodic workloads (a strict set/get alternation would put EVERY
        #: sample on the same op and double-count it).
        self._stats_tick = 1
        self._stats_seed = 0x5EED
        #: set by a sampled op so _send attributes exactly that op's
        #: response bytes (scaled); False costs one short-circuit per send
        self._stats_armed = False
        if stats_enabled:
            from tpu_resiliency.utils.opstats import OpStats

            self._opstats = OpStats()

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # A fixed port may still be held by a previous job's lingering server
        # (wrap.py server_linger keeps it listening briefly after completion) or
        # by a close() whose loop thread has not yet released the fd —
        # SO_REUSEADDR does not allow a second live listener. Retry briefly so
        # back-to-back jobs on one host don't die on EADDRINUSE.
        deadline = time.monotonic() + (8.0 if port != 0 else 0.0)
        while True:
            try:
                self._sock.bind((host, port))
                break
            except OSError as e:
                if e.errno != errno.EADDRINUSE or time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)
        self._sock.listen(1024)
        self._sock.setblocking(False)
        self.port = self._sock.getsockname()[1]
        self.host = host

        # Self-pipe so close() (any thread) can wake the loop immediately.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)

        # Reserve fd for fd-exhaustion shedding: when accept() hits EMFILE, the
        # pending connection would re-fire the level-triggered selector forever.
        # Closing the reserve frees one fd to accept-and-close the peer (it sees a
        # clean disconnect and can retry), then the reserve is reopened.
        try:
            self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
        except OSError:
            self._reserve_fd = None

        self._sel = selectors.DefaultSelector()
        self._sel.register(self._sock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._conns: dict[socket.socket, _Conn] = {}
        self._parked: set[_Conn] = set()  # conns with a parked request (O(parked) scans)
        #: wait-key → parked conns; mutations notify only their own key's waiters,
        #: so a full-world blocking barrier does not tax unrelated traffic.
        self._waiters: dict[tuple, set[_Conn]] = {}
        self._awaiting_auth: set[_Conn] = set()

        self._loop_thread = threading.Thread(
            target=self._loop, name="kvstore-loop", daemon=True
        )
        self._loop_thread.start()

    # -- lifecycle ---------------------------------------------------------

    def close(self, join: bool = True, timeout: float = 5.0) -> None:
        """Signal the loop thread to tear down. With ``join`` (default) block
        until the listening socket is actually released, so a successor server
        can bind the same fixed port immediately."""
        self._shutdown.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if join and threading.current_thread() is not self._loop_thread:
            self._loop_thread.join(timeout)

    # -- event loop --------------------------------------------------------

    def _loop(self) -> None:
        try:
            while not self._shutdown.is_set():
                timeout = 1.0
                now = time.monotonic()
                for c in self._parked:
                    timeout = min(timeout, max(0.0, c.park.deadline - now))
                for c in self._awaiting_auth:
                    timeout = min(timeout, max(0.0, c.auth_deadline - now))
                try:
                    for key, events in self._sel.select(timeout=timeout):
                        if key.data == "accept":
                            self._accept()
                        elif key.data == "wake":
                            try:
                                self._wake_r.recv(4096)
                            except OSError:
                                pass
                        else:
                            conn: _Conn = key.data
                            if events & selectors.EVENT_WRITE:
                                self._flush(conn)
                            if events & selectors.EVENT_READ:
                                self._read(conn)
                    self._expire_parked()
                    # `now` is the loop-top stamp — stale by at most one
                    # select, irrelevant at a multi-second emit interval and
                    # one fewer clock read per wakeup.
                    if (
                        self._opstats is not None
                        and now - self._last_stats_emit >= self.stats_interval
                    ):
                        self._emit_stats()
                except Exception:
                    # A coordinator must not die on one bad connection; per-conn
                    # errors are handled inline, so this is a genuine bug — log it
                    # and keep serving.
                    log.exception("store: event-loop error (continuing)")
        finally:
            self._teardown()

    # -- op telemetry ------------------------------------------------------

    def _stats_disable(self, e: Exception) -> None:
        """First collector exception wins: stop paying for a broken collector
        and surface the failure through the stats document, never the op."""
        self._stats_error = repr(e)
        self._opstats = None
        log.warning(f"store: op-stats collector failed; stats disabled: {e!r}")

    def _emit_stats(self) -> None:
        """One ``store_stats`` event with counter deltas (loop thread) — the
        live/post-hoc parity path: replaying the stream reconstructs the same
        ``tpu_store_*`` totals the live registry holds. Called when the loop's
        interval check fires, and once at teardown so even a short-lived
        store leaves its totals behind."""
        st = self._opstats
        if st is None:
            return
        self._last_stats_emit = time.monotonic()
        try:
            deltas = st.take_deltas()
        except Exception as e:
            self._stats_disable(e)
            return
        if deltas is None:
            return
        record_event(
            "store", "store_stats",
            conns=len(self._conns), parked=len(self._parked), **deltas,
        )

    def _teardown(self) -> None:
        self._emit_stats()
        shutdown_resp = {"status": "error", "error": repr(RuntimeError("store shut down"))}
        for conn in list(self._conns.values()):
            if conn.park is not None:
                conn.park = None
                self._parked.discard(conn)
                try:  # best-effort: tell blocked clients rather than hang them
                    conn.sock.setblocking(True)
                    conn.sock.settimeout(1.0)
                    # Drain any buffered response bytes first: writing the
                    # shutdown frame past an undrained wbuf would interleave
                    # frames and corrupt the client's stream.
                    if conn.wbuf:
                        conn.sock.sendall(conn.wbuf)
                        conn.wbuf.clear()
                    framing.send_obj(conn.sock, shutdown_resp)
                except OSError:
                    pass
            self._drop(conn)
        for s in (self._sock, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        if self._reserve_fd is not None:
            try:
                os.close(self._reserve_fd)
            except OSError:
                pass
            self._reserve_fd = None
        self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._sock.accept()
            except BlockingIOError:
                return
            except OSError as e:
                if e.errno in (errno.EMFILE, errno.ENFILE):
                    if self._reserve_fd is None:
                        # A previous shed lost the race to reopen the reserve;
                        # keep trying so shedding never stays disabled for life.
                        try:
                            self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
                        except OSError:
                            return
                    # Shed the pending connection via the reserve fd so the
                    # selector doesn't busy-spin on the still-readable listener.
                    os.close(self._reserve_fd)
                    self._reserve_fd = None
                    try:
                        shed, _ = self._sock.accept()
                        shed.close()
                        log.warning("store: fd limit reached; shed one connection")
                    except OSError:
                        pass
                    finally:
                        try:
                            self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
                        except OSError:
                            self._reserve_fd = None
                    continue
                return
            if chaos.check_accept("store"):
                # Injected EOF-on-accept: the client sees a clean close before
                # any frame and retries its connect.
                sock.close()
                continue
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            if self._opstats is not None:
                try:
                    self._opstats.note_conn(len(self._conns))
                except Exception as e:
                    self._stats_disable(e)
            # Connection hello; challenge/response when auth is on. A peer that
            # never completes the challenge is dropped at the deadline (the
            # threaded server's 30 s handshake timeout).
            conn.nonce = secrets.token_bytes(16)
            if self.auth_key is not None:
                conn.awaiting_mac = True
                conn.auth_deadline = time.monotonic() + self.auth_timeout
                self._awaiting_auth.add(conn)
            self._send(
                conn, {"v": 1, "auth": self.auth_key is not None, "nonce": conn.nonce}
            )

    def _drop(self, conn: _Conn) -> None:
        if conn.park is not None:
            waiters = self._waiters.get(conn.park.wait_key)
            if waiters is not None:
                waiters.discard(conn)
                if not waiters:
                    self._waiters.pop(conn.park.wait_key, None)
        self._parked.discard(conn)
        self._awaiting_auth.discard(conn)
        self._conns.pop(conn.sock, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    #: Per-connection buffer caps — the backpressure the threaded design got for
    #: free from blocking sockets. A legitimate client has at most one request in
    #: flight and drains responses promptly; a peer violating either is dropped.
    _MAX_RBUF = framing.DEFAULT_MAX_FRAME + 65536
    _MAX_WBUF = 4 * framing.DEFAULT_MAX_FRAME

    def _send(self, conn: _Conn, obj: Any) -> None:
        frame = framing.encode_obj(obj)
        if self._stats_armed:
            # Sampled-scaled outbound byte tally: exactly the sampled op's
            # response, ×SAMPLE — same estimate semantics as the op tallies.
            self._stats_armed = False
            if self._opstats is not None:
                self._opstats.bytes_out += len(frame) * self._opstats.SAMPLE
        conn.wbuf += frame
        if len(conn.wbuf) > self._MAX_WBUF:
            log.warning("store: dropping connection with %d B of undrained responses",
                        len(conn.wbuf))
            self._drop(conn)
            return
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        try:
            while conn.wbuf:
                sent = conn.sock.send(conn.wbuf)
                del conn.wbuf[:sent]
        except BlockingIOError:
            pass
        except OSError:
            self._drop(conn)
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if conn.wbuf else 0)
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError):
            pass

    def _read(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(256 * 1024)
        except BlockingIOError:
            return
        except OSError:
            self._drop(conn)
            return
        if not chunk:
            self._drop(conn)  # peer gone; any parked request dies with it
            return
        if self._opstats is not None and self._stats_tick <= 1:
            # Queue-wait anchor, read only by the next (sampled) op. A frame
            # that crosses the sample boundary mid-chunk finds recv_ts == 0
            # and skips its wait observation — under-sampling, never a stale
            # stamp.
            conn.recv_ts = time.perf_counter()
        conn.rbuf += chunk
        if len(conn.rbuf) > self._MAX_RBUF:
            log.warning("store: dropping connection with %d B of unparsed input",
                        len(conn.rbuf))
            self._drop(conn)
            return
        self._parse(conn)

    def _parse(self, conn: _Conn) -> None:
        """Consume complete frames from the read buffer. A connection with a parked
        request stops parsing (strict request/response: the next frame is only
        legal after our reply) but keeps buffering."""
        while conn.park is None and conn.sock in self._conns:
            max_frame = 1024 if conn.awaiting_mac else framing.DEFAULT_MAX_FRAME
            try:
                decoded = framing.decode_frame(conn.rbuf, max_frame=max_frame)
            except Exception:  # oversized or unpicklable frame
                self._drop(conn)
                return
            if decoded is None:
                return
            obj, consumed = decoded
            del conn.rbuf[:consumed]
            conn.frame_bytes = consumed
            if conn.awaiting_mac:
                mac = obj.get("mac", b"") if isinstance(obj, dict) else b""
                ok = isinstance(mac, (bytes, bytearray)) and hmac.compare_digest(
                    bytes(mac), _hmac(self.auth_key, conn.nonce)
                )
                if not ok:
                    log.warning("store: rejected connection with bad auth")
                    self._drop(conn)
                    return
                conn.awaiting_mac = False
                self._awaiting_auth.discard(conn)
                continue
            self._handle_request(conn, obj)

    def _handle_request(self, conn: _Conn, req: Any) -> None:
        # Op telemetry, fully sampled: 1 op in OpStats.SAMPLE pays the whole
        # accounting (op/error/byte tallies scaled by SAMPLE, queue wait =
        # socket readable → here, handle = the dispatch itself — a park is a
        # wait, not work, and parks aren't re-counted on wake); the other
        # SAMPLE-1 ops pay ONE counter decrement. Exact per-op counting was
        # measured at 2-4 µs/op of py3.10 attribute traffic — a >5% tax on a
        # ~35 µs loopback op, which is why every figure in the doc is a
        # sampled estimate and the knob stays ON by default. Contained: a
        # collector bug disables stats, the response still goes out.
        sampled = False
        t0 = 0.0
        if self._opstats is not None:
            self._stats_tick -= 1
            if self._stats_tick <= 0:
                # Jittered reload, mean SAMPLE (6..10): breaks phase lock
                # with periodic op mixes.
                seed = (self._stats_seed * 1103515245 + 12345) & 0x7FFFFFFF
                self._stats_seed = seed
                self._stats_tick = self._opstats.SAMPLE - 2 + seed % 5
                self._stats_armed = True
                sampled = True
                t0 = time.perf_counter()
        try:
            resp = self._dispatch(req)
        except BarrierOverflow as e:
            resp = {"status": "overflow", "error": str(e)}
        except TimeoutError:
            resp = {"status": "timeout"}
        except Exception as e:  # surface server-side faults to the client
            resp = {"status": "error", "error": repr(e)}
        if sampled and self._opstats is not None:
            try:
                is_dict = type(req) is dict
                self._opstats.note_op(
                    req.get("op", "?") if is_dict else "?",
                    (t0 - conn.recv_ts) if conn.recv_ts else -1.0,
                    time.perf_counter() - t0,
                    conn.frame_bytes,
                    req if is_dict else None,
                    type(resp) is dict
                    and resp.get("status") not in ("ok", None),
                )
                conn.recv_ts = 0.0  # consumed: never reused as a stale anchor
            except Exception as e:
                self._stats_disable(e)
        if isinstance(resp, _Park):
            ready = resp.ready()
            if ready is not None:
                self._send(conn, ready)
            elif resp.deadline <= time.monotonic():
                self._send(conn, {"status": "timeout"})
            else:
                conn.park = resp
                self._parked.add(conn)
                self._waiters.setdefault(resp.wait_key, set()).add(conn)
        else:
            self._send(conn, resp)

    def _notify(self, wait_key: tuple) -> None:
        """Wake the parked requests waiting on `wait_key` (called by the mutation
        that may have satisfied them); each re-checks its condition."""
        waiters = self._waiters.get(wait_key)
        if not waiters:
            return
        for conn in list(waiters):
            if conn.park is None:
                continue
            resp = conn.park.ready()
            if resp is not None:
                self._unpark(conn)
                self._send(conn, resp)
                self._parse(conn)  # drain any frames buffered while parked

    def _unpark(self, conn: _Conn) -> None:
        waiters = self._waiters.get(conn.park.wait_key)
        if waiters is not None:
            waiters.discard(conn)
            if not waiters:
                self._waiters.pop(conn.park.wait_key, None)
        conn.park = None
        self._parked.discard(conn)

    def _expire_parked(self) -> None:
        now = time.monotonic()
        for conn in list(self._parked):
            if conn.park is not None and conn.park.deadline <= now:
                self._unpark(conn)
                self._send(conn, {"status": "timeout"})
                self._parse(conn)
        for conn in list(self._awaiting_auth):
            if conn.awaiting_mac and conn.auth_deadline <= now:
                log.warning("store: dropping connection that never authenticated")
                self._drop(conn)

    # -- operation dispatch ------------------------------------------------

    def _dispatch(self, req: dict) -> Any:
        op = req["op"]
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            return {"status": "error", "error": f"unknown op {op!r}"}
        req_id = req.get("req_id")
        if req_id is not None:
            hit = self._dedup.get(req_id)
            st = self._opstats
            if st is not None:  # inline attribute adds: this is a hot path
                st.dedup_lookups += 1
                if hit is not None and hit[0] == "resp":
                    st.dedup_hits += 1
            if hit is not None and hit[0] == "resp":
                # Retry of a request that fully applied; replay the recorded
                # response instead of re-applying the mutation.
                self._dedup.move_to_end(req_id)
                return hit[1]
        resp = handler(req)
        if req_id is not None:
            if isinstance(resp, _Park):
                resp = self._park_caching(req_id, resp)
            else:
                self._dedup_put(req_id, ("resp", resp))
        return resp

    def _dedup_put(self, req_id: str, entry: tuple) -> None:
        od = self._dedup
        od[req_id] = entry
        od.move_to_end(req_id)
        while len(od) > _DEDUP_MAX:
            od.popitem(last=False)

    def _park_caching(self, req_id: str, park: _Park) -> _Park:
        """Wrap a park so its eventual response is recorded under ``req_id``
        the moment it materializes (release via ``_notify``) — a retry arriving
        after the release replays it instead of re-joining."""
        inner = park.ready

        def ready() -> Optional[dict]:
            r = inner()
            if r is not None:
                self._dedup_put(req_id, ("resp", r))
            return r

        return _Park(ready=ready, deadline=park.deadline, wait_key=park.wait_key)

    @staticmethod
    def _ok(value: Any = None) -> dict:
        return {"status": "ok", "value": value}

    def _op_ping(self, req: dict) -> dict:
        return self._ok("pong")

    def _bump(self, key: str) -> int:
        self._version_clock += 1
        self._versions[key] = self._version_clock
        return self._version_clock

    def _op_set(self, req: dict) -> dict:
        self._data[req["key"]] = req["value"]
        self._bump(req["key"])
        self._notify(("k", req["key"]))
        return self._ok()

    def _op_get(self, req: dict) -> Any:
        deadline = time.monotonic() + req.get("timeout", 0.0)
        key = req["key"]

        def ready() -> Optional[dict]:
            if key in self._data:
                return self._ok(self._data[key])
            return None

        return _Park(ready=ready, deadline=deadline, wait_key=("k", key))

    def _op_check(self, req: dict) -> dict:
        return self._ok(all(k in self._data for k in req["keys"]))

    def _op_delete(self, req: dict) -> dict:
        existed = req["key"] in self._data
        self._data.pop(req["key"], None)
        if existed:
            # Drop (not bump): version reverts to 0 — different from whatever
            # any watcher saw — and the table stays bounded by live keys.
            self._versions.pop(req["key"], None)
            self._notify(("k", req["key"]))
        return self._ok(existed)

    def _op_add(self, req: dict) -> dict:
        new = int(self._data.get(req["key"], 0)) + int(req["amount"])
        self._data[req["key"]] = new
        self._bump(req["key"])
        self._notify(("k", req["key"]))
        return self._ok(new)

    def _op_cas(self, req: dict) -> dict:
        """Compare-and-set: set key to `desired` iff current == `expected`.

        `expected=None` means "key must be absent". Returns (success, current_value).
        Analogue of the c10d rendezvous backend's CAS state blob
        (reference ``rendezvous/c10d_rendezvous_backend.py``).
        """
        current = self._data.get(req["key"])
        if current == req["expected"]:
            self._data[req["key"]] = req["desired"]
            self._bump(req["key"])
            self._notify(("k", req["key"]))
            return self._ok((True, req["desired"]))
        return self._ok((False, current))

    def _op_getv(self, req: dict) -> dict:
        key = req["key"]
        return self._ok((self._data.get(key), self._versions.get(key, 0)))

    def _op_wait_changed(self, req: dict) -> Any:
        """Park until ``key``'s mutation version differs from ``seen_version``
        (set/add/cas/delete all count, even back to the same value), then
        return ``(value, new_version)`` — the event-driven alternative to
        polling a CAS state blob (rendezvous close detection rides this)."""
        deadline = time.monotonic() + req.get("timeout", 0.0)
        key, seen = req["key"], req["seen_version"]

        def ready() -> Optional[dict]:
            v = self._versions.get(key, 0)
            if v != seen:
                return self._ok((self._data.get(key), v))
            return None

        return _Park(ready=ready, deadline=deadline, wait_key=("k", key))

    def _op_prefix_get(self, req: dict) -> dict:
        prefix = req["prefix"]
        return self._ok({k: v for k, v in self._data.items() if k.startswith(prefix)})

    def _op_num_keys(self, req: dict) -> dict:
        return self._ok(len(self._data))

    def _op_keys(self, req: dict) -> dict:
        """Key names only under a prefix — introspection without hauling
        values (prefix_get on a 4096-rank job's store moves megabytes)."""
        prefix = req.get("prefix", "")
        return self._ok(sorted(k for k in self._data if k.startswith(prefix)))

    def _op_barriers(self, req: dict) -> dict:
        """Names of live barriers (states via ``barrier_status``)."""
        return self._ok(sorted(self._barriers))

    def _op_list_append(self, req: dict) -> dict:
        self._lists.setdefault(req["key"], []).append(req["value"])
        return self._ok()

    def _op_list_get(self, req: dict) -> dict:
        return self._ok(list(self._lists.get(req["key"], [])))

    def _op_list_clear(self, req: dict) -> dict:
        self._lists.pop(req["key"], None)
        return self._ok()

    def _op_set_add(self, req: dict) -> dict:
        s = self._sets.setdefault(req["key"], set())
        s.update(req["values"])
        return self._ok(len(s))

    def _op_set_get(self, req: dict) -> dict:
        return self._ok(set(self._sets.get(req["key"], set())))

    @staticmethod
    def _barrier_maybe_release(b: _Barrier) -> bool:
        covered = len(b.arrived | b.absent)
        if b.world_size and covered >= b.world_size:
            b.generation += 1
            b.arrived = set()  # absent stays: dead ranks stay dead for future rounds
            b.arrived_at = {}
            b.world_size = 0
            b.opened_at = 0.0
            return True
        return False

    def _op_barrier(self, req: dict) -> Any:
        """Join barrier `name` as `rank`; release when `world_size` ranks are covered.

        Three join modes:

        - ``wait=True`` — arrive and block until the round releases (the normal join).
        - ``wait=False`` — *register* arrival and return immediately; the caller polls
          ``barrier_status`` for the release (how completers overlap the barrier wait
          with interruption watching). Duplicate registrations are no-ops.
        - ``on_behalf=True`` — proxy join *for a dead rank*: the rank is marked absent
          stickily, counting toward this and every future round of the name until the
          world size changes (reference ``monitor_process.py:260-282``). Repeats are
          no-ops; release fires only on a coverage *transition*, so a late duplicate
          proxy can neither plant a phantom arrival nor re-release a finished round.

        A dead-marked rank arriving itself gets :class:`BarrierOverflow` — the
        falsely-declared-dead signal the restart loop converts into exclusion.
        Reentrant: each completed round bumps the generation (reference
        ``reentrant_barrier``, ``store.py:244``); a round opening with a different
        world size (elastic shrink/grow) resets the absent set, since rank identities
        were remapped by reassignment.

        A blocking join parks on the *barrier object* (not the name): if the barrier
        is deleted and recreated while a waiter is parked, the waiter keeps waiting on
        the old object until its deadline — same behavior as the threaded server had.
        On timeout the arrival stays in place: a late joiner may still release
        everyone; callers treat barrier timeout as fatal anyway.
        """
        name, rank = req["name"], req["rank"]
        world_size = int(req["world_size"])
        deadline = time.monotonic() + req.get("timeout", 0.0)
        req_id = req.get("req_id")
        if req_id is not None:
            hit = self._dedup.get(req_id)
            if hit is not None and hit[0] == "barrier":
                # Retry of a blocking join whose arrival already landed (the
                # first attempt's connection died while parked). Re-wait on
                # the same round without re-applying — a blind re-join would
                # surface as a spurious "joined twice" overflow.
                bname, gen0 = hit[1]
                b0 = self._barriers.get(bname)
                if b0 is None:
                    return self._ok(None)
                if b0.generation != gen0:
                    return self._ok(b0.generation)

                def replay_ready() -> Optional[dict]:
                    if b0.generation != gen0:
                        return self._ok(b0.generation)
                    return None

                return _Park(
                    ready=replay_ready, deadline=deadline, wait_key=("b", id(b0))
                )
        b = self._barriers.setdefault(name, _Barrier())
        if b.world_size and b.world_size != world_size:
            # Mismatch within an in-progress round is a protocol error.
            if b.arrived:
                raise BarrierOverflow(
                    f"barrier {name!r}: world_size {world_size} != in-progress "
                    f"round's {b.world_size}"
                )
            # Proxy-only round (world size held open by on_behalf joins with no
            # real arrivals): a join under a different world size re-opens the
            # round; the first-join branch below then clears the stale absences
            # (last_world != world_size always holds here), which must not
            # phantom-cover the new rank numbering.
            b.world_size = 0
        if b.world_size == 0:  # first join of a round
            if b.last_world and b.last_world != world_size:
                # Elastic membership change: stale absences refer to the old
                # rank numbering and must not count toward the new round.
                b.absent = set()
            b.last_world = world_size
            b.opened_at = time.monotonic()
        b.world_size = world_size
        gen = b.generation
        if req.get("on_behalf", False):
            if rank not in b.absent:
                b.absent.add(rank)
                if self._barrier_maybe_release(b):
                    self._notify(("b", id(b)))
            return self._ok(None)
        if rank in b.absent:
            raise BarrierOverflow(
                f"barrier {name!r}: rank {rank} was proxied as dead"
            )
        if rank in b.arrived:
            if not req.get("wait", True):
                return self._ok(None)  # idempotent re-registration
            raise BarrierOverflow(f"barrier {name!r}: rank {rank} joined twice")
        b.arrived.add(rank)
        b.arrived_at[rank] = time.monotonic()
        if len(b.arrived | b.absent) > world_size:
            raise BarrierOverflow(
                f"barrier {name!r}: {len(b.arrived | b.absent)} arrivals > "
                f"world {world_size}"
            )
        if req_id is not None and req.get("wait", True):
            # Arrival applied but the response may be a long way off (park):
            # mark it so a retried join re-waits instead of double-arriving.
            # Overwritten with the real response when it materializes.
            self._dedup_put(req_id, ("barrier", (name, gen)))
        if self._barrier_maybe_release(b):
            self._notify(("b", id(b)))
            return self._ok(b.generation)
        if not req.get("wait", True):
            return self._ok(None)

        def ready() -> Optional[dict]:
            if b.generation != gen:
                return self._ok(b.generation)
            return None

        return _Park(ready=ready, deadline=deadline, wait_key=("b", id(b)))

    def _op_barrier_del(self, req: dict) -> dict:
        """Drop barrier `name` exactly (no prefix semantics — ``barrier/iter/1`` must
        not take ``barrier/iter/10`` with it)."""
        existed = self._barriers.pop(req["name"], None) is not None
        return self._ok(existed)

    def _op_barrier_status(self, req: dict) -> dict:
        b = self._barriers.get(req["name"])
        if b is None:
            return self._ok(None)
        return self._ok(
            {
                "generation": b.generation,
                "arrived": set(b.arrived),
                "absent": set(b.absent),
                "world_size": b.world_size,
            }
        )

    def _op_barrier_census(self, req: dict) -> dict:
        """Snapshot of every barrier with an in-progress round: who arrived
        (with waiter ages), who is proxied absent, and — the hang-forensics
        payoff — who is *missing*: the ranks the waiters are blocked on.

        ``prefix`` optionally scopes the scan. One response answers "what is
        the job waiting on, and on whom" without touching any value keys —
        the live half of the ``/hangz`` census and ``tpu-store-info
        --barriers``.
        """
        prefix = req.get("prefix", "")
        now = time.monotonic()
        out = {}
        for name, b in self._barriers.items():
            if prefix and not name.startswith(prefix):
                continue
            if not b.world_size:
                continue  # between rounds: nobody is waiting here
            arrived = {
                int(r): round(max(0.0, now - ts), 3)
                for r, ts in b.arrived_at.items()
                if r in b.arrived
            }
            known = set(b.arrived) | set(b.absent)
            missing = sorted(
                r for r in range(b.world_size) if r not in known
            )
            out[name] = {
                "generation": b.generation,
                "world_size": b.world_size,
                "arrived": arrived,
                "absent": sorted(b.absent),
                "missing": missing,
                "open_age_s": round(max(0.0, now - b.opened_at), 3)
                if b.opened_at else 0.0,
            }
        return self._ok(out)

    def _op_touch(self, req: dict) -> dict:
        """Store the *server's* wall time under `key`. Heartbeat freshness must be
        judged by one clock — comparing a peer host's ``time.time()`` against the local
        one turns NTP offset into false UNRESPONSIVE verdicts."""
        self._data[req["key"]] = time.time()
        self._bump(req["key"])
        self._notify(("k", req["key"]))
        return self._ok()

    def _op_stale(self, req: dict) -> dict:
        """Return ``{key: age}`` for keys under `prefix` whose touch-stamp is older
        than `max_age` seconds by the server clock.

        This is the watchers' liveness query: the response carries only the *stale*
        entries, so N watchers polling every second costs O(stale) wire traffic, not
        O(N²) full-table transfers. Scans are coalesced through a short-lived cache —
        liveness tolerates a second of slack, the event loop does not tolerate
        N full scans per second.
        """
        prefix, max_age = req["prefix"], float(req["max_age"])
        cached = self._stale_cache.get((prefix, max_age))
        now = time.time()
        if cached is not None and now - cached[0] < 1.0:
            return self._ok(dict(cached[1]))
        out = {}
        for k, v in self._data.items():
            # bool is an int subclass: a True/False flag under the prefix must
            # not be read as a ~epoch-0 timestamp and reported forever-stale.
            if k.startswith(prefix) and isinstance(v, (int, float)) and not isinstance(v, bool):
                age = now - v
                if age > max_age:
                    out[k] = age
        self._stale_cache[(prefix, max_age)] = (now, out)
        return self._ok(dict(out))

    def _op_prefix_clear(self, req: dict) -> dict:
        """Delete every datum, list, set, and barrier whose key starts with `prefix` —
        the GC hook that keeps per-iteration restart state (interruption records,
        completion flags, old barriers) from accumulating for the job's lifetime."""
        prefix = req["prefix"]
        removed = 0
        for table in (self._data, self._lists, self._sets, self._barriers):
            dead = [k for k in table if k.startswith(prefix)]
            for k in dead:
                del table[k]
                if table is self._data:
                    self._versions.pop(k, None)
                    self._notify(("k", k))
            removed += len(dead)
        self._stale_cache.clear()
        return self._ok(removed)

    def _op_store_stats(self, req: dict) -> dict:
        """The server's self-telemetry document (schema ``tpu-store-stats-1``):
        per-op latency (queue wait vs handle split), bytes in/out, connection
        counts, dedup-LRU hit rate, barrier park depth, hot key prefixes.

        Idempotent and read-only. A broken/disabled collector degrades the
        document (``enabled: false`` + ``error``), never the op — this is the
        instrument the perf work is judged with, so it must answer even when
        it has nothing to say."""
        base = {
            # Serving-backend identity for skew-aware tooling: this server is
            # the selectors event loop; a document with NO backend field is a
            # pre-epoll (thread-per-connection) build — tpu-store-info renders
            # the absence as "threaded".
            "backend": BACKEND,
            "conns": len(self._conns),
            "parked": len(self._parked),
            "barriers_open": sum(
                1 for b in self._barriers.values() if b.world_size
            ),
            "keys": len(self._data),
            "dedup_entries": len(self._dedup),
        }
        if self._opstats is None:
            from tpu_resiliency.utils.opstats import SCHEMA as STATS_SCHEMA

            doc = {"schema": STATS_SCHEMA, "enabled": False, **base}
            if self._stats_error:
                doc["error"] = self._stats_error
            return self._ok(doc)
        try:
            doc = self._opstats.snapshot()
        except Exception as e:
            self._stats_disable(e)
            from tpu_resiliency.utils.opstats import SCHEMA as STATS_SCHEMA

            return self._ok({
                "schema": STATS_SCHEMA, "enabled": False,
                "error": self._stats_error, **base,
            })
        doc.update(base)
        return self._ok(doc)


class KVClient:
    """Client for :class:`KVServer`: one persistent connection for fast ops, one-shot
    connections for long-blocking ops. Thread-safe."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
    ):
        self.host, self.port = host, port
        self.default_timeout = timeout
        #: total wall-clock budget for transparent transport-failure retries of
        #: one call (exponential backoff 50ms → 1s). 0 disables retrying.
        self.retry_budget = retry_budget
        if auth_key is None:
            auth_key = os.environ.get(AUTH_KEY_ENV) or None
        self.auth_key = auth_key
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._closed = False
        #: req_id prefix unique to this client instance; the sequence makes
        #: each non-idempotent call's nonce unique for the server's dedup LRU.
        self._client_id = secrets.token_hex(8)
        self._req_seq = itertools.count()
        self._sock = self._connect(connect_retries)

    def _connect(self, retries: int = 3) -> socket.socket:
        # Breaker open: one probe, no sleep ladder. Only clamps the small
        # in-call reconnect (an explicit high-retry construction — e.g. the
        # in-process Wrapper waiting out a store re-host — keeps its patience).
        if retries <= 3 and _breaker_open(self.host, self.port):
            retries = 1
        delay = 0.05
        last: Exception | None = None
        for _ in range(max(1, retries)):
            if self._closed:
                # close() raced the retry loop: stop reconnecting a client
                # nobody will ever use instead of sleeping out the budget.
                raise StoreError("store client is closed")
            try:
                chaos.check_connect("store", peer=f"{self.host}:{self.port}")
                sock = socket.create_connection((self.host, self.port), timeout=30.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock = chaos.wrap(sock, "store", peer=f"{self.host}:{self.port}")
                self._client_handshake(sock)
                return sock
            except (OSError, EOFError, StoreError, ValueError) as e:
                last = e
                time.sleep(delay)
                delay = min(delay * 1.7, 2.0)
        # Constructor-path connects raise from HERE, never reaching _call's
        # exhaustion bookkeeping — without this trip, a lazily-(re)constructed
        # client to a dead endpoint pays the full connect ladder on EVERY op
        # and the HA routing layer, which keys off the breaker, never learns
        # the shard is down.
        if self.retry_budget > 0:
            _breaker_trip(self.host, self.port, self.retry_budget)
        raise StoreTransportError(
            f"cannot connect to store at {self.host}:{self.port}: {last!r}"
        )

    def _client_handshake(self, sock: socket.socket) -> None:
        _client_hello(sock, self.auth_key)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def _call(self, req: dict, *, op_timeout: float | None = None) -> Any:
        """One request/response round-trip, transparently retried across
        transport faults.

        Fast ops share the persistent socket; ops whose server-side wait can be long run
        on their own one-shot connection so they never starve concurrent control traffic
        (e.g. a heartbeat behind a 300 s barrier join). The socket timeout exceeds the
        server-side operation timeout so server waits surface as protocol timeouts.
        Any transport error invalidates the persistent socket — a half-read frame means
        framing can no longer be trusted — and the call reconnect-and-retries under
        ``retry_budget`` with exponential backoff. Idempotent ops
        (:data:`_IDEMPOTENT_OPS`) reissue blindly; non-idempotent ops
        (:data:`_NONIDEMPOTENT_OPS`) carry a client-minted ``req_id`` nonce the
        server dedups, so a retry whose first attempt *did* land replays the
        recorded response instead of double-applying. Server-side error
        responses are never retried — only the wire is.
        """
        op = req.get("op")
        if op in _NONIDEMPOTENT_OPS and "req_id" not in req:
            req = dict(req, req_id=f"{self._client_id}:{next(self._req_seq)}")
        wait_s = op_timeout or 0.0
        breaker_open = _breaker_open(self.host, self.port)
        deadline = time.monotonic() + (0.0 if breaker_open else self.retry_budget)
        delay = 0.05
        failed = False
        while True:
            try:
                if wait_s > _BLOCKING_THRESHOLD_S:
                    out = self._call_oneshot(req, wait_s)
                else:
                    out = self._call_persistent(req, wait_s)
                if failed or breaker_open:
                    _breaker_clear(self.host, self.port)
                if failed:
                    _retry_event(op, "recovered")
                return out
            except StoreShutdownError:
                # Definitive: the server said goodbye. Reconnect-retrying this
                # endpoint inside the call buys nothing — open the breaker so
                # every client of it fails fast and HA routing moves on.
                if not breaker_open:
                    _breaker_trip(self.host, self.port, self.retry_budget)
                    _retry_event(op, "exhausted")
                raise
            except StoreTransportError:
                failed = True
                if self._closed or time.monotonic() + delay >= deadline:
                    if not breaker_open:
                        # A whole budget spent without one successful
                        # reconnect: open the breaker so subsequent calls (any
                        # client of this endpoint) fail fast instead of each
                        # burning a fresh budget against a server that is gone.
                        _breaker_trip(self.host, self.port, self.retry_budget)
                        _retry_event(op, "exhausted")
                    raise
                _retry_event(op, "retried")
                time.sleep(delay)
                delay = min(delay * 1.7, 1.0)

    def _call_persistent(self, req: dict, wait_s: float) -> Any:
        with self._lock:
            if self._closed:
                raise StoreError("store client is closed")
            if self._sock is None:
                self._sock = self._connect()
            self._sock.settimeout(wait_s + 60.0)
            try:
                framing.send_obj(self._sock, req)
                resp = framing.recv_obj(self._sock)
            except (ConnectionError, EOFError, OSError) as e:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                raise StoreTransportError(f"store transport failure: {e!r}") from e
        return self._parse(req, resp)

    def _call_oneshot(self, req: dict, wait_s: float) -> Any:
        sock = self._connect()
        try:
            sock.settimeout(wait_s + 60.0)
            try:
                framing.send_obj(sock, req)
                resp = framing.recv_obj(sock)
            except (ConnectionError, EOFError, OSError) as e:
                raise StoreTransportError(f"store transport failure: {e!r}") from e
        finally:
            try:
                sock.close()
            except OSError:
                pass
        return self._parse(req, resp)

    @staticmethod
    def _parse(req: dict, resp: Any) -> Any:
        if not isinstance(resp, dict):
            raise StoreError("malformed store response")
        status = resp.get("status")
        if status == "ok":
            return resp.get("value")
        if status == "timeout":
            raise StoreTimeoutError(f"store op {req.get('op')} timed out")
        if status == "overflow":
            raise BarrierOverflow(resp.get("error", ""))
        err = resp.get("error")
        if isinstance(err, str) and "store shut down" in err:
            # Teardown cut a parked op loose: the op did NOT complete and the
            # endpoint is going away. That is a transport-class failure, not a
            # server-side verdict — surfacing it as one lets HA clique clients
            # fail a graceful shard shutdown over to the successor exactly
            # like a SIGKILL'd shard.
            raise StoreShutdownError(
                f"store op {req.get('op')} aborted by server shutdown"
            )
        raise StoreError(f"store op {req.get('op')} failed: {resp.get('error')}")

    # -- primitive ops -----------------------------------------------------

    def ping(self) -> bool:
        return self._call({"op": "ping"}) == "pong"

    def set(self, key: str, value: Any) -> None:
        self._call({"op": "set", "key": key, "value": value})

    def get(self, key: str, timeout: float | None = None) -> Any:
        t = self.default_timeout if timeout is None else timeout
        return self._call({"op": "get", "key": key, "timeout": t}, op_timeout=t)

    def try_get(self, key: str, default: Any = None) -> Any:
        """Opportunistic read: ``default`` on a missing key *or* a transport
        failure (retry budget exhausted against a dead socket/server). Callers
        use this for best-effort probes — they must never crash on a blip."""
        try:
            return self.get(key, timeout=0.0)
        except StoreTimeoutError:
            return default
        except StoreError:
            if self._closed:
                raise
            return default

    def check(self, keys: Iterable[str]) -> bool:
        return self._call({"op": "check", "keys": list(keys)})

    def delete(self, key: str) -> bool:
        return self._call({"op": "delete", "key": key})

    def add(self, key: str, amount: int = 1) -> int:
        return self._call({"op": "add", "key": key, "amount": amount})

    def compare_set(self, key: str, expected: Any, desired: Any) -> tuple[bool, Any]:
        return tuple(self._call({"op": "cas", "key": key, "expected": expected, "desired": desired}))

    def get_versioned(self, key: str) -> tuple[Any, int]:
        """``(value_or_None, mutation_version)`` — the version feeds
        :meth:`wait_changed`."""
        return tuple(self._call({"op": "getv", "key": key}))

    def wait_changed(
        self, key: str, seen_version: int, timeout: float
    ) -> tuple[bool, Any, int]:
        """Block until ``key`` mutates past ``seen_version`` (any set/add/cas/
        delete) or ``timeout`` elapses. Returns ``(changed, value, version)``;
        on timeout ``(False, None, seen_version)``. Event-driven replacement
        for sleep-polling a state key."""
        try:
            value, version = self._call(
                {
                    "op": "wait_changed",
                    "key": key,
                    "seen_version": seen_version,
                    "timeout": timeout,
                },
                op_timeout=timeout,
            )
            return True, value, version
        except StoreTimeoutError:
            return False, None, seen_version

    def prefix_get(self, prefix: str) -> dict[str, Any]:
        return self._call({"op": "prefix_get", "prefix": prefix})

    def prefix_clear(self, prefix: str) -> int:
        return self._call({"op": "prefix_clear", "prefix": prefix})

    def touch(self, key: str) -> None:
        self._call({"op": "touch", "key": key})

    def stale_keys(self, prefix: str, max_age: float) -> dict[str, float]:
        return self._call({"op": "stale", "prefix": prefix, "max_age": max_age})

    def num_keys(self) -> int:
        return self._call({"op": "num_keys"})

    def keys(self, prefix: str = "") -> list[str]:
        """Key names under ``prefix`` — values stay server-side."""
        return self._call({"op": "keys", "prefix": prefix})

    def barrier_names(self) -> list[str]:
        return self._call({"op": "barriers"})

    def list_append(self, key: str, value: Any) -> None:
        self._call({"op": "list_append", "key": key, "value": value})

    def list_get(self, key: str) -> list:
        return self._call({"op": "list_get", "key": key})

    def list_clear(self, key: str) -> None:
        self._call({"op": "list_clear", "key": key})

    def set_add(self, key: str, values: Iterable) -> int:
        return self._call({"op": "set_add", "key": key, "values": list(values)})

    def set_get(self, key: str) -> set:
        return self._call({"op": "set_get", "key": key})

    def barrier_join(
        self,
        name: str,
        rank: int,
        world_size: int,
        timeout: float,
        wait: bool = True,
        on_behalf: bool = False,
    ) -> Optional[int]:
        req = {
            "op": "barrier",
            "name": name,
            "rank": rank,
            "world_size": world_size,
            "timeout": timeout,
            "wait": wait,
            "on_behalf": on_behalf,
        }
        if wait and not on_behalf:
            # A blocking join is THE place a rank gets stuck in a collective:
            # tag the process's location beacon for the duration so the
            # watchdog's hang diagnosis can name the barrier.
            from tpu_resiliency.utils import location as location_mod

            with location_mod.barrier(name):
                return self._barrier_call(req, name, timeout)
        return self._barrier_call(req, name, timeout if wait else 0.0)

    def _barrier_call(self, req: dict, name: str, timeout: float) -> Optional[int]:
        try:
            return self._call(req, op_timeout=timeout)
        except StoreTimeoutError as e:
            raise BarrierTimeout(f"barrier {name!r} timed out after {timeout}s") from e

    def barrier_status(self, name: str) -> Optional[dict]:
        return self._call({"op": "barrier_status", "name": name})

    def barrier_census(self, prefix: str = "") -> dict[str, dict]:
        """Every in-progress barrier round under ``prefix``: arrived ranks
        with waiter ages, proxied-absent ranks, and the missing ranks the
        round is blocked on (``platform/store.py:_op_barrier_census``)."""
        return self._call({"op": "barrier_census", "prefix": prefix})

    def barrier_del(self, name: str) -> bool:
        return self._call({"op": "barrier_del", "name": name})

    def store_stats(self) -> dict:
        """The server's self-telemetry document (``tpu-store-stats-1``;
        ``platform/store.py:_op_store_stats``). Raises :class:`StoreError`
        against a pre-stats server — server-side *error responses* are never
        retried, so the unknown-op reply costs one round trip, not a retry
        budget (version-skew containment, tested both directions)."""
        return self._call({"op": "store_stats"})


class StoreView:
    """A prefix-scoped coordination API over a :class:`KVClient`.

    Provides the primitive surface of the reference's ``StoreMixin``
    (``inprocess/store.py:48-311``): namespaced KV ops, named reentrant barriers, and
    on-behalf barrier completion. The restart-protocol schema on top (interruption
    records, terminated sets, heartbeats) lives in
    ``inprocess/coordination.py:RestartCoordinator``. ``scoped()`` derives a deeper
    view, the per-restart-iteration namespace pattern (reference ``store.py:360
    PrefixStore``, ``wrap.py:417``).
    """

    def __init__(self, client: KVClient, prefix: str = ""):
        self.client = client
        self.prefix = prefix

    def _k(self, key: str) -> str:
        return f"{self.prefix}{key}"

    def scoped(self, prefix: str) -> "StoreView":
        return StoreView(self.client, f"{self.prefix}{prefix}/")

    # -- namespaced primitives --------------------------------------------

    def ping(self) -> bool:
        return self.client.ping()

    def set(self, key: str, value: Any) -> None:
        self.client.set(self._k(key), value)

    def get(self, key: str, timeout: float | None = None) -> Any:
        return self.client.get(self._k(key), timeout)

    def try_get(self, key: str, default: Any = None) -> Any:
        return self.client.try_get(self._k(key), default)

    def check(self, keys: Iterable[str]) -> bool:
        return self.client.check([self._k(k) for k in keys])

    def delete(self, key: str) -> bool:
        return self.client.delete(self._k(key))

    def add(self, key: str, amount: int = 1) -> int:
        return self.client.add(self._k(key), amount)

    def compare_set(self, key: str, expected: Any, desired: Any) -> tuple[bool, Any]:
        return self.client.compare_set(self._k(key), expected, desired)

    def get_versioned(self, key: str) -> tuple[Any, int]:
        return self.client.get_versioned(self._k(key))

    def wait_changed(
        self, key: str, seen_version: int, timeout: float
    ) -> tuple[bool, Any, int]:
        return self.client.wait_changed(self._k(key), seen_version, timeout)

    def prefix_get(self, prefix: str = "") -> dict[str, Any]:
        """Scan keys under this view; returned keys are relative to the view."""
        full = self._k(prefix)
        raw = self.client.prefix_get(full)
        start = len(self.prefix)
        return {k[start:]: v for k, v in raw.items()}

    def prefix_clear(self, prefix: str) -> int:
        return self.client.prefix_clear(self._k(prefix))

    def touch(self, key: str) -> None:
        self.client.touch(self._k(key))

    def stale_keys(self, prefix: str, max_age: float) -> dict[str, float]:
        raw = self.client.stale_keys(self._k(prefix), max_age)
        start = len(self.prefix)
        return {k[start:]: v for k, v in raw.items()}

    def list_append(self, key: str, value: Any) -> None:
        self.client.list_append(self._k(key), value)

    def list_get(self, key: str) -> list:
        return self.client.list_get(self._k(key))

    def list_clear(self, key: str) -> None:
        self.client.list_clear(self._k(key))

    def set_add(self, key: str, values: Iterable) -> int:
        return self.client.set_add(self._k(key), values)

    def set_get(self, key: str) -> set:
        return self.client.set_get(self._k(key))

    def barrier_join(self, name, rank, world_size, timeout, wait=True, on_behalf=False):
        return self.client.barrier_join(
            self._k(name), rank, world_size, timeout, wait, on_behalf
        )

    def barrier_status(self, name: str) -> Optional[dict]:
        return self.client.barrier_status(self._k(name))

    def barrier_census(self, prefix: str = "") -> dict[str, dict]:
        """Census of this view's in-progress barriers, names view-relative."""
        raw = self.client.barrier_census(self._k(prefix))
        start = len(self.prefix)
        return {k[start:]: v for k, v in raw.items()}

    def barrier_del(self, name: str) -> bool:
        return self.client.barrier_del(self._k(name))

    # -- restart-coordination API -----------------------------------------

    def barrier(self, name: str, rank: int, world_size: int, timeout: float) -> None:
        self.barrier_join(name, rank, world_size, timeout)

    def complete_barrier_for(self, name: str, rank: int, world_size: int) -> None:
        """Join `name` on behalf of (possibly dead) `rank` without blocking."""
        self.barrier_join(name, rank, world_size, timeout=0.0, wait=False, on_behalf=True)


class CoordStore(StoreView):
    """A :class:`StoreView` that owns its connection — the usual entry point."""

    def __init__(
        self,
        host: str,
        port: int,
        prefix: str = "",
        timeout: float = 300.0,
        connect_retries: int = 60,
        auth_key: str | None = None,
        retry_budget: float = 8.0,
    ):
        client = KVClient(
            host, port, timeout=timeout, connect_retries=connect_retries,
            auth_key=auth_key, retry_budget=retry_budget,
        )
        super().__init__(client, prefix)

    def close(self) -> None:
        self.client.close()


def host_store(
    rank: int,
    host: str,
    port: int,
    *,
    prefix: str = "",
    timeout: float = 300.0,
    auth_key: str | None = None,
) -> tuple[CoordStore, Optional[KVServer]]:
    """Rank 0 hosts a :class:`KVServer` and every rank connects a :class:`CoordStore`.

    Mirrors the reference pattern where rank 0 hosts the TCPStore
    (``inprocess/store.py:311,345-353``). Single-host jobs bind loopback; multi-host
    jobs must provide ``auth_key`` (or ``$TPU_RESILIENCY_STORE_KEY``) and a reachable
    ``host``. Returns ``(client, server_or_None)``.
    """
    server = None
    if rank == 0:
        effective_key = auth_key or os.environ.get(AUTH_KEY_ENV) or None
        bind_host = "0.0.0.0" if effective_key else "127.0.0.1"
        server = KVServer(host=bind_host, port=port, auth_key=effective_key)
        host = "127.0.0.1"
        port = server.port
    client = CoordStore(host, port, prefix=prefix, timeout=timeout, auth_key=auth_key)
    return client, server


def store_answers(
    host: str, port: int, *, auth_key: str | None = None, timeout: float = 1.0
) -> bool:
    """True iff a live :class:`KVServer` completes a handshake and answers
    ``ping`` within ``timeout``.

    Distinguishes a legitimately live store on a busy port (another job on a
    shared ``--rdzv-id`` endpoint — connect to it) from a lingering listener
    mid-teardown, which holds the port but never answers (wait out the bind
    retry). A would-be client can therefore decide instantly instead of paying
    the hosting path's EADDRINUSE retry window."""
    if auth_key is None:
        auth_key = os.environ.get(AUTH_KEY_ENV) or None
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except OSError:
        return False
    try:
        sock.settimeout(timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _client_hello(sock, auth_key)
        framing.send_obj(sock, {"op": "ping"})
        resp = framing.recv_obj(sock)
        return isinstance(resp, dict) and resp.get("value") == "pong"
    except (OSError, EOFError, ValueError, StoreError):
        return False
    finally:
        try:
            sock.close()
        except OSError:
            pass


def store_addr_from_env() -> tuple[str, int]:
    """Read the coordinator address from the environment (set by the launcher)."""
    host = os.environ.get("TPU_RESILIENCY_STORE_HOST", os.environ.get("MASTER_ADDR", "127.0.0.1"))
    port = int(os.environ.get("TPU_RESILIENCY_STORE_PORT", os.environ.get("MASTER_PORT", "29511")))
    return host, port


def _serve_forever(argv: Optional[list[str]] = None) -> int:
    """Standalone store server: ``python -m tpu_resiliency.platform.store
    [HOST:]PORT`` — a coordination store that OUTLIVES any one job, for
    multi-job endpoints (``tpu-ft-launcher --rdzv-id``) where a job-hosted
    store would die with the first job to finish. Runs until SIGTERM/SIGINT."""
    import argparse
    import signal as _signal

    ap = argparse.ArgumentParser(description=_serve_forever.__doc__)
    ap.add_argument("endpoint", nargs="?", default="127.0.0.1:29511")
    args = ap.parse_args(argv)
    host, _, port_s = args.endpoint.rpartition(":")
    server = KVServer(host=host or "127.0.0.1", port=int(port_s))
    print(f"store serving on {server.host}:{server.port}", flush=True)
    done = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, lambda *_: done.set())
    done.wait()
    server.close()
    return 0


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(_serve_forever())
