"""jax.distributed lifecycle for resilient jobs: initialize survivable, tear down
restartable, re-initialize with a new world.

The TPU-native analogue of the reference's NCCL abort + process-group destroy
(``inprocess/abort.py:58-105``): there, surviving ranks abort communicators so the
restarted iteration can rebuild collectives over a new group. Under JAX the
coordination layer is the distributed runtime client/service, and two facts
(measured on jax 0.9, CPU/Gloo backend — see tests/inprocess/test_abort_reinit.py)
shape this module:

- **Peer death is fatal by default.** The XLA distributed client LOG(FATAL)s the
  *surviving* process the moment the coordination service reports any peer dead
  ("Terminating process because the JAX distributed service detected fatal
  errors"). A resilient job must opt in to ``jax_enable_recoverability`` (jax >=
  0.7) at initialize time — after the fault it is too late.
- **Re-initialize requires dead backends.** ``jax.distributed.initialize`` refuses
  to run once the XLA backends are live, so the restart teardown must also clear
  them (dropping device buffers — the restart loop reloads state from local
  checkpoints anyway, ``checkpoint/local_manager.py``).

A collective already in flight against a dead peer can still block indefinitely
(Gloo has no liveness timeout); that case is the monitor process's hard-timeout
ladder (``inprocess/monitor_process.py``), not this module's. This module makes the
*between-steps* fault — the overwhelmingly common case — restartable in-process.
"""

from __future__ import annotations

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


def client_active() -> bool:
    """Is a jax.distributed client currently connected?"""
    import jax

    return jax._src.distributed.global_state.client is not None  # noqa: SLF001


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    *,
    heartbeat_timeout: float = 10.0,
    initialization_timeout: float = 60.0,
    recoverable: bool = True,
) -> None:
    """``jax.distributed.initialize`` with survivable-peer-death defaults.

    ``recoverable`` turns on ``jax_enable_recoverability`` so peer death surfaces
    as an error instead of terminating this process (required for any in-process
    restart); set it False only for jobs that prefer fail-fast semantics.
    """
    import jax

    if recoverable:
        try:
            jax.config.update("jax_enable_recoverability", True)
        except Exception:
            # Older jax: flag absent. The job still runs, but peer death will
            # kill survivors — only the in-job (launcher) restart layer applies.
            log.warning(
                "jax_enable_recoverability unavailable: peer death will "
                "terminate surviving processes (in-job restart still works)"
            )
    jax.distributed.initialize(
        coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        # jax takes whole seconds; never truncate a sub-second request to 0
        # (0 would disable/instant-fire the heartbeat).
        heartbeat_timeout_seconds=max(1, round(heartbeat_timeout)),
        initialization_timeout=max(1, round(initialization_timeout)),
    )
    log.info(
        f"jax.distributed initialized: world={num_processes} rank={process_id} "
        f"coordinator={coordinator_address} recoverable={recoverable}"
    )


def clear_backends() -> None:
    """Tear down live XLA backends (public API removed in jax 0.9)."""
    import jax

    try:
        jax.clear_backends()  # pre-0.9 public API
        return
    except AttributeError:
        pass
    import jax._src.xla_bridge as xb  # noqa: SLF001

    xb._clear_backends()  # noqa: SLF001


def shutdown_ordered(
    store,
    active_rank: int,
    active_world_size: int,
    *,
    iteration: int = 0,
    timeout: float = 30.0,
    key: str = "jd_shutdown_done",
) -> None:
    """Orderly END-OF-JOB teardown: coordinator's service outlives every peer.

    A recoverable client's shutdown barrier does not block (by design — see
    :func:`initialize`), so at job completion the coordinator (active rank 0,
    which hosts the coordination service) can exit before a peer's client sends
    its disconnect RPC; that late disconnect then LOG(FATAL)s the peer at
    interpreter exit. Here non-coordinator ranks shut down their clients first
    and announce on the job ``store``; the coordinator waits for every
    announcement (bounded by ``timeout``, best-effort beyond it) before tearing
    the service down. Call once per rank after the last collective, passing the
    restart ``iteration`` (stale announcements from an earlier, fault-aborted
    completion attempt must not satisfy this round's wait). Backends are left
    alive (nothing restarts after completion). Never raises: a completed job
    must not be re-classified as faulted because its teardown hiccuped.
    """
    import time as _time

    import jax

    if not client_active():
        return
    skey = f"{key}/{iteration}"
    if active_rank != 0:
        try:
            jax.distributed.shutdown()
        except Exception as e:
            log.warning(f"shutdown_ordered: client shutdown failed: {e!r}")
        try:
            store.set_add(skey, [int(active_rank)])
        except Exception as e:
            log.warning(f"shutdown_ordered: announcement failed: {e!r}")
        return
    expected = set(range(1, active_world_size))
    deadline = _time.monotonic() + timeout
    try:
        while _time.monotonic() < deadline:
            if set(store.set_get(skey)) >= expected:
                break
            _time.sleep(0.05)
        else:
            log.warning(
                f"shutdown_ordered: peers {expected - set(store.set_get(skey))} "
                f"never announced client shutdown within {timeout}s; tearing down "
                f"anyway"
            )
    except Exception as e:
        log.warning(f"shutdown_ordered: announcement wait failed: {e!r}")
    try:
        jax.distributed.shutdown()
    except Exception as e:
        log.warning(f"shutdown_ordered: coordinator shutdown failed: {e!r}")


def shutdown_graceful(process_id: int, grace: float = 5.0) -> None:
    """End-of-job teardown WITHOUT a coordination store: non-coordinator ranks
    disconnect immediately; the coordinator idles ``grace`` seconds before
    tearing its service down, so a peer's slightly-later disconnect RPC cannot
    LOG(FATAL) that peer at interpreter exit (recoverable clients have no
    synchronized shutdown barrier — see :func:`shutdown_ordered`, which is
    deterministic and preferred when a KV store is available). Typical use: the
    exit path after :class:`PreemptionCheckpointCallback` stops the loop.
    Never raises."""
    import time as _time

    import jax

    if not client_active():
        return
    try:
        # Only the coordinator waits, and only when peers exist whose late
        # disconnects its service must outlive (single-process worlds skip it).
        if process_id == 0 and jax.process_count() > 1:
            _time.sleep(grace)
        jax.distributed.shutdown()
    except Exception as e:
        log.warning(f"shutdown_graceful: {e!r}")


def shutdown_for_restart() -> bool:
    """Tear down the distributed client/service AND the XLA backends so a later
    :func:`initialize` with a different world is legal.

    Returns True when a distributed client was actually shut down (callers can
    skip backend-rebuild costs otherwise). Never raises: the restart loop must
    proceed no matter how broken the old world's state is.
    """
    import jax

    had_client = False
    try:
        had_client = client_active()
        if had_client:
            jax.distributed.shutdown()
            log.info("jax.distributed client/service shut down")
    except Exception as e:
        log.warning(f"jax.distributed.shutdown failed (continuing): {e!r}")
    if not had_client:
        return False
    try:
        jax.clear_caches()
        clear_backends()
        log.info("XLA backends cleared for re-initialize")
    except Exception as e:
        log.warning(f"backend teardown failed (continuing): {e!r}")
    return True
