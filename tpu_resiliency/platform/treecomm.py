"""Tree-structured barriers and gathers over the coordination store.

The flat collectives the store grew up with put O(N) work on ONE event loop:
a full-world barrier is N arrivals serialized through one selector thread and
N release frames sent from it; a flat ``all_gather`` adds N ``prefix_get``
responses each carrying the whole world's values. ``BENCH_store_baseline.json``
records the resulting curve — p50 37 µs at 1 client, 3.3 ms at 64 — and every
subsystem since PR 4 (reshard holder-gather, metrics push, barrier census,
fleet leases) stacked onto it.

This module restructures the two collective shapes through a ``fanout``-ary
tree over the *group index space* (0..world-1, parent of ``i`` is
``(i-1)//fanout``), so the critical path is O(fanout · log_fanout N) store
round trips instead of O(N), and — the compounding move — every tree edge is
its own store *key*, so under a sharded clique (``platform/shardstore.py``)
the edges hash across shards and no single event loop serializes the round.

Two primitives, both built from the store's existing parked-wait ops (no new
wire ops, no server change — an unmodified or even pre-epoll server serves
them):

- :func:`tree_barrier` — reentrant: per-tag edge keys hold round *numbers*
  (``u/{i}`` = "subtree i fully arrived for round r", ``d/{i}`` = "round r
  released down to i"), so repeated rounds mutate 2N small int keys instead
  of minting namespace. Waits ride ``wait_changed`` (event-driven, parked
  server-side — never a poll loop).
- :func:`tree_all_gather` — round-scoped fan-in of value dicts up the tree,
  result fan-out down per-child keys (each rank's result wait parks on its
  OWN key — shard-local, no thundering herd on one key), then an ack fan-in
  so index 0 deletes the round's keys only after every rank has read.

Failure semantics match the flat collectives: a dead rank starves its
ancestors' edge waits and the deadline surfaces as :class:`BarrierTimeout`
(callers treat that as fatal, exactly as before); transport faults under the
waits land on the client's existing retry/dedup ladder — every op here is
idempotent (set/get/wait_changed), so blind retries are safe. Proxy
(``on_behalf``) completion is NOT supported on tree rounds — restart-protocol
barriers that monitors complete for dead ranks stay on the flat server-side
barrier op.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from tpu_resiliency.exceptions import BarrierTimeout, StoreTimeoutError

#: Env knobs (read by the consumers — StoreComm, rendezvous — not here):
#: tree arity, and the smallest world a collective switches to tree shape at.
TREE_FANOUT_ENV = "TPU_RESILIENCY_STORE_TREE_FANOUT"
TREE_MIN_ENV = "TPU_RESILIENCY_STORE_TREE_MIN"
DEFAULT_FANOUT = 8
#: Worlds below this stay flat: at ≤16 members the flat barrier's single
#: server-side op per rank beats the tree's extra edge round trips, and the
#: restart-protocol's proxy-completion semantics (flat-only) keep working for
#: every group the monitors actually watch today.
DEFAULT_TREE_MIN = 17


def children(i: int, world: int, fanout: int) -> list[int]:
    """Child indices of node ``i`` in the ``fanout``-ary heap layout."""
    lo = fanout * i + 1
    return list(range(lo, min(lo + fanout, world)))


# -- scattered registration (the rendezvous join ladder's edge shape) --------
#
# The tree collectives above assume the group is already ranked. The
# rendezvous JOIN phase can't be — ranks don't exist until the round closes —
# so its tree-laddered form uses the degenerate one-level tree: every joiner
# publishes one *edge key* of its own (hash-scattered across a sharded
# clique, exactly like the barrier edges above), and the single aggregator
# (the round's opener/leader) folds them with concurrent prefix scans. That
# turns N contended CAS retries on ONE state key — each retry a full
# read-modify-write round trip through one event loop — into N independent
# one-hop sets plus O(N/batch) scans on the leader, the same
# serialization-killing move as the tree barrier's per-edge keys.

def scatter_register(store, scope: str, member: str, payload: Any = 1) -> None:
    """Publish ``member``'s registration under its own edge key — one
    idempotent ``set`` (safe under blind retry), no CAS, no contention."""
    store.set(f"{scope}/{member}", payload)


def scatter_collect(store, scope: str) -> dict[str, Any]:
    """Aggregator side: every registered member (name → payload), via the
    store's concurrent prefix scan (fans across clique shards)."""
    out = {}
    for k, v in store.prefix_get(f"{scope}/").items():
        out[k.rsplit("/", 1)[1]] = v
    return out


def scatter_clear(store, scope: str) -> int:
    """GC a finished scope's edge keys (aggregator, post-close)."""
    return store.prefix_clear(f"{scope}/")


def parent(i: int, fanout: int) -> int:
    return (i - 1) // fanout


def tree_depth(world: int, fanout: int) -> int:
    """Levels below the root (0 for a single-node tree)."""
    d, i = 0, world - 1
    while i > 0:
        i = parent(i, fanout)
        d += 1
    return d


def tree_hops(world: int, fanout: int) -> int:
    """Store round trips on the release critical path of one tree round:
    each level's deepest parent absorbs ≤ ``fanout`` child signals going up
    and emits ≤ ``fanout`` going down, plus the root's turn-around."""
    d = tree_depth(world, fanout)
    return 2 * fanout * d + 2


def flat_hops(world: int) -> int:
    """Serialized ops on the flat collective's critical path: N arrivals
    through one event loop, then N release/read responses from it."""
    return 2 * world


class TreeComm:
    """Tree collectives for one member of a fixed group.

    ``store`` is any :class:`~tpu_resiliency.platform.store.StoreView`-shaped
    object; ``index`` is this member's position in the group's sorted order
    (the tree runs in index space — callers map ranks to indices). Instances
    carry per-tag round counters, so every member must call each tagged
    collective the same number of times in the same order (the usual
    collective contract, identical to the flat paths).
    """

    def __init__(self, store, index: int, world: int, fanout: int = DEFAULT_FANOUT):
        if not 0 <= index < world:
            raise ValueError(f"index {index} outside world {world}")
        self.store = store
        self.index = index
        self.world = world
        self.fanout = max(2, int(fanout))
        self._kids = children(index, world, self.fanout)
        self._brounds: dict[str, int] = {}
        self._grounds: dict[str, int] = {}
        self._bcrounds: dict[str, int] = {}
        #: last-seen mutation versions of the reentrant barrier edge keys,
        #: so each wait_changed parks from where the previous round left off
        #: instead of re-reading history.
        self._seen: dict[str, int] = {}
        #: client-side op counter — the measured half of the hop accounting
        #: (``scripts/bench_store.py`` records it next to the analytic
        #: :func:`tree_hops` / :func:`flat_hops` figures).
        self.ops = 0

    # -- key-wait plumbing --------------------------------------------------

    def _await_value(self, key: str, want: int, deadline: float, tag: str) -> None:
        """Park until integer ``key`` reaches ``want`` (values are round
        numbers — monotonic, so ``>=`` absorbs a racing later round)."""
        self.ops += 1
        value, version = self.store.get_versioned(key)
        self._seen[key] = version
        while not (isinstance(value, int) and value >= want):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise BarrierTimeout(
                    f"tree barrier {tag!r}: timed out waiting for {key} "
                    f"to reach round {want} (index {self.index}/{self.world})"
                )
            self.ops += 1
            changed, value, version = self.store.wait_changed(
                key, self._seen[key], timeout=min(remaining, 30.0)
            )
            if changed:
                self._seen[key] = version

    def _set(self, key: str, value: Any) -> None:
        self.ops += 1
        self.store.set(key, value)

    def _get(self, key: str, timeout: float, tag: str) -> Any:
        self.ops += 1
        try:
            return self.store.get(key, timeout=max(0.0, timeout))
        except StoreTimeoutError as e:
            raise BarrierTimeout(
                f"tree collective {tag!r}: timed out waiting for {key} "
                f"(index {self.index}/{self.world})"
            ) from e

    # -- barrier ------------------------------------------------------------

    def barrier(self, tag: str = "barrier", timeout: float = 300.0) -> int:
        """Tree-structured barrier round; returns the completed round number.

        Up phase: every node waits for each child subtree's arrival key to
        reach this round, then publishes its own (the root's publication is
        implicit — collecting its children IS global arrival). Down phase:
        release propagates parent→child through per-child keys, so each
        waiter parks on its own key and the wake fan-out is ``fanout`` sets
        per node, not N frames from one loop.
        """
        r = self._brounds.get(tag, 0) + 1
        self._brounds[tag] = r
        deadline = time.monotonic() + timeout
        up, down = f"{tag}/u", f"{tag}/d"
        for c in self._kids:
            self._await_value(f"{up}/{c}", r, deadline, tag)
        if self.index != 0:
            self._set(f"{up}/{self.index}", r)
            self._await_value(f"{down}/{self.index}", r, deadline, tag)
        for c in self._kids:
            self._set(f"{down}/{c}", r)
        return r

    # -- all_gather ---------------------------------------------------------

    def all_gather(self, obj: Any, tag: str = "ag", timeout: float = 300.0) -> list:
        """Returns ``[obj_from_index]`` ordered by group index.

        Fan-in: each node merges its children's value dicts with its own and
        publishes the merged dict one level up — every level moves the
        world's values once, so total bytes are O(N log N) up plus the
        irreducible O(N · world_bytes) result fan-out (every member needs
        every value; that part no topology can shrink). Fan-out: the root's
        assembled result propagates parent→child on per-child keys. Ack
        fan-in: a node acks only after it AND its subtree have read, and
        index 0 deletes the round's namespace only after its own ack wait —
        the tree-shaped version of the flat path's exit barrier.
        """
        r = self._grounds.get(tag, 0)
        self._grounds[tag] = r + 1
        deadline = time.monotonic() + timeout
        base = f"{tag}/r{r}"
        merged: dict[int, Any] = {self.index: obj}
        for c in self._kids:
            sub = self._get(
                f"{base}/v/{c}", deadline - time.monotonic(), tag
            )
            merged.update(sub)
        if self.index == 0:
            if len(merged) != self.world:
                # Every subtree reported, yet values are missing: the store
                # lost state mid-round (restart) — surface, don't truncate.
                raise BarrierTimeout(
                    f"tree all_gather {tag!r} round {r}: root assembled "
                    f"{len(merged)}/{self.world} values"
                )
            result = merged
        else:
            self._set(f"{base}/v/{self.index}", merged)
            result = self._get(
                f"{base}/res/{self.index}", deadline - time.monotonic(), tag
            )
        for c in self._kids:
            self._set(f"{base}/res/{c}", result)
        # Read-complete ack up the tree, then the root GCs the round. An ack
        # means "me and my whole subtree have read", so when the root's ack
        # waits drain, nobody can still be parked under this round's keys.
        self._ack_and_gc(base, deadline, tag)
        return [result[i] for i in range(self.world)]

    def _ack_and_gc(self, base: str, deadline: float, tag: str) -> None:
        for c in self._kids:
            self._get(f"{base}/a/{c}", deadline - time.monotonic(), tag)
        if self.index != 0:
            self._set(f"{base}/a/{self.index}", 1)
        else:
            self.ops += 1
            self.store.prefix_clear(f"{base}/")

    # -- broadcast ----------------------------------------------------------

    def broadcast(
        self, obj: Any, src_index: int, tag: str = "bc", timeout: float = 300.0
    ) -> Any:
        """One value, source → everyone, through the tree.

        The source publishes under one round-scoped key (one hop — unless it
        IS the root); the root fans the value out parent→child on per-child
        keys exactly like :meth:`all_gather`'s result phase, so no single
        store loop serves N waiters and the critical path stays
        O(fanout · log N). Same ack fan-in + root GC as ``all_gather``.
        The flat broadcast parked the whole world on ONE key — the wake was
        N frames from one event loop, the shape this module exists to kill.
        """
        r = self._bcrounds.get(tag, 0)
        self._bcrounds[tag] = r + 1
        deadline = time.monotonic() + timeout
        base = f"{tag}/r{r}"
        if self.index == src_index:
            result = obj
            if self.index != 0:
                self._set(f"{base}/v", obj)
        if self.index == 0:
            result = obj if src_index == 0 else self._get(
                f"{base}/v", deadline - time.monotonic(), tag
            )
        elif self.index != src_index:
            result = self._get(
                f"{base}/res/{self.index}", deadline - time.monotonic(), tag
            )
        for c in self._kids:
            self._set(f"{base}/res/{c}", result)
        self._ack_and_gc(base, deadline, tag)
        return result
