from tpu_resiliency.platform.store import (
    CoordStore,
    KVClient,
    KVServer,
    StoreView,
    host_store,
    store_addr_from_env,
)
from tpu_resiliency.platform.shardstore import (
    CliqueStore,
    ShardedKVClient,
    connect_store,
)
from tpu_resiliency.platform.treecomm import TreeComm
from tpu_resiliency.platform.device import (
    Topology,
    DeviceInfo,
    device_liveness_probe,
    global_device_count,
    local_device_count,
    make_mesh,
    platform_kind,
    probe_topology,
    process_count,
    process_index,
)
from tpu_resiliency.platform import distributed, ipc

__all__ = [
    "distributed",
    "CoordStore",
    "KVClient",
    "KVServer",
    "StoreView",
    "CliqueStore",
    "ShardedKVClient",
    "TreeComm",
    "connect_store",
    "host_store",
    "store_addr_from_env",
    "Topology",
    "DeviceInfo",
    "device_liveness_probe",
    "global_device_count",
    "local_device_count",
    "make_mesh",
    "platform_kind",
    "probe_topology",
    "process_count",
    "process_index",
    "ipc",
]
