"""Shared wire framing: 8-byte big-endian length prefix + pickle payload.

Single implementation used by the TCP coordination store (``platform/store.py``), the
local UDS IPC (``platform/ipc.py``), and the checkpoint peer-exchange links
(``checkpoint/comm.py``) so the wire protocol evolves in one place. The length prefix
is 64-bit because peer-exchange frames carry whole checkpoint shards (multi-GB).
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
from typing import Any

LEN = struct.Struct("!Q")
DEFAULT_MAX_FRAME = 64 * 1024 * 1024


def encode_obj(obj: Any) -> bytes:
    """One frame, ready for the wire."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return LEN.pack(len(payload)) + payload


def decode_frame(buf, max_frame: int = DEFAULT_MAX_FRAME):
    """Incremental counterpart of :func:`recv_obj` for event-loop readers: try to
    decode one frame from the head of ``buf`` (any bytes-like). Returns
    ``(obj, bytes_consumed)``, or ``None`` if the frame is still incomplete.
    Raises ``ValueError`` on an oversized frame (the caller should drop the peer).
    """
    if len(buf) < LEN.size:
        return None
    (length,) = LEN.unpack(bytes(buf[: LEN.size]))
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    end = LEN.size + length
    if len(buf) < end:
        return None
    return pickle.loads(bytes(buf[LEN.size : end])), end


def send_obj(sock: socket.socket, obj: Any) -> None:
    sock.sendall(encode_obj(obj))


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def recv_obj(sock: socket.socket, max_frame: int = DEFAULT_MAX_FRAME) -> Any:
    (length,) = LEN.unpack(recv_exact(sock, LEN.size))
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    return pickle.loads(recv_exact(sock, length))


async def read_obj_stream(reader: asyncio.StreamReader, max_frame: int = DEFAULT_MAX_FRAME) -> Any:
    header = await reader.readexactly(LEN.size)
    (length,) = LEN.unpack(header)
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    return pickle.loads(await reader.readexactly(length))


async def write_obj_stream(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    writer.write(LEN.pack(len(payload)) + payload)
    await writer.drain()
