"""Shared wire framing: 8-byte big-endian length prefix + pickle payload.

Single implementation used by the TCP coordination store (``platform/store.py``), the
local UDS IPC (``platform/ipc.py``), and the checkpoint peer-exchange links
(``checkpoint/comm.py``) so the wire protocol evolves in one place. The length prefix
is 64-bit because peer-exchange frames carry whole checkpoint shards (multi-GB).

Because every channel funnels through these helpers, this is also the boundary
where deterministic network fault injection applies: ``platform/chaos.py`` wraps
the sockets handed to these functions (resets, mid-frame truncation, stalls —
see ``docs/chaos.md``), and the channels' retry layers are tested against it.

Two frame kinds share one stream (version 2 of the p2p protocol):

- **object frame** (v1, unchanged): ``len(!Q) | pickle`` — control messages and
  small payloads, and the compatibility format for whole-shard blobs.
- **bulk frame** (v2): ``BULK_MAGIC(8) | header_len(!Q) | header pickle | raw
  payload bytes`` — the streaming path for multi-GB shards. The header is a small
  pickled dict carrying routing metadata plus ``nbytes``; the payload never
  transits pickle. Senders scatter-gather an iovec list straight onto the socket
  (:func:`send_bulk`) or splice a file with ``os.sendfile`` (:func:`send_bulk_file`);
  receivers :func:`recv_any` into ONE preallocated buffer. ``BULK_MAGIC`` read as a
  v1 length prefix is ~6.1e18 bytes — beyond any ``max_frame`` — so an old receiver
  rejects a bulk frame cleanly instead of misparsing it, and a v1 length can never
  alias the magic.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import socket
import struct
from typing import Any, Optional, Sequence

LEN = struct.Struct("!Q")
DEFAULT_MAX_FRAME = 64 * 1024 * 1024

#: p2p protocol versions, negotiated via the hello's ``v`` field: a v2 sender
#: talking to a v1 receiver falls back to object frames; a v1 sender's object
#: frames are always accepted by a v2 receiver (``recv_any``).
PROTO_V1 = 1
PROTO_V2 = 2
PROTO_VERSION = PROTO_V2

#: Interpreted as a !Q length this is 6075449640710064946 — rejected by every
#: ``max_frame`` check a v1 peer could hold, so the two frame kinds are
#: self-discriminating on the first 8 bytes.
BULK_MAGIC = b"TPUBULK2"
assert LEN.unpack(BULK_MAGIC)[0] > (1 << 62)

#: Max pickled-header size of a bulk frame (routing metadata only, never payload).
MAX_BULK_HEADER = 1 << 20

#: Linux UIO_MAXIOV is 1024; batch sendmsg iovecs below it.
_IOV_MAX = 1000

#: Chunk size for the sendfile fallback read loop (no sendfile support / EINVAL).
_FILE_CHUNK = 4 * 1024 * 1024


def encode_obj(obj: Any) -> bytes:
    """One frame, ready for the wire."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return LEN.pack(len(payload)) + payload


def decode_frame(buf, max_frame: int = DEFAULT_MAX_FRAME):
    """Incremental counterpart of :func:`recv_obj` for event-loop readers: try to
    decode one frame from the head of ``buf`` (any bytes-like). Returns
    ``(obj, bytes_consumed)``, or ``None`` if the frame is still incomplete.
    Raises ``ValueError`` on an oversized frame (the caller should drop the peer).
    """
    if len(buf) < LEN.size:
        return None
    (length,) = LEN.unpack(bytes(buf[: LEN.size]))
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    end = LEN.size + length
    if len(buf) < end:
        return None
    return pickle.loads(bytes(buf[LEN.size : end])), end


def send_obj(sock: socket.socket, obj: Any) -> None:
    sock.sendall(encode_obj(obj))


def recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` completely from the socket — the single receive primitive
    every channel shares. ``recv_into`` writes straight into the caller's buffer,
    so no intermediate chunk objects or joins exist at any payload size."""
    while view.nbytes:
        n = sock.recv_into(view)
        if n == 0:
            raise EOFError("peer closed connection")
        view = view[n:]


def recv_exact(sock: socket.socket, n: int) -> memoryview:
    """Exactly ``n`` bytes as a view over one preallocated buffer.

    Returns a ``memoryview`` (bytes-like; fine for ``pickle.loads`` /
    ``struct.unpack``) rather than ``bytes`` — the historical
    ``bytes(bytearray)`` tail copied every payload a second time, which on the
    p2p channel meant an extra multi-GB allocation per shard.
    """
    buf = memoryview(bytearray(n))
    recv_exact_into(sock, buf)
    return buf


def recv_obj(sock: socket.socket, max_frame: int = DEFAULT_MAX_FRAME) -> Any:
    (length,) = LEN.unpack(recv_exact(sock, LEN.size))
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    return pickle.loads(recv_exact(sock, length))


async def read_obj_stream(reader: asyncio.StreamReader, max_frame: int = DEFAULT_MAX_FRAME) -> Any:
    header = await reader.readexactly(LEN.size)
    (length,) = LEN.unpack(header)
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    return pickle.loads(await reader.readexactly(length))


async def write_obj_stream(writer: asyncio.StreamWriter, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    writer.write(LEN.pack(len(payload)) + payload)
    await writer.drain()


# -- bulk (raw payload) frames ----------------------------------------------


def _byte_views(parts: Sequence[Any]) -> list[memoryview]:
    """Normalize bytes-like parts to flat uint8 views; drops empties."""
    views = []
    for p in parts:
        v = memoryview(p).cast("B")
        if v.nbytes:
            views.append(v)
    return views


def _sendmsg_all(sock: socket.socket, views: list[memoryview]) -> None:
    """Scatter-gather sendall: every byte of every view, no join.

    Handles partial sends (advance within a view) and iovec-count limits
    (batches of ``_IOV_MAX``). Falls back to per-view ``sendall`` where
    ``sendmsg`` is unavailable.
    """
    if not hasattr(sock, "sendmsg"):
        for v in views:
            sock.sendall(v)
        return
    idx = 0
    while idx < len(views):
        sent = sock.sendmsg(views[idx : idx + _IOV_MAX])
        while sent > 0:
            v = views[idx]
            if sent >= v.nbytes:
                sent -= v.nbytes
                idx += 1
            else:
                views[idx] = v[sent:]
                sent = 0


def _bulk_preamble(header: dict, nbytes: int) -> tuple[bytes, dict]:
    hdr = dict(header)
    hdr["nbytes"] = nbytes
    hb = pickle.dumps(hdr, protocol=pickle.HIGHEST_PROTOCOL)
    if len(hb) > MAX_BULK_HEADER:
        raise ValueError(f"bulk header too large: {len(hb)} > {MAX_BULK_HEADER}")
    return BULK_MAGIC + LEN.pack(len(hb)) + hb, hdr


def send_bulk(sock: socket.socket, header: dict, parts: Sequence[Any]) -> int:
    """One bulk frame: pickled ``header`` (stamped with ``nbytes``) + the raw
    bytes of ``parts``, scatter-gathered from the caller's buffers. No joined
    payload ever exists on the send side. Returns payload bytes sent."""
    views = _byte_views(parts)
    nbytes = sum(v.nbytes for v in views)
    pre, _ = _bulk_preamble(header, nbytes)
    _sendmsg_all(sock, [memoryview(pre), *views])
    return nbytes


def send_bulk_start(sock: socket.socket, header: dict, nbytes: int) -> None:
    """Open a bulk frame whose payload will be streamed in chunks.

    Sends the preamble (magic + pickled ``header`` stamped with the TOTAL
    ``nbytes``) and returns; the caller then pushes exactly ``nbytes`` payload
    bytes with plain ``sendall`` as they become available — e.g. checkpoint
    leaves resolving off the D2H queue. The receiver cannot tell a streamed
    frame from a :func:`send_bulk` one: ``recv_any`` just fills its buffer as
    bytes arrive, so the two ends pipeline naturally. Under-sending desyncs
    the stream — the caller must either complete the payload or close the
    socket (the receiver sees EOF and drops the frame)."""
    pre, _ = _bulk_preamble(header, nbytes)
    sock.sendall(pre)


def send_bulk_file(
    sock: socket.socket,
    header: dict,
    path: str,
    offset: int = 0,
    count: Optional[int] = None,
) -> int:
    """Bulk frame whose payload is spliced from ``path`` with ``os.sendfile`` —
    zero userspace copies for shards already on disk (mirror re-spreads, shard
    routing). Falls back to a bounded read/sendall loop where sendfile is
    unsupported. Returns payload bytes sent."""
    nbytes = (os.path.getsize(path) - offset) if count is None else count
    pre, _ = _bulk_preamble(header, nbytes)
    sock.sendall(pre)
    with open(path, "rb") as f:
        off, remaining = offset, nbytes
        use_sendfile = hasattr(os, "sendfile")
        while remaining:
            if use_sendfile:
                try:
                    sent = os.sendfile(sock.fileno(), f.fileno(), off, remaining)
                except OSError:
                    # EINVAL/ENOSYS (fs or platform without support): degrade to
                    # the copy loop for the rest of this payload.
                    use_sendfile = False
                    continue
                if sent == 0:
                    raise EOFError("peer closed connection during sendfile")
                off += sent
                remaining -= sent
            else:
                f.seek(off)
                chunk = f.read(min(_FILE_CHUNK, remaining))
                if not chunk:
                    raise EOFError(f"{path}: truncated during send")
                sock.sendall(chunk)
                off += len(chunk)
                remaining -= len(chunk)
    return nbytes


def recv_any(
    sock: socket.socket,
    max_frame: int = DEFAULT_MAX_FRAME,
    alloc=None,
):
    """Receive either frame kind from a stream that may carry both.

    Returns ``("obj", obj, None)`` for a v1 object frame or
    ``("bulk", header, payload_view)`` for a bulk frame. ``alloc(header)`` may
    return a writable preallocated buffer of at least ``header["nbytes"]`` bytes
    (a registered ``recv_into`` destination); returning ``None`` — or a too-small
    buffer — falls back to a fresh allocation. Either way the payload is received
    by ``recv_into`` directly into its final buffer: one allocation, zero copies.

    A bulk header carrying ``crc32c`` (senders opt in —
    ``PeerExchange(wire_checksums=True)``) is verified against the landed
    payload; a mismatch raises ``ValueError`` like any malformed frame, so the
    receive loop drops it and the sender-side retry/degrade machinery owns
    recovery. Verification is skipped (not failed) when the header's
    ``crc_algo`` is not the one this host computes.
    """
    head = recv_exact(sock, LEN.size)
    if bytes(head) == BULK_MAGIC:
        (hlen,) = LEN.unpack(recv_exact(sock, LEN.size))
        if hlen > MAX_BULK_HEADER:
            raise ValueError(f"bulk header too large: {hlen} > {MAX_BULK_HEADER}")
        header = pickle.loads(recv_exact(sock, hlen))
        nbytes = int(header["nbytes"])
        if nbytes > max_frame:
            raise ValueError(f"frame too large: {nbytes} > {max_frame}")
        buf = alloc(header) if alloc is not None else None
        if buf is not None:
            view = memoryview(buf).cast("B")
            if view.nbytes < nbytes:
                buf = None
        if buf is None:
            view = memoryview(bytearray(nbytes))
        payload = view[:nbytes]
        recv_exact_into(sock, payload)
        if "crc32c" in header:
            # Layering note: the checksum implementation lives with the
            # container format (one algo tag for disk and wire); import
            # lazily, only for frames that actually carry a CRC.
            from tpu_resiliency.checkpoint.format import CRC_ALGO, crc32c

            if header.get("crc_algo", CRC_ALGO) == CRC_ALGO and crc32c(
                payload
            ) != int(header["crc32c"]):
                raise ValueError(
                    f"bulk frame payload checksum mismatch "
                    f"({nbytes} bytes from src={header.get('src')!r})"
                )
        return "bulk", header, payload
    (length,) = LEN.unpack(head)
    if length > max_frame:
        raise ValueError(f"frame too large: {length} > {max_frame}")
    return "obj", pickle.loads(recv_exact(sock, length)), None
