"""Persistent XLA compilation cache that survives in-job restarts.

Every restarted worker used to re-trace and re-compile its step function from
scratch — on real models that is the dominant residual cost of a warm-spare
respawn (the interpreter floor is already paid, the XLA compile is not). This
module wires JAX's persistent compilation cache (``jax_compilation_cache_dir``)
into the launcher's env plumbing so round N+1's first step loads round N's
executables instead of recompiling:

- ``tpu-ft-launcher --compile-cache-dir DIR`` exports
  :data:`CACHE_DIR_ENV` (and ``JAX_COMPILATION_CACHE_DIR`` for workers that
  never import this package) to every worker, scoped under the run dir by
  convention so one job's cache never collides with another's.
- Workers apply it through :func:`apply_from_env` (called by
  ``inprocess/wrap.py`` at engine start and by
  ``platform/device.py:apply_platform_env``), which records ONE
  ``compile_cache`` event per process — outcome ``hit`` (valid entries were
  waiting), ``miss`` (cold cache), or ``miss_corrupt`` (damaged entries were
  purged) — feeding ``tpu_compile_cache_total{outcome}`` and the goodput
  ledger's restart attribution.

Integrity posture (the ``ckpt`` plane's rule, applied here): a corrupt cache
entry costs a cold compile, NEVER a crash and never a wrong executable. JAX
itself degrades unreadable entries to a warning, but only at first use deep in
a compile path; the sweep here verifies entries against a CRC **manifest**
up front and deletes mismatches, so damage is detected, counted, and evented
at process start — the same posture as the checkpoint recovery ladder's
"quarantine, then recompute". Entries newer than the manifest (written after
the last manifest refresh, e.g. by a worker that was SIGKILLed) cannot be
judged and are left for JAX's own decode-failure fallback.

The manifest is refreshed by the launcher after every round (the one process
that survives worker churn) and at worker interpreter exit — both
best-effort: a missing or stale manifest only narrows detection, never
correctness.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: exported by the launcher; consumed by :func:`apply_from_env` in workers
CACHE_DIR_ENV = "TPU_RESILIENCY_COMPILE_CACHE_DIR"

#: integrity manifest file kept inside the cache dir (never a cache entry:
#: JAX entry files end in ``-cache``)
MANIFEST_NAME = "MANIFEST.tpures.json"

#: only files with this suffix are cache entries (JAX writes ``<key>-cache``
#: payloads plus tiny ``-atime`` stamps we ignore)
_ENTRY_SUFFIX = "-cache"

#: process-level latch: the cache is applied (and its event recorded) once
_applied: Optional[dict] = None


def _entry_names(path: str) -> list[str]:
    try:
        return sorted(
            n for n in os.listdir(path) if n.endswith(_ENTRY_SUFFIX)
        )
    except OSError:
        return []


def _digest_file(p: str) -> tuple[int, int]:
    """(size, crc32) of a file, streamed."""
    crc = 0
    size = 0
    with open(p, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return size, crc


def scan(path: str) -> dict[str, list[int]]:
    """{entry_name: [size, crc32]} for every cache entry currently on disk."""
    out: dict[str, list[int]] = {}
    for name in _entry_names(path):
        try:
            size, crc = _digest_file(os.path.join(path, name))
        except OSError:
            continue  # racing writer/deleter: skip, never raise
        out[name] = [size, crc]
    return out


def write_manifest(path: str) -> int:
    """Atomically record the current entry digests; returns the entry count.
    Best-effort: an unwritable cache dir is a log line, not a failure."""
    entries = scan(path)
    doc = {"version": 1, "entries": entries}
    tmp = os.path.join(path, f"{MANIFEST_NAME}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    except OSError:
        log.debug("compile-cache manifest write failed", exc_info=True)
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return len(entries)


def read_manifest(path: str) -> dict[str, list[int]]:
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            doc = json.load(f)
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}
    except (OSError, ValueError):
        return {}


def sweep(path: str) -> dict:
    """Verify manifest-covered entries; purge mismatches (truncated, bit-flipped,
    torn) so they cost a cold compile instead of a decode failure — or worse.

    Returns ``{"entries", "bytes", "purged", "unverified"}`` where ``entries``/
    ``bytes`` count the cache AFTER the purge and ``unverified`` counts entries
    newer than the manifest (left in place for JAX's own fallback).
    """
    manifest = read_manifest(path)
    purged = 0
    for name, want in sorted(manifest.items()):
        p = os.path.join(path, name)
        if not os.path.exists(p):
            continue  # evicted/cleaned: not corruption
        try:
            size, crc = _digest_file(p)
        except OSError:
            continue
        if [size, crc] != list(want):
            log.warning(
                f"compile cache entry {name} fails integrity "
                f"({size}B/crc{crc:08x} != manifest {want}); purging — "
                "this program will cold-compile"
            )
            for victim in (p, p[: -len(_ENTRY_SUFFIX)] + "-atime"):
                try:
                    os.unlink(victim)
                except OSError:
                    pass
            purged += 1
    entries = 0
    total = 0
    names = _entry_names(path)
    for name in names:
        try:
            total += os.path.getsize(os.path.join(path, name))
            entries += 1
        except OSError:
            continue
    unverified = sum(1 for n in names if n not in manifest)
    return {
        "entries": entries, "bytes": total,
        "purged": purged, "unverified": unverified,
    }


def outcome_of(stats: dict) -> str:
    """Classify a sweep for the ``compile_cache`` event / metric."""
    if stats.get("purged"):
        return "miss_corrupt"
    return "hit" if stats.get("entries") else "miss"


def enable(path: str) -> dict:
    """Sweep ``path``, point JAX's persistent compilation cache at it, and
    register an exit-time manifest refresh. Returns the sweep stats.

    Every failure mode degrades to a cold compile: an unusable directory or a
    JAX without the cache config simply leaves caching off."""
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        log.warning(f"compile cache dir {path!r} unusable; caching disabled")
        return {"entries": 0, "bytes": 0, "purged": 0, "unverified": 0,
                "enabled": False}
    stats = sweep(path)
    stats["enabled"] = True
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # Loopback/test programs compile in microseconds; without a zero
        # threshold nothing under 1 s would ever be cached and every restart
        # bench would read as a miss.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        log.warning("JAX persistent compilation cache unavailable", exc_info=True)
        stats["enabled"] = False
        return stats
    import atexit

    atexit.register(lambda: write_manifest(path))
    return stats


def apply_from_env(record: bool = True) -> Optional[dict]:
    """Apply :data:`CACHE_DIR_ENV` once per process; None when unset or when
    already applied. On first application records the ``compile_cache``
    event (hit / miss / miss_corrupt + entry count and bytes)."""
    global _applied
    path = os.environ.get(CACHE_DIR_ENV, "")
    if not path or _applied is not None:
        return None
    stats = enable(path)
    stats["outcome"] = outcome_of(stats)
    _applied = stats
    if record and stats.get("enabled"):
        from tpu_resiliency.utils.events import record as record_event

        record_event(
            "platform", "compile_cache",
            outcome=stats["outcome"], entries=stats["entries"],
            bytes=stats["bytes"], purged=stats["purged"],
            unverified=stats["unverified"], dir=path,
        )
    return stats


def refresh_manifest_from_env() -> None:
    """Launcher-side post-round manifest refresh: covers workers that died
    without their atexit hook (SIGKILL, OOM). Cheap — CRC of a few files."""
    path = os.environ.get(CACHE_DIR_ENV, "")
    if path and os.path.isdir(path):
        write_manifest(path)
