"""Deterministic network + disk fault injection for the out-of-band planes.

``inprocess/tools/inject_fault.py`` covers process- and device-level faults
(SIGKILL, GIL lockup, device hang); this module covers the faults a real
pod-slice *network* produces — connection resets, mid-frame truncation,
latency/jitter, short-read stalls, EOF on accept, and partition of a named
peer — injected at the socket boundary shared by all three out-of-band
channels (``platform/framing.py`` callers):

- ``store``  — the :class:`~tpu_resiliency.platform.store.KVClient` /
  ``KVServer`` coordination channel (client sockets + server accepts),
- ``p2p``    — :class:`~tpu_resiliency.checkpoint.comm.PeerExchange`
  replication links (dial, send/recv, accepts),
- ``ipc``    — the UDS channel (``platform/ipc.py``: ``connect``, receiver
  accepts/reads),

plus a fourth, **disk**, channel covering the faults node-local *storage*
produces against checkpoint containers (``checkpoint/format.py``'s patchable
IO shim): silent bit flips, post-commit tail truncation, torn renames
(rename journaled, data blocks lost), ``ENOSPC``, and slow IO. Disk rules use
``op`` = ``write`` (every container write call: header prefix, each leaf,
trailer, striped pwrites) or ``commit`` (the ``.dirty``→visible rename), and
their ``peer=`` names the target file as its final
``<holder-dir>/<filename>`` path pair (e.g.
``peer=r0/iter_0000002_0_local.ckpt``) so one rank's copy of one shard can be
corrupted while its clique mirrors stay intact. Disk call indices (``at=``)
count per *file*, not per process — each container is written sequentially by
one thread, so disk schedules reproduce even under racy cross-rank timing.

A fifth channel, **cold**, mirrors the disk channel for the durable cold tier
(``checkpoint/coldtier.py``'s :class:`ObjectStore` backends): same ``write``/
``commit`` ops and fault kinds, but ``peer=`` names the *object key* (e.g.
``peer=s0/iter_0000002/owner_0.ckpt``) and — like disk — call indices count
per key, so one artifact upload can be corrupted while the manifest beside it
lands intact. Uploads stream in fixed-size slices, so ``at=N`` picks the
N-th slice of one object deterministically.

Faults are *planned*, not sprayed: a :class:`ChaosPlan` is parsed from
``$TPU_RESILIENCY_CHAOS`` (``"<seed>:<rule>[;<rule>...]"``) or installed
programmatically, holds a seeded RNG, and decides per channel, per op, by
exact call index (``at=``) or probability (``p=``). Every injection is
recorded as a structured ``chaos_inject`` event (→
``chaos_faults_injected_total{kind,channel}`` via the events→metrics bridge)
and on the plan's ``injected`` list, so a surviving run's injection schedule
is inspectable and — for ``at=`` rules — exactly reproducible from the seed:
the per-``(channel, op)`` call counters are process-local and advance once
per operation regardless of thread interleaving.

Rule grammar (see ``docs/chaos.md`` for the channel × fault coverage matrix)::

    rule    := <channel>.<op>.<kind>[@param[,param...]]
    channel := store | p2p | ipc | disk | cold | *
    op      := connect | accept | send | recv | write | commit | *
    kind    := reset | truncate | eof | delay | stall | partition
             | bitflip | torn-rename | enospc | slow-io
    param   := at=N[+N...] | p=FLOAT | n=N | peer=NAME | delay=S | jitter=S

Examples::

    TPU_RESILIENCY_CHAOS="42:store.send.reset@at=3;p2p.send.truncate@at=1+5"
    TPU_RESILIENCY_CHAOS="7:p2p.connect.partition@peer=2,n=4;ipc.recv.delay@p=0.2,delay=0.05"
    TPU_RESILIENCY_CHAOS="9:disk.write.bitflip@peer=r0/iter_0000002_0_local.ckpt"
    TPU_RESILIENCY_CHAOS="3:disk.commit.torn-rename@at=1;disk.write.enospc@p=0.01"

``n=`` bounds total injections of a rule (defaults: one per ``at=`` index;
unbounded for ``p=`` rules; ``partition`` and the disk-only kinds default to
``p=1.0`` so a peer-scoped rule fires without an explicit schedule). Chaos is
for tests of THIS framework only; with the variable unset every hook is a
no-op returning the socket (or write buffer) unchanged.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import random
import socket
import threading
import time
from typing import Any, Optional, Sequence

from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

CHAOS_ENV = "TPU_RESILIENCY_CHAOS"

CHANNELS = ("store", "p2p", "ipc", "disk", "cold")
OPS = ("connect", "accept", "send", "recv", "write", "commit")
KINDS = (
    "reset", "truncate", "eof", "delay", "stall", "partition",
    "bitflip", "torn-rename", "enospc", "slow-io",
)

#: Kinds a rule may apply at each disk op; hooks skip rules outside these sets
#: (a wildcard ``*.*.reset`` must never "reset" a file write).
DISK_WRITE_KINDS = ("bitflip", "enospc", "slow-io", "delay")
DISK_COMMIT_KINDS = ("truncate", "torn-rename", "slow-io", "delay")
#: Kinds that default to ``p=1.0`` when a rule gives neither ``at=`` nor
#: ``p=`` — they are scoped by ``peer=``/``n=`` instead of a schedule.
_ALWAYS_ON_KINDS = ("partition", "bitflip", "torn-rename", "enospc", "slow-io")


@dataclasses.dataclass
class Rule:
    channel: str
    op: str
    kind: str
    at: Optional[frozenset[int]] = None
    p: Optional[float] = None
    #: remaining injection budget; None = unbounded
    n: Optional[int] = None
    peer: Optional[str] = None
    delay: float = 0.05
    jitter: float = 0.0

    def matches(self, channel: str, op: str, peer: Optional[str]) -> bool:
        if self.channel != "*" and self.channel != channel:
            return False
        if self.op != "*" and self.op != op:
            return False
        if self.peer is not None and peer is not None and self.peer != str(peer):
            return False
        # A peer-scoped rule never fires on an op whose peer is unknown.
        if self.peer is not None and peer is None:
            return False
        return True


def _parse_rule(text: str) -> Rule:
    head, _, params = text.partition("@")
    parts = head.strip().split(".")
    if len(parts) != 3:
        raise ValueError(f"chaos rule {text!r}: expected channel.op.kind")
    channel, op, kind = (p.strip() for p in parts)
    if channel != "*" and channel not in CHANNELS:
        raise ValueError(f"chaos rule {text!r}: unknown channel {channel!r}")
    if op != "*" and op not in OPS:
        raise ValueError(f"chaos rule {text!r}: unknown op {op!r}")
    if kind not in KINDS:
        raise ValueError(f"chaos rule {text!r}: unknown fault kind {kind!r}")
    rule = Rule(channel=channel, op=op, kind=kind)
    for item in filter(None, (s.strip() for s in params.split(","))):
        key, _, val = item.partition("=")
        if key == "at":
            rule.at = frozenset(int(v) for v in val.split("+"))
        elif key == "p":
            rule.p = float(val)
        elif key == "n":
            rule.n = int(val)
        elif key == "peer":
            rule.peer = val
        elif key == "delay":
            rule.delay = float(val)
        elif key == "jitter":
            rule.jitter = float(val)
        else:
            raise ValueError(f"chaos rule {text!r}: unknown param {key!r}")
    if rule.at is None and rule.p is None:
        if rule.kind in _ALWAYS_ON_KINDS:
            rule.p = 1.0  # holds until the n= budget runs out / peer scope ends
        else:
            raise ValueError(f"chaos rule {text!r}: needs at= or p=")
    if rule.n is None and rule.at is not None:
        rule.n = len(rule.at)
    return rule


@dataclasses.dataclass(frozen=True)
class Injection:
    """One executed injection — the reproducible schedule unit."""

    channel: str
    op: str
    kind: str
    index: int
    peer: Optional[str] = None


class ChaosPlan:
    """A parsed, seeded fault plan. ``check()`` is the single decision point
    every hook funnels through; it advances the per-``(channel, op)`` call
    counter exactly once per operation, so ``at=`` schedules are deterministic
    under any thread interleaving, and probabilistic draws come from the one
    seeded RNG."""

    def __init__(self, seed: int, rules: Sequence[Rule], spec: str = ""):
        self.seed = seed
        self.rules = list(rules)
        self.spec = spec
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, str], int] = {}
        self.injected: list[Injection] = []

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        seed_s, sep, rules_s = spec.partition(":")
        if not sep:
            raise ValueError(f"chaos spec {spec!r}: expected '<seed>:<rules>'")
        rules = [_parse_rule(r) for r in filter(None, (s.strip() for s in rules_s.split(";")))]
        return cls(int(seed_s), rules, spec=spec)

    def check(
        self, channel: str, op: str, peer: Optional[str] = None,
        kinds: Optional[Sequence[str]] = None,
    ) -> Optional[Rule]:
        """Advance the ``(channel, op)`` counter; return the rule to apply to
        this operation, or None. At most one rule fires per op (first match in
        spec order wins). ``kinds`` restricts which fault kinds this hook can
        apply (non-matching rules are skipped, their budget untouched)."""
        return self.check_injection(channel, op, peer, kinds)[0]

    def check_injection(
        self, channel: str, op: str, peer: Optional[str] = None,
        kinds: Optional[Sequence[str]] = None,
    ) -> tuple[Optional[Rule], Optional[Injection]]:
        """Like :meth:`check` but also returns the :class:`Injection` record —
        hooks that derive deterministic fault parameters (a bit-flip offset)
        key them off the injection's ``(peer, index)`` identity.

        Counter scope: network channels count per ``(channel, op)`` process-
        wide; the ``disk`` and ``cold`` channels count per ``(channel, op,
        peer)`` — i.e. per target file / object key — because each container
        (or upload) is written sequentially by one thread, which makes
        per-file ``at=`` schedules deterministic where a process-global write
        counter would race across ranks."""
        with self._lock:
            key = (channel, op, peer) if channel in ("disk", "cold") else (channel, op)
            idx = self._counters.get(key, 0)
            self._counters[key] = idx + 1
            for rule in self.rules:
                if rule.n == 0 or not rule.matches(channel, op, peer):
                    continue
                if kinds is not None and rule.kind not in kinds:
                    continue
                hit = False
                if rule.at is not None:
                    hit = idx in rule.at
                elif rule.p is not None:
                    hit = self._rng.random() < rule.p
                if not hit:
                    continue
                if rule.n is not None:
                    rule.n -= 1
                inj = Injection(channel, op, rule.kind, idx, peer)
                self.injected.append(inj)
                self._record(inj)
                return rule, inj
        return None, None

    @staticmethod
    def _record(inj: Injection) -> None:
        log.warning(
            f"chaos: injecting {inj.kind} into {inj.channel}.{inj.op}"
            f"[{inj.index}]" + (f" peer={inj.peer}" if inj.peer else "")
        )
        record_event(
            "chaos", "chaos_inject",
            fault=inj.kind, channel=inj.channel, op=inj.op,
            index=inj.index, peer=inj.peer,
        )

    def schedule(self) -> list[tuple[str, str, str, int]]:
        """The executed injection schedule as sorted ``(channel, op, kind,
        index)`` tuples — the reproducibility artifact two same-seed runs must
        agree on. Sorted, not append-ordered: the schedule is a mapping of
        op-index → fault, and which *thread* reaches its index first is racy
        even though the injection points themselves are not."""
        with self._lock:
            return sorted((i.channel, i.op, i.kind, i.index) for i in self.injected)


# -- process-global plan -----------------------------------------------------

_plan: Optional[ChaosPlan] = None
#: env string the current plan was parsed from; _INSTALLED marks a
#: programmatically installed plan (env is ignored until cleared)
_INSTALLED = object()
_plan_env: Any = None
_plan_lock = threading.Lock()


def active_plan() -> Optional[ChaosPlan]:
    """The installed plan, else the one lazily parsed from ``$TPU_RESILIENCY_CHAOS``
    (re-checked each call so spawned children and late exports take effect)."""
    global _plan, _plan_env
    if _plan_env is _INSTALLED:
        return _plan
    spec = os.environ.get(CHAOS_ENV) or None
    if spec != _plan_env:
        with _plan_lock:
            if spec != _plan_env and _plan_env is not _INSTALLED:
                if spec is None:
                    _plan = None
                else:
                    try:
                        _plan = ChaosPlan.parse(spec)
                        log.warning(f"chaos plan active: {spec!r}")
                    except ValueError as e:
                        log.error(f"ignoring malformed ${CHAOS_ENV}: {e}")
                        _plan = None
                _plan_env = spec
    return _plan


def install_plan(plan: Optional[ChaosPlan]) -> Optional[ChaosPlan]:
    """Install ``plan`` process-wide (tests); pass None to clear (the env var
    becomes authoritative again). Returns the previous plan."""
    global _plan, _plan_env
    with _plan_lock:
        prev = _plan
        _plan = plan
        _plan_env = _INSTALLED if plan is not None else None
    return prev


def clear_plan() -> None:
    install_plan(None)


# -- hook points -------------------------------------------------------------


def _apply_connect(rule: Rule) -> None:
    if rule.kind in ("delay", "stall"):
        time.sleep(rule.delay + rule.jitter * random.random())
        return
    # reset / eof / partition / truncate at connect: the dial fails.
    raise ConnectionRefusedError(
        errno.ECONNREFUSED, f"chaos: injected {rule.kind} on connect"
    )


def check_connect(channel: str, peer: Optional[str] = None) -> None:
    """Call before dialing; raises ``ConnectionRefusedError`` to simulate a
    failed/partitioned dial, or sleeps for a delay fault."""
    plan = active_plan()
    if plan is None:
        return
    rule = plan.check(channel, "connect", peer)
    if rule is not None:
        _apply_connect(rule)


def check_accept(channel: str, peer: Optional[str] = None) -> bool:
    """Call after accepting; True means "close this connection immediately"
    (the peer observes EOF before any frame — EOF-on-accept)."""
    plan = active_plan()
    if plan is None:
        return False
    rule = plan.check(channel, "accept", peer)
    if rule is None:
        return False
    if rule.kind in ("delay", "stall"):
        time.sleep(rule.delay + rule.jitter * random.random())
        return False
    return True  # reset/eof/truncate/partition on accept: drop the conn


# -- disk channel hooks (consumed by checkpoint/format.py's IO shim) ---------


def disk_peer(path: str) -> str:
    """Stable rule-targetable name for a container path: the final
    ``<holder-dir>/<filename>`` pair, with any ``.dirty`` suffix stripped —
    ``/ssd/ckpt/s0/r1/iter_0000002_0_local.ckpt.dirty`` →
    ``r1/iter_0000002_0_local.ckpt``. The holder dir is part of the name so a
    rule can corrupt one rank's copy of a shard without touching its clique
    mirrors (same filename, different holder dir)."""
    if path.endswith(".dirty"):
        path = path[: -len(".dirty")]
    parts = path.replace(os.sep, "/").rstrip("/").split("/")
    return "/".join(parts[-2:])


def _deterministic_rng(plan: ChaosPlan, inj: Injection) -> random.Random:
    """Fault parameters (bit offsets, truncation points) come from an RNG
    seeded by ``(seed, file, injection index)`` — NOT the plan's shared RNG,
    whose draw order is racy across threads. Same seed → same corruption."""
    return random.Random((plan.seed, inj.peer, inj.index))


def _on_storage_write(channel: str, peer: str, path: str, data):
    """Shared body of :func:`on_disk_write` / :func:`on_cold_write` — the two
    channels differ only in how the rule-targetable peer name is derived."""
    plan = active_plan()
    if plan is None:
        return data
    rule, inj = plan.check_injection(
        channel, "write", peer=peer, kinds=DISK_WRITE_KINDS
    )
    if rule is None:
        return data
    if rule.kind == "enospc":
        raise OSError(errno.ENOSPC, f"chaos: injected enospc writing {path}")
    if rule.kind in ("slow-io", "delay"):
        time.sleep(rule.delay + rule.jitter * random.random())
        return data
    # bitflip: corrupt a copy, never the caller's buffer (it may be a live
    # staging-pool view feeding peer sockets that should stay intact).
    view = memoryview(data)
    if view.ndim != 1 or view.itemsize != 1:
        view = view.cast("B")
    out = bytearray(view)
    if out:
        rng = _deterministic_rng(plan, inj)
        pos = rng.randrange(len(out))
        out[pos] ^= 1 << rng.randrange(8)
    return out


def on_disk_write(path: str, data):
    """Chaos hook for one container write call (header prefix, a leaf, the
    trailer, or one striped pwrite range). Returns the buffer to actually put
    on disk — a copy with one deterministically chosen bit flipped under
    ``bitflip`` — sleeps under ``slow-io``/``delay``, raises
    ``OSError(ENOSPC)`` under ``enospc``. Identity when no plan is active."""
    plan = active_plan()
    if plan is None:
        return data
    return _on_storage_write("disk", disk_peer(path), path, data)


def on_disk_commit(tmp: str, path: str):
    """Chaos hook before the ``.dirty``→visible rename. ``torn-rename``
    truncates the temp file before the rename lands (the rename was journaled
    but the data blocks never hit the platter — the visible file is torn);
    ``truncate`` returns a post-rename action that cuts the *visible* file's
    tail (post-commit corruption); ``slow-io``/``delay`` sleep. Returns a
    callable to run after ``os.replace``, or None."""
    plan = active_plan()
    if plan is None:
        return None
    rule, inj = plan.check_injection(
        "disk", "commit", peer=disk_peer(path), kinds=DISK_COMMIT_KINDS
    )
    if rule is None:
        return None
    if rule.kind in ("slow-io", "delay"):
        time.sleep(rule.delay + rule.jitter * random.random())
        return None
    rng = _deterministic_rng(plan, inj)
    if rule.kind == "torn-rename":
        _truncate_tail(tmp, rng)
        return None
    return lambda: _truncate_tail(path, rng)  # post-commit truncate


def on_cold_write(key: str, path: str, data):
    """Chaos hook for one cold-tier upload slice. ``key`` is the object key
    (the rule's ``peer=`` target); ``path`` is the backend's physical temp
    path, only used for error text. Same fault kinds and semantics as
    :func:`on_disk_write`."""
    return _on_storage_write("cold", key, path, data)


def on_cold_commit(tmp: str, key: str, path: str):
    """Chaos hook before a cold-tier upload's tmp→visible rename. Mirrors
    :func:`on_disk_commit`, with rules targeting the object ``key``; the
    returned post-commit action (under ``truncate``) cuts the tail of the
    visible ``path``."""
    plan = active_plan()
    if plan is None:
        return None
    rule, inj = plan.check_injection(
        "cold", "commit", peer=key, kinds=DISK_COMMIT_KINDS
    )
    if rule is None:
        return None
    if rule.kind in ("slow-io", "delay"):
        time.sleep(rule.delay + rule.jitter * random.random())
        return None
    rng = _deterministic_rng(plan, inj)
    if rule.kind == "torn-rename":
        _truncate_tail(tmp, rng)
        return None
    return lambda: _truncate_tail(path, rng)  # post-commit truncate


def _truncate_tail(path: str, rng: random.Random) -> None:
    """Cut a deterministic 1..half-of-file tail off ``path`` (at least one
    byte, so the loss is always detectable)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size <= 1:
        return
    keep = rng.randrange(max(1, size // 2), size)
    with open(path, "r+b") as f:
        f.truncate(keep)


def wrap(sock: socket.socket, channel: str, peer: Optional[str] = None):
    """Wrap a connected socket with fault-injecting send/recv; identity when
    no plan is active (zero overhead on the unchaosed hot path)."""
    plan = active_plan()
    if plan is None:
        return sock
    return ChaosSocket(sock, plan, channel, peer)


class ChaosSocket:
    """Fault-injecting proxy over a connected socket.

    Intercepts the data-plane calls the framing layer uses (``send``,
    ``sendall``, ``sendmsg``, ``recv``, ``recv_into``); everything else —
    ``settimeout``, ``close``, ``fileno``, ... — delegates to the wrapped
    socket. ``os.sendfile`` payloads bypass the wrapper (they ride the raw
    fd); the bulk preamble still goes through ``sendall``, so file sends are
    reset/truncate-injectable at the frame boundary.
    """

    def __init__(self, sock: socket.socket, plan: ChaosPlan, channel: str,
                 peer: Optional[str] = None):
        self._sock = sock
        self._plan = plan
        self._channel = channel
        self._peer = peer

    def __getattr__(self, name: str) -> Any:
        return getattr(self._sock, name)

    def __enter__(self) -> "ChaosSocket":
        return self

    def __exit__(self, *exc) -> None:
        self._sock.close()

    # -- fault application -------------------------------------------------

    def _kill(self, kind: str) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        raise ConnectionResetError(
            errno.ECONNRESET, f"chaos: injected {kind}"
        )

    def _sleep(self, rule: Rule) -> None:
        time.sleep(rule.delay + rule.jitter * random.random())

    def _check_send(self, data) -> Optional[memoryview]:
        """Returns a truncated prefix to really send before dying, or None to
        proceed with the faultless path (after any delay)."""
        rule = self._plan.check(self._channel, "send", self._peer)
        if rule is None:
            return None
        if rule.kind in ("delay", "stall"):
            self._sleep(rule)
            return None
        if rule.kind == "truncate":
            v = memoryview(data).cast("B") if data is not None else memoryview(b"")
            # Deliver a genuine partial frame: at least 1 byte, at most half.
            return v[: max(1, v.nbytes // 2)]
        self._kill(rule.kind)  # reset / eof / partition
        raise AssertionError("unreachable")

    # -- send side ---------------------------------------------------------

    def sendall(self, data, *args) -> None:
        prefix = self._check_send(data)
        if prefix is None:
            return self._sock.sendall(data, *args)
        try:
            self._sock.sendall(prefix)
        except OSError:
            pass
        self._kill("truncate")

    def send(self, data, *args) -> int:
        prefix = self._check_send(data)
        if prefix is None:
            return self._sock.send(data, *args)
        try:
            self._sock.sendall(prefix)
        except OSError:
            pass
        self._kill("truncate")
        raise AssertionError("unreachable")

    def sendmsg(self, buffers, *args):
        bufs = list(buffers)
        first = bufs[0] if bufs else b""
        prefix = self._check_send(first)
        if prefix is None:
            return self._sock.sendmsg(bufs, *args)
        try:
            self._sock.sendall(prefix)
        except OSError:
            pass
        self._kill("truncate")

    # -- recv side ---------------------------------------------------------

    def _check_recv(self) -> Optional[Rule]:
        rule = self._plan.check(self._channel, "recv", self._peer)
        if rule is None:
            return None
        if rule.kind == "reset":
            self._kill("reset")
        if rule.kind in ("truncate", "eof"):
            # Observed from the read side, a truncated frame is a premature
            # close: deliver EOF (framing raises EOFError mid-frame).
            try:
                self._sock.close()
            except OSError:
                pass
            return rule
        self._sleep(rule)  # delay / stall
        return rule if rule.kind == "stall" else None

    def recv(self, bufsize: int, *args) -> bytes:
        rule = self._check_recv()
        if rule is not None and rule.kind in ("truncate", "eof"):
            return b""
        if rule is not None and rule.kind == "stall":
            bufsize = 1  # short read: one byte this call
        return self._sock.recv(bufsize, *args)

    def recv_into(self, buffer, nbytes: int = 0, *args) -> int:
        rule = self._check_recv()
        if rule is not None and rule.kind in ("truncate", "eof"):
            return 0
        if rule is not None and rule.kind == "stall":
            nbytes = 1  # short read: one byte this call
        return self._sock.recv_into(buffer, nbytes, *args)


# -- plan generation ---------------------------------------------------------


def random_spec(
    seed: int,
    channels: Sequence[str] = CHANNELS,
    ops: Sequence[str] = ("send", "connect"),
    kinds: Sequence[str] = ("reset", "truncate", "delay"),
    faults_per_channel: int = 2,
    max_index: int = 12,
) -> str:
    """Generate a randomized-but-seeded ``at=``-only spec string: the soak
    harness's fault plans. Deterministic in ``seed``; every channel receives
    ``faults_per_channel`` faults at early call indices (truncate rules are
    pinned to ``send`` — a connect can't truncate mid-frame)."""
    rng = random.Random(seed)
    rules = []
    for ch in channels:
        picked_kinds = list(kinds[:faults_per_channel]) + [
            rng.choice(kinds) for _ in range(max(0, faults_per_channel - len(kinds)))
        ]
        for kind in picked_kinds[:faults_per_channel]:
            op = "send" if kind == "truncate" else rng.choice(list(ops))
            idx = rng.randrange(1, max_index)
            rules.append(f"{ch}.{op}.{kind}@at={idx}")
    return f"{seed}:" + ";".join(rules)
