"""Device, platform, and mesh/topology introspection.

TPU-native analogue of the reference's device shim (``common/device_utils.py:23-85``:
``get_current_device`` / ``get_current_device_type`` / ``get_local_device_count`` /
``get_distributed_backend`` / ``get_distributed_init_method``) plus the hardware-topology
probing its health checks do via NVML/PCI (``shared_utils/health_check.py:352-465``).
On TPU the probe-able topology is the ICI mesh: per-device chip coordinates and the
host↔chip mapping, read from JAX's device list rather than the PCI tree.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Optional, Sequence

import numpy as np


def platform_kind() -> str:
    """'tpu' | 'gpu' | 'cpu' — the JAX default backend platform."""
    import jax

    plat = jax.default_backend()
    # Experimental TPU transports (e.g. 'axon') still expose TPU devices.
    if plat not in ("cpu", "gpu", "tpu"):
        try:
            kind = jax.devices()[0].device_kind.lower()
            if "tpu" in kind:
                return "tpu"
        except Exception:
            pass
    return plat


def local_device_count() -> int:
    import jax

    return jax.local_device_count()


def global_device_count() -> int:
    import jax

    return jax.device_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def default_device():
    import jax

    return jax.devices()[0]


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    """One accelerator device and where it lives."""

    device_id: int
    process_index: int
    platform: str
    device_kind: str
    coords: Optional[tuple[int, ...]]  # ICI chip coordinates (TPU only)
    core_on_chip: Optional[int]


@dataclasses.dataclass(frozen=True)
class Topology:
    """Snapshot of the device topology visible to this process' JAX runtime."""

    devices: tuple[DeviceInfo, ...]
    num_processes: int

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def devices_on_host(self, proc: int) -> list[DeviceInfo]:
        return [d for d in self.devices if d.process_index == proc]

    def host_of_device(self, device_id: int) -> int:
        for d in self.devices:
            if d.device_id == device_id:
                return d.process_index
        raise KeyError(device_id)

    def hosts(self) -> list[int]:
        return sorted({d.process_index for d in self.devices})


def probe_topology() -> Topology:
    """Read the global device topology from JAX."""
    import jax

    infos = []
    for d in jax.devices():
        coords = getattr(d, "coords", None)
        infos.append(
            DeviceInfo(
                device_id=d.id,
                process_index=d.process_index,
                platform=d.platform,
                device_kind=getattr(d, "device_kind", d.platform),
                coords=tuple(coords) if coords is not None else None,
                core_on_chip=getattr(d, "core_on_chip", None),
            )
        )
    return Topology(devices=tuple(infos), num_processes=jax.process_count())


def make_mesh(axis_shapes: dict[str, int], *, devices: Optional[Sequence[Any]] = None):
    """Build a ``jax.sharding.Mesh`` with named axes.

    ``axis_shapes`` maps axis name → size in declaration order, e.g.
    ``{"dp": 2, "tp": 4}``. Uses ``mesh_utils.create_device_mesh`` for an ICI-friendly
    physical layout when possible (keeps collectives riding ICI rather than DCN), falling
    back to a plain reshape for virtual/CPU device sets.
    """
    import jax
    from jax.sharding import Mesh

    names = tuple(axis_shapes.keys())
    shape = tuple(axis_shapes.values())
    devs = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n != len(devs):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devs)}")
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devs)
    except Exception:
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, names)


def apply_platform_env() -> None:
    """Make ``$JAX_PLATFORMS`` authoritative even under a site-installed plugin.

    Plugin boot code (sitecustomize) may force-select its platform via
    ``jax.config`` at interpreter start, after which the env var alone no longer
    wins. Scripts that honor ``JAX_PLATFORMS=cpu`` (benches, tools) call this
    once before any backend use. Also applies the launcher-exported persistent
    compilation cache (``--compile-cache-dir``) when present, so a restarted
    worker's first step loads the previous round's executables instead of
    re-compiling."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    apply_compile_cache_env()


def apply_compile_cache_env() -> None:
    """Worker-side half of the ``--compile-cache-dir`` plumbing: point JAX's
    persistent compilation cache at the launcher-exported directory (after the
    integrity sweep — corrupt entries cost a cold compile, never a crash).
    One-shot per process; a no-op when the launcher didn't export a dir."""
    from tpu_resiliency.platform import compile_cache

    compile_cache.apply_from_env()


def warm_runtime() -> dict:
    """Platform-safe runtime warmup for parked warm spares (``launcher/park.py``
    ``--warm-spare-warmup runtime``): pre-pay everything a worker's first
    backend use costs that does NOT touch an accelerator device.

    The hard constraint: a parked spare coexists with the round's live workers
    — and, at promotion time, with the *dying* worker whose device lease is
    still held — so device-grabbing stays strictly post-promotion. Three
    warmup levels, each gated:

    - **plugin discovery**: enumerate (and import, which only *registers*)
      PJRT plugin entry points — never initialize them.
    - **tracing machinery**: a backend-free ``jax.eval_shape`` trace warms
      jaxpr/lowering import chains.
    - **CPU/loopback backend pre-init**: only when ``$JAX_PLATFORMS`` pins the
      workload to ``cpu`` (tests, loopback benches, CPU jobs) — then the
      backend the worker will use is the host CPU, which no dying worker can
      hold a lease on, so full init + one dispatched op is safe and removes
      backend-init from the promoted worker's first step.

    Must not mutate ``os.environ`` or ``sys.path`` (promotion parity contract).
    Raises on genuine breakage so the shim dies before writing its ready file
    (startup death), rather than parking a half-warm interpreter.
    """
    import jax
    import jax.numpy as jnp

    info: dict[str, Any] = {"plugins": 0, "traced": False, "cpu_init": False}
    try:
        from importlib import metadata

        eps = metadata.entry_points()
        group = (
            eps.select(group="jax_plugins")
            if hasattr(eps, "select")
            else eps.get("jax_plugins", [])  # pre-3.10 metadata API
        )
        info["plugins"] = len(list(group))
    except Exception:
        pass  # discovery is best-effort; absence of plugins is normal
    jax.eval_shape(
        lambda x: jnp.tanh(x @ x.T).sum(),
        jax.ShapeDtypeStruct((4, 4), jnp.float32),
    )
    info["traced"] = True
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        apply_platform_env()
        jax.block_until_ready(jnp.zeros((8,), jnp.float32) + 1.0)
        info["cpu_init"] = True
    return info


def device_liveness_probe(timeout: float = 30.0, device=None) -> bool:
    """Check the accelerator still executes and completes work.

    Direct analogue of the reference's ``CudaHealthCheck`` double
    ``torch.cuda.synchronize`` under a timeout thread (``inprocess/health_check.py:70-110``):
    submit a tiny computation twice and ``block_until_ready`` with a watchdog thread, so a
    wedged device (hung ICI collective, dead runtime) turns into a ``False`` rather than a
    forever-block. Device RESOLUTION happens inside the guarded worker too: when the
    runtime is dead enough that backend init itself raises (or blocks), the probe's
    answer is still ``False``, never an exception — health paths must keep running
    on a broken host.
    """
    import jax
    import jax.numpy as jnp

    result: dict[str, bool] = {}

    def _work():
        try:
            dev = device if device is not None else default_device()
            for _ in range(2):
                x = jax.device_put(jnp.ones((8,), jnp.float32), dev)
                jax.block_until_ready(x + 1.0)
            result["ok"] = True
        except Exception:
            result["ok"] = False

    t = threading.Thread(target=_work, name="device-probe", daemon=True)
    t.start()
    t.join(timeout)
    return result.get("ok", False)


def visible_device_env() -> dict[str, str]:
    """Environment variables that pin TPU visibility for spawned worker processes."""
    out = {}
    for key in ("TPU_VISIBLE_DEVICES", "TPU_PROCESS_BOUNDS", "JAX_PLATFORMS", "XLA_FLAGS"):
        if key in os.environ:
            out[key] = os.environ[key]
    return out
