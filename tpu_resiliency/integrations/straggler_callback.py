"""Straggler-detection callback: per-step section timing + periodic scored reports.

Analogue of the reference's ``StragglerDetectionCallback``
(``ptl_resiliency/straggler_det_callback.py``): wraps the training step into a
detection section (``:91-98`` via ``Detector.wrap_callables``; here the loop hooks
bracket the step directly), calls ``generate_report_if_interval_elapsed`` each step,
logs best/worst scores, exports per-rank scores into ``ctx.metrics``, and optionally
requests a cooperative stop when stragglers are found (``trainer.should_stop``)."""

from __future__ import annotations

from typing import Optional

from tpu_resiliency.integrations.loop import Callback, LoopContext
from tpu_resiliency.telemetry.detector import Detector
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class StragglerDetectionCallback(Callback):
    def __init__(
        self,
        report_time_interval: float = 300.0,
        calc_relative_scores: bool = True,
        calc_individual_scores: bool = False,
        threshold: float = 0.75,
        stop_if_detected: bool = False,
        export_metrics: bool = True,
        profiling_interval: int = 1,
        section_name: str = "train_step",
        store=None,
        use_pallas: bool = False,
        health_policy=None,
        use_device_mesh: bool = False,
        mesh_signal_capacity: int = 16,
        profile_programs_every: Optional[int] = None,
        profile_ops: bool = False,
    ):
        """``health_policy``: an optional
        :class:`~tpu_resiliency.telemetry.policy.HealthVectorPolicy` fed every
        report — its sinks close the loop to restart demotion / node exclusion /
        replication avoidance (BASELINE target 5).

        ``profile_programs_every``: every Nth step, bracket the step in an XLA
        profiler window and feed per-compiled-program device times into the scored
        matrix as ``prog/...`` signals (the CUPTI capture-every-Nth-entry analogue,
        reference ``profiling_interval``). Tracing is not free — use O(100).

        ``profile_ops``: with ``profile_programs_every``, additionally feed
        per-op/scope device times from the same windows as ``op/...`` signals
        (``jax.named_scope`` paths when XLA carries them) — one granularity
        below programs, the closest XLA analogue of the reference's per-kernel
        CUPTI stream. Parse cost only; no extra tracing overhead. With
        ``use_device_mesh`` the op signals count against
        ``mesh_signal_capacity`` like every other column — size it for
        sec/ + dev/ + prog/ + one op/<scope> per named scope, or the first
        over-capacity report permanently drops the mesh path for the run and
        falls back to the store gather (logged, training never interrupted).

        ``use_device_mesh``: route report rounds through the mesh-sharded scoring
        path (:class:`~tpu_resiliency.telemetry.sharded.MeshTelemetry`) instead of
        the per-rank store gather. Requires one JAX process per rank
        (``jax.process_count() == world_size``, i.e. each worker called
        ``jax.distributed.initialize``); outside that configuration the callback
        logs once and falls back to the store path. ``mesh_signal_capacity`` caps
        the number of distinct timed signals the compiled scorer carries."""
        self.threshold = threshold
        self.stop_if_detected = stop_if_detected
        self.export_metrics = export_metrics
        self.section_name = section_name
        self.health_policy = health_policy
        self.use_device_mesh = use_device_mesh
        self.mesh_signal_capacity = mesh_signal_capacity
        self.profile_programs_every = profile_programs_every
        self.profile_ops = profile_ops
        self._program_profiler = None
        self._step_count = 0
        self._init_kwargs = dict(
            scores_to_compute=(
                (["relative_perf_scores"] if calc_relative_scores else [])
                + (["individual_perf_scores"] if calc_individual_scores else [])
            ),
            report_time_interval=report_time_interval,
            profiling_interval=profiling_interval,
            store=store,
            use_pallas=use_pallas,
        )
        self._section = None
        self.last_report = None

    def _build_mesh_telemetry(self, ctx: LoopContext):
        """One telemetry row per rank on a one-device-per-process mesh — the
        configuration ``Detector._generate_mesh_report`` scores with zero per-rank
        store gathers (summaries travel as shards, reduced by XLA collectives)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from tpu_resiliency.telemetry.sharded import MeshTelemetry

        if ctx.world_size <= 1 or jax.process_count() != ctx.world_size:
            log.info(
                "use_device_mesh requested but job is not one-JAX-process-per-rank "
                f"(process_count={jax.process_count()}, world={ctx.world_size}); "
                "falling back to the store summary path"
            )
            return None
        per_proc = [
            [d for d in jax.devices() if d.process_index == p][0]
            for p in range(ctx.world_size)
        ]
        mesh = Mesh(np.array(per_proc), ("ranks",))
        return MeshTelemetry(
            mesh,
            "ranks",
            n_ranks=ctx.world_size,
            signal_names=tuple(f"c{i}" for i in range(self.mesh_signal_capacity)),
        )

    def on_train_start(self, ctx: LoopContext) -> None:
        device_telemetry = (
            self._build_mesh_telemetry(ctx) if self.use_device_mesh else None
        )
        Detector.initialize(
            rank=ctx.rank,
            world_size=ctx.world_size,
            device_telemetry=device_telemetry,
            **self._init_kwargs,
        )

    def on_step_start(self, ctx: LoopContext) -> None:
        if self.profile_programs_every:
            if self._program_profiler is None:
                from tpu_resiliency.telemetry.device_profiler import DeviceTimeProfiler

                self._program_profiler = DeviceTimeProfiler(
                    collect_ops=self.profile_ops
                )
            if self._step_count % self.profile_programs_every == 0:
                self._program_profiler.start()
        self._section = Detector.detection_section(self.section_name)
        self._section.__enter__()

    def on_step_end(self, ctx: LoopContext) -> None:
        if self._section is not None:
            self._section.__exit__(None, None, None)
            self._section = None
        self._step_count += 1
        if self._program_profiler is not None and self._program_profiler.active:
            self._program_profiler.stop()
            Detector.record_program_samples(self._program_profiler.drain())
            if self.profile_ops:
                Detector.record_op_samples(self._program_profiler.drain_ops())
        report = Detector.generate_report_if_interval_elapsed()
        if report is not None:
            self._handle_report(ctx, report)

    def _close_profiler_window(self) -> None:
        if self._program_profiler is not None and self._program_profiler.active:
            self._program_profiler.stop()

    def on_exception(self, ctx: LoopContext, exc: BaseException) -> None:
        # A step that dies mid-window must not leak the process-global JAX trace:
        # the restarted loop's fresh profiler would find it active and crash.
        self._close_profiler_window()

    def on_train_end(self, ctx: LoopContext) -> None:
        if self._section is not None:
            self._section.__exit__(None, None, None)
            self._section = None
        self._close_profiler_window()
        Detector.shutdown()

    # -- report handling ---------------------------------------------------

    def _handle_report(self, ctx: LoopContext, report) -> None:
        self.last_report = report
        flat = dict(report.perf_scores or {})
        if flat:
            best = max(flat, key=flat.get)
            worst = min(flat, key=flat.get)
            log.info(
                f"straggler report: best rank {best}={flat[best]:.3f} "
                f"worst rank {worst}={flat[worst]:.3f}"
            )
            if self.export_metrics:
                ctx.metrics["straggler/best_score"] = float(flat[best])
                ctx.metrics["straggler/worst_score"] = float(flat[worst])
        stragglers = report.identify_stragglers(
            perf_threshold=self.threshold, section_threshold=self.threshold
        )
        if stragglers.by_perf or stragglers.by_section:
            log.warning(f"stragglers detected: {stragglers}")
            if self.export_metrics:
                ctx.metrics["straggler/detected"] = stragglers
            if self.stop_if_detected:
                ctx.should_stop = True
        # The machine-readable twin of the log lines above, on the same
        # structured JSONL stream the launcher narrates to ($TPU_RESILIENCY_
        # EVENTS_FILE) — the role the reference fills with its torchelastic
        # events/metrics streams + PTL logger export
        # (straggler_det_callback.py enable_ptl_logging, events/ metrics/).
        record_event(
            "telemetry",
            "straggler_report",
            step=ctx.step,
            # String keys: json.dumps would coerce int keys anyway, so use the
            # on-disk schema everywhere — in-process sinks and JSONL readers
            # index the same way.
            perf_scores={str(k): float(v) for k, v in flat.items()},
            stragglers_by_perf=sorted(s.rank for s in stragglers.by_perf),
            stragglers_by_section={
                name: sorted(s.rank for s in ids)
                for name, ids in stragglers.by_section.items()
            },
        )
        if self.health_policy is not None:
            self.health_policy.observe(report)
