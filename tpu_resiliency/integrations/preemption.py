"""Preemption-synchronized checkpointing: turn a preemption notice into one
agreed-upon save point across every rank.

TPU-first capability with no reference analogue: Cloud TPU maintenance events
and spot reclamations deliver SIGTERM with a grace window, and XLA's
coordination service ships a preemption sync manager for exactly this — any
task's SIGTERM is broadcast, and ``reached_preemption_sync_point(step)`` returns
True on EVERY rank at the same step (the max across ranks of the steps at which
they heard the notice). That agreement is what makes the final checkpoint
usable: a per-rank "save on SIGTERM" writes shards from different steps, which
is not a checkpoint.

Requires the job to be initialized through
:func:`tpu_resiliency.platform.distributed.initialize` (the sync manager rides
the coordination client). Measured end-to-end on CPU multi-process in
``tests/integrations/test_preemption.py``: SIGTERM to one rank, both ranks save
the same step and exit cleanly.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from tpu_resiliency.integrations.loop import Callback, LoopContext
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class PreemptionCheckpointCallback(Callback):
    """Poll the coordination service's preemption sync point each step; on the
    agreed step, run ``on_preemption(state, step)`` (typically a blocking save —
    the grace window is short) and cooperatively stop the loop.

    ``stop_on_preemption=False`` keeps training (save-and-continue — useful when
    the scheduler sometimes cancels the reclamation).

    ``grace_steps=N`` defers the drain/save/stop by N steps after the sync
    point first asserts — the **rescind window**. Cloud schedulers withdraw
    maintenance notices routinely; before this knob a rescinded notice still
    forced the full drain path (blocking ``maybe_finalize``, grace-window
    save, loop stop) for a reclamation that never happened. With a grace
    window, a notice that clears before it elapses emits one
    ``preemption_rescinded`` event, cancels the pending deferred drain (it
    simply never runs), and re-arms the callback for a later real notice.
    The default (0) keeps today's act-immediately behavior.

    ``ckpt_manager`` (anything with ``maybe_finalize(blocking=True)`` — a
    :class:`~tpu_resiliency.checkpoint.local_manager.LocalCheckpointManager`,
    an :class:`~tpu_resiliency.checkpoint.async_ckpt.AsyncCheckpointer`, or a
    bare callable) defers acting on a notice that lands DURING an in-flight
    async save: the callback first drains the pending save to its
    commit/rename (and collective finalization), THEN runs ``on_preemption``.
    Without the drain, the grace-window save races the background writer — at
    shrink time the "latest" iteration can be a torn mix of the two, which is
    exactly the checkpoint the resharded resume would pick.

    After the loop stops, tear jax.distributed down coordinator-last before
    process exit — :func:`platform.distributed.shutdown_ordered` (store-backed,
    deterministic) or :func:`shutdown_graceful` (store-free) — or a peer's
    atexit disconnect can race the coordinator's death and terminate that peer
    with a spurious fatal.
    """

    def __init__(
        self,
        on_preemption: Callable[[Any, int], None],
        stop_on_preemption: bool = True,
        ckpt_manager: Any = None,
        grace_steps: int = 0,
    ):
        if grace_steps < 0:
            raise ValueError("grace_steps must be >= 0")
        self.on_preemption = on_preemption
        self.stop_on_preemption = stop_on_preemption
        self.ckpt_manager = ckpt_manager
        self.grace_steps = grace_steps
        self.preempted_at: Optional[int] = None  # last fired sync step
        self.rescinded: int = 0  # notices withdrawn before the grace elapsed
        self._armed = True
        #: step at which the current (armed) notice was first observed; the
        #: drain/save is deferred until ``grace_steps`` later — the window a
        #: rescind can cancel it in
        self._pending_since: Optional[int] = None

    def _drain_inflight_saves(self, step: int) -> None:
        """Block until any in-flight async save has committed (rename done,
        coverage finalized) before the preemption save runs. Failures are
        logged, not raised — a broken background save must not eat the grace
        window the final save needs."""
        mgr = self.ckpt_manager
        if mgr is None:
            return
        t0 = time.monotonic()
        try:
            if callable(getattr(mgr, "maybe_finalize", None)):
                mgr.maybe_finalize(blocking=True)
            elif callable(mgr):
                mgr()
        except Exception:
            log.exception(
                "draining in-flight async save before the preemption save "
                "failed; saving anyway"
            )
            record_event(
                "preemption", "preemption_drain", step=step, ok=False,
                duration_s=time.monotonic() - t0,
            )
            return
        record_event(
            "preemption", "preemption_drain", step=step, ok=True,
            duration_s=time.monotonic() - t0,
        )

    @staticmethod
    def _reached(step: int) -> bool:
        from tpu_resiliency.platform.distributed import client_active

        if not client_active():
            return False  # single-controller job: no coordination service
        from jax.experimental import multihost_utils

        return bool(multihost_utils.reached_preemption_sync_point(step))

    def on_step_end(self, ctx: LoopContext) -> None:
        # Edge-triggered: fire once per notice, re-arm when the sync manager
        # stops reporting the point (save-and-continue jobs must catch a LATER
        # preemption; note upstream's sync manager handles one preemption per
        # process lifetime as of jax 0.9 — a second notice then simply keeps
        # the point asserted and no re-fire happens).
        reached = self._reached(ctx.step)
        if not reached:
            if self._pending_since is not None:
                # The notice cleared inside the grace window: the scheduler
                # withdrew the reclamation. Cancel the pending deferred
                # drain/save (it never runs) and re-arm for a real one.
                self.rescinded += 1
                log.warning(
                    f"preemption notice from step {self._pending_since} "
                    f"rescinded at step {ctx.step}: cancelling the deferred "
                    f"drain/save"
                )
                record_event(
                    "preemption", "preemption_rescinded", step=ctx.step,
                    noticed_step=self._pending_since, rank=ctx.rank,
                )
                self._pending_since = None
            self._armed = True
            return
        if not self._armed:
            return
        if self._pending_since is None:
            self._pending_since = ctx.step
            record_event(
                "preemption", "preemption_sync_point", step=ctx.step,
                rank=ctx.rank,
            )
        if ctx.step - self._pending_since < self.grace_steps:
            return  # rescind window still open: the drain/save stays deferred
        self._armed = False
        self._pending_since = None
        self.preempted_at = ctx.step
        log.warning(
            f"preemption sync point at step {ctx.step}: saving before the grace "
            f"window closes"
        )
        # A notice landing mid-async-save must wait for the commit/rename:
        # otherwise the final save and the background writer interleave and
        # the "latest" iteration at shrink time can be torn.
        self._drain_inflight_saves(ctx.step)
        self.on_preemption(ctx.state, ctx.step)
        if self.stop_on_preemption:
            ctx.should_stop = True
