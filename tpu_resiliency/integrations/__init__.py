"""L5 framework integration: training-loop callbacks (the ``ptl_resiliency`` analogue).

The reference binds resiliency into PyTorch-Lightning; here the seam is a minimal
callback protocol over a JAX train loop (``loop.py``), with the same four callbacks:
FT heartbeats, FT sections, straggler detection, hierarchical checkpointing.
"""

from tpu_resiliency.integrations.checkpoint_callback import HierarchicalCheckpointCallback
from tpu_resiliency.integrations.ft_callbacks import (
    FaultToleranceCallback,
    FaultToleranceSectionsCallback,
)
from tpu_resiliency.integrations.loop import (
    Callback,
    CallbackRunner,
    LoopContext,
    StopTraining,
    run_training,
)
from tpu_resiliency.integrations.straggler_callback import StragglerDetectionCallback

# orbax itself loads lazily, at OrbaxCheckpointCallback construction
from tpu_resiliency.integrations.orbax_adapter import OrbaxCheckpointCallback
from tpu_resiliency.integrations.preemption import PreemptionCheckpointCallback

__all__ = [
    "OrbaxCheckpointCallback",
    "PreemptionCheckpointCallback",
    "Callback",
    "CallbackRunner",
    "LoopContext",
    "StopTraining",
    "run_training",
    "FaultToleranceCallback",
    "FaultToleranceSectionsCallback",
    "StragglerDetectionCallback",
    "HierarchicalCheckpointCallback",
]
