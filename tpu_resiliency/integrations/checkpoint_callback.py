"""Hierarchical checkpointing callback: fast local saves + slower global saves.

Analogue of the reference's ``LocalCheckpointCallback`` + ``HierarchicalCheckpointIO``
(``ptl_resiliency/local_checkpoint_callback.py:93-203``): local (node-disk/ramdisk)
checkpoints every ``local_every`` steps through the replicated
:class:`LocalCheckpointManager`, global checkpoints every ``global_every`` steps
through the :class:`AsyncCheckpointer`, async finalization polled each step, and
``restore_latest`` picking whichever of (local, global) is newest — local first,
since reading the node's own disk beats re-fetching from shared storage.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

from tpu_resiliency.checkpoint.async_ckpt import AsyncCheckpointer
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.integrations.loop import Callback, LoopContext
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class HierarchicalCheckpointCallback(Callback):
    """Drives both checkpoint tiers from loop hooks.

    ``to_state_dict`` / ``from_state_dict``: optional adapters between the user's
    train state and the saved pytree (the reference's abstract
    ``to/from_tensor_aware_state_dict``); identity by default.
    """

    def __init__(
        self,
        local_manager: Optional[LocalCheckpointManager] = None,
        global_dir: Optional[str] = None,
        local_every: int = 0,
        global_every: int = 0,
        to_state_dict: Callable[[Any], Any] = lambda s: s,
        from_state_dict: Callable[[Any, Any], Any] = lambda s, loaded: loaded,
        global_checkpointer: Optional[AsyncCheckpointer] = None,
        rank: Optional[int] = None,
        driven_by_loop: bool = False,
    ):
        if local_every and local_manager is None:
            raise ValueError("local_every set but no local_manager given")
        if global_every and not global_dir:
            raise ValueError("global_every set but no global_dir given")
        self.local_manager = local_manager
        self.global_dir = global_dir
        self.local_every = local_every
        self.global_every = global_every
        self.to_state_dict = to_state_dict
        self.from_state_dict = from_state_dict
        self.global_ckpt = global_checkpointer or (
            AsyncCheckpointer() if global_every else None
        )
        self.rank = rank
        self.driven_by_loop = driven_by_loop

    def rebuild_group(self, comm, remirror: bool = True) -> None:
        """After a restart round changed the active world: adopt the new rank
        group on the local tier (clique rebuild + re-mirror; collective — every
        surviving rank's callback calls this with the new group's comm). See
        :meth:`LocalCheckpointManager.rebuild_group`."""
        if self.local_manager is not None:
            self.local_manager.rebuild_group(comm, remirror=remirror)

    # -- save path ---------------------------------------------------------

    @property
    def cadence(self) -> int:
        """The loop's ``checkpoint_every`` when driving saves via ``save_now``:
        the GCD of the tier cadences (each tier still fires only on its own)."""
        import math

        vals = [v for v in (self.local_every, self.global_every) if v]
        return math.gcd(*vals) if len(vals) > 1 else (vals[0] if vals else 0)

    def save_now(self, state: Any, step_index: int) -> None:
        """Save whichever tiers are due after ``step_index`` (0-based) completed.

        Wire as ``run_training(..., checkpoint_every=cb.cadence,
        checkpoint_fn=cb.save_now, callbacks=[sections_cb, cb])`` so the loop's
        ``on_checkpoint_start/end`` brackets fire and section-timing/heartbeat
        callbacks attribute checkpoint time correctly. The train state is popped
        and device→host-copied ONCE even when both tiers fire on the same step.
        """
        step = step_index + 1  # checkpoints are named by completed steps
        local_due = self.local_every and step % self.local_every == 0
        global_due = self.global_every and step % self.global_every == 0
        if not (local_due or global_due):
            return
        sd = PyTreeStateDict(self.to_state_dict(state))
        sd.pop_tensors()
        if local_due and global_due:
            # Both tiers consume the same payload: one shared blocking D2H
            # beats two independent async snapshots of the same tree.
            sd.copy_tensors_to_host()
        # Single-tier steps hand the device tensors straight to the engine —
        # pipelined savers enqueue their own async D2H, so the loop never
        # blocks on the copy.
        if local_due:
            self.local_manager.save(step, sd, is_async=True)
        if global_due:
            path = os.path.join(self.global_dir, f"step_{step:08d}")
            self.global_ckpt.async_save(sd, path, rank=self.rank)

    def on_step_end(self, ctx: LoopContext) -> None:
        if not self.driven_by_loop:
            # Standalone mode: save from the step hook. (Checkpoint time is then
            # attributed to the step/out-of-section bucket — wire save_now as the
            # loop's checkpoint_fn and pass driven_by_loop=True when running a
            # sections callback, so the on_checkpoint brackets fire instead.)
            self.save_now(ctx.state, ctx.step)
        # Poll async finalization without blocking the step.
        if self.local_manager is not None:
            self.local_manager.maybe_finalize(blocking=False)
        if self.global_ckpt is not None:
            self.global_ckpt.maybe_finalize(blocking=False)

    def on_train_end(self, ctx: LoopContext) -> None:
        if self.local_manager is not None:
            self.local_manager.maybe_finalize(blocking=True)
        if self.global_ckpt is not None:
            self.global_ckpt.finalize_all()

    # -- restore path ------------------------------------------------------

    def latest_global_step(self) -> int:
        if not self.global_dir or not os.path.isdir(self.global_dir):
            return -1
        steps = []
        for name in os.listdir(self.global_dir):
            if name.startswith("step_"):
                # Strip the per-rank suffix (`step_00000008.r0`) before parsing.
                stem = name[len("step_") :].split(".", 1)[0]
                try:
                    steps.append(int(stem))
                except ValueError:
                    continue
        return max(steps, default=-1)

    def restore_latest(self, ctx: LoopContext) -> bool:
        """Load the newest checkpoint across tiers into ``ctx.state`` and set
        ``ctx.start_step``. Returns False if nothing is restorable."""
        local_step = self.local_manager.find_latest() if self.local_manager else -1
        global_step = self.latest_global_step()
        if local_step < 0 and global_step < 0:
            return False
        if local_step >= global_step:
            tree, meta = self.local_manager.load_tree(local_step)
            step = local_step
            source = "local"
        else:
            path = os.path.join(self.global_dir, f"step_{global_step:08d}")
            tree, meta = AsyncCheckpointer.load(path, rank=self.rank)
            step = global_step
            source = "global"
        ctx.state = self.from_state_dict(ctx.state, tree)
        ctx.start_step = step
        log.info(f"restored {source} checkpoint at step {step}")
        return True

    def close(self) -> None:
        if self.local_manager is not None:
            self.local_manager.close()
        if self.global_ckpt is not None:
            self.global_ckpt.close()
