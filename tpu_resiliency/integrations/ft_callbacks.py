"""Fault-tolerance callbacks: heartbeat-based and section-based workload monitoring.

Analogues of the reference's ``FaultToleranceCallback``
(``ptl_resiliency/fault_tolerance_callback.py:233-285`` heartbeats on every hook,
``:43-164`` the training state machine gating timeout recalculation, ``:297`` the
autoresume finished-flag file) and ``FaultToleranceSectionsCallback``
(``fault_tolerance_sections_callback.py:141-179`` — setup/step/checkpointing sections,
out-of-section covering the rest), re-hosted on the JAX loop protocol of ``loop.py``.
"""

from __future__ import annotations

import os
from typing import Optional

from tpu_resiliency.integrations.loop import Callback, LoopContext
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.watchdog.monitor_client import RankMonitorClient

log = get_logger(__name__)

FINISHED_FLAG_ENV = "TPU_FT_FINISHED_FLAG_FILE"


class SimulatedFault(BaseException):
    """Raised by the test-only ``simulated_fault_step`` hook. BaseException so the
    callback runner's "callback failures are never fatal" guard can't swallow it —
    a simulated fault must kill training like a real one (reference
    ``fault_tolerance_callback.py`` simulated-fault hook)."""


class _TrainingStateMachine:
    """Tracks enough loop history to decide (a) when observed heartbeat gaps are
    trustworthy inputs for timeout recalculation — at least two mid-training
    heartbeats and no exception seen — and (b) when training truly finished
    (reference ``_TrainingStateMachine``, ``fault_tolerance_callback.py:43-164``)."""

    def __init__(self):
        self.heartbeats = 0
        self.exception_seen = False
        self.finished = False

    def on_heartbeat(self) -> None:
        self.heartbeats += 1

    def on_exception(self) -> None:
        self.exception_seen = True

    def on_train_end(self, completed_all_steps: bool) -> None:
        self.finished = completed_all_steps and not self.exception_seen

    @property
    def can_update_timeouts(self) -> bool:
        return self.heartbeats >= 2 and not self.exception_seen


class FaultToleranceCallback(Callback):
    """Heartbeat on every step/validation/checkpoint hook; auto-calibrated timeouts
    persisted across restarts; finished-flag file for autoresume schedulers.

    ``state_dict_path``: where calculated timeouts are persisted (the reference keeps
    them in the PTL checkpoint; here a tiny sidecar JSON-ish pickle next to it).
    ``sync_store``: optional coordination store view for cross-rank MAX timeout sync.
    """

    def __init__(
        self,
        autoresume: bool = False,
        finished_flag_path: Optional[str] = None,
        state_dict_path: Optional[str] = None,
        calc_timeouts: bool = True,
        sync_store=None,
        simulated_fault_step: Optional[int] = None,
    ):
        self.client = RankMonitorClient()
        self.machine = _TrainingStateMachine()
        self.autoresume = autoresume
        self.finished_flag_path = finished_flag_path or os.environ.get(FINISHED_FLAG_ENV)
        self.state_dict_path = state_dict_path
        self.calc_timeouts = calc_timeouts
        self.sync_store = sync_store
        self.simulated_fault_step = simulated_fault_step
        self._timeouts_updated = False

    # -- hooks -------------------------------------------------------------

    def on_train_start(self, ctx: LoopContext) -> None:
        if self.autoresume and self.finished_flag_path and os.path.exists(self.finished_flag_path):
            log.info("finished flag present: training already done; stopping")
            ctx.should_stop = True
            return
        if self.state_dict_path and os.path.exists(self.state_dict_path):
            import pickle

            with open(self.state_dict_path, "rb") as f:
                self.client.load_state_dict(pickle.load(f))
        self.client.init_workload_monitoring()

    def _beat(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        self.client.send_heartbeat()
        self.machine.on_heartbeat()
        if (
            self.simulated_fault_step is not None
            and ctx.step == self.simulated_fault_step
        ):
            raise SimulatedFault(f"simulated fault at step {ctx.step}")

    def on_step_end(self, ctx: LoopContext) -> None:
        self._beat(ctx)

    def on_validation_end(self, ctx: LoopContext) -> None:
        self._beat(ctx)

    def on_checkpoint_end(self, ctx: LoopContext) -> None:
        self._beat(ctx)
        self._maybe_update_timeouts(ctx)

    def on_exception(self, ctx: LoopContext, exc: BaseException) -> None:
        self.machine.on_exception()

    def on_train_end(self, ctx: LoopContext) -> None:
        # Only a full run is "finished": a cooperative stop (straggler eviction,
        # preemption) must NOT write the autoresume flag, or the scheduler would
        # abandon the remaining steps.
        completed = ctx.step >= ctx.max_steps
        self.machine.on_train_end(completed)
        if not self._timeouts_updated:
            self._maybe_update_timeouts(ctx)
        if self.machine.finished:
            flag_written = bool(self.autoresume and self.finished_flag_path)
            if flag_written:
                with open(self.finished_flag_path, "w") as f:
                    f.write("finished\n")
            # "finished" is a fact about the run, not about autoresume: emit
            # it whenever the machine says so; the flag path marks whether an
            # autoresume scheduler will also see it on disk.
            record_event(
                "ft", "training_finished",
                step=ctx.step,
                flag_path=self.finished_flag_path if flag_written else None,
            )
        if self.client.is_initialized:
            self.client.shutdown_workload_monitoring()

    # -- timeout persistence ----------------------------------------------

    def _maybe_update_timeouts(self, ctx: LoopContext) -> None:
        if not (self.calc_timeouts and self.machine.can_update_timeouts):
            return
        if not self.client.is_initialized:
            return
        try:
            self.client.calculate_and_set_hb_timeouts(
                store=self.sync_store, rank=ctx.rank, world_size=ctx.world_size
            )
            self._timeouts_updated = True
            hb = self.client.hb_timeouts
            record_event(
                "ft", "timeouts_calculated",
                step=ctx.step, initial_s=hb.initial, subsequent_s=hb.subsequent,
            )
            if self.state_dict_path:
                import pickle

                with open(self.state_dict_path, "wb") as f:
                    pickle.dump(self.client.state_dict(), f)
        except Exception:
            log.exception("timeout recalculation failed")


class FaultToleranceSectionsCallback(Callback):
    """Section-based monitoring: ``setup`` (train start → first step), ``step``
    (around each step), ``checkpointing`` (around checkpoint writes); everything
    else is out-of-section time, each with its own timeout."""

    SETUP = "setup"
    STEP = "step"
    CKPT = "checkpointing"

    def __init__(self, calc_timeouts: bool = True, sync_store=None):
        self.client = RankMonitorClient()
        self.calc_timeouts = calc_timeouts
        self.sync_store = sync_store
        self._setup_open = False
        self.machine = _TrainingStateMachine()

    def on_train_start(self, ctx: LoopContext) -> None:
        self.client.init_workload_monitoring()
        self.client.start_section(self.SETUP)
        self._setup_open = True

    def on_step_start(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        if self._setup_open:
            self.client.end_section(self.SETUP)
            self._setup_open = False
        self.client.start_section(self.STEP)

    def on_step_end(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        self.client.end_section(self.STEP)
        self.machine.on_heartbeat()

    def on_checkpoint_start(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        self.client.start_section(self.CKPT)

    def on_checkpoint_end(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        self.client.end_section(self.CKPT)

    def on_exception(self, ctx: LoopContext, exc: BaseException) -> None:
        self.machine.on_exception()
        if self.client.is_initialized:
            try:
                self.client.end_all_sections()
            except Exception:
                pass

    def on_train_end(self, ctx: LoopContext) -> None:
        if not self.client.is_initialized:
            return
        try:
            if self.calc_timeouts and self.machine.can_update_timeouts:
                self.client.calculate_and_set_section_timeouts(
                    store=self.sync_store, rank=ctx.rank, world_size=ctx.world_size
                )
        except Exception:
            log.exception("section timeout recalculation failed")
        finally:
            try:
                self.client.end_all_sections()
            except Exception:
                pass
            self.client.shutdown_workload_monitoring()
