"""Training-loop callback protocol: the L5 integration seam.

The reference integrates via PyTorch-Lightning hooks (``ptl_resiliency/``); a JAX
train loop has no Trainer object, so the seam here is a minimal callback protocol
plus ``run_training``, a loop driver that owns hook dispatch. Users with their own
loop call the hooks directly — every callback works either way, and all of them are
usable inside an ``inprocess.Wrapper``-wrapped train fn (layered restart).

Hook order per step: ``on_step_start`` → user step fn → ``on_step_end``. Checkpoint
and validation phases are bracketed so section-timing callbacks can attribute time
correctly (the reference's three sections: setup/step/checkpointing,
``fault_tolerance_sections_callback.py:141-179``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Callback:
    """Base class: override any subset of hooks. All hooks are no-ops by default."""

    def on_train_start(self, ctx: "LoopContext") -> None: ...

    def on_step_start(self, ctx: "LoopContext") -> None: ...

    def on_step_end(self, ctx: "LoopContext") -> None: ...

    def on_validation_start(self, ctx: "LoopContext") -> None: ...

    def on_validation_end(self, ctx: "LoopContext") -> None: ...

    def on_checkpoint_start(self, ctx: "LoopContext") -> None: ...

    def on_checkpoint_end(self, ctx: "LoopContext") -> None: ...

    def on_exception(self, ctx: "LoopContext", exc: BaseException) -> None: ...

    def on_train_end(self, ctx: "LoopContext") -> None: ...


@dataclasses.dataclass
class LoopContext:
    """What callbacks can see/alter. ``should_stop`` mirrors the reference's
    ``trainer.should_stop`` cooperative-stop contract."""

    step: int = 0
    max_steps: int = 0
    rank: int = 0
    world_size: int = 1
    should_stop: bool = False
    state: Any = None  # user train state (params/opt state pytree)
    metrics: dict = dataclasses.field(default_factory=dict)
    start_step: int = 0


class CallbackRunner:
    """Dispatches a hook across callbacks; a callback failure is logged, never
    fatal to training (reference callbacks guard the same way)."""

    def __init__(self, callbacks: Iterable[Callback]):
        self.callbacks = list(callbacks)

    def fire(self, hook: str, ctx: LoopContext, *args) -> None:
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(ctx, *args)
            except StopTraining:
                ctx.should_stop = True
            except Exception:
                log.exception(f"callback {type(cb).__name__}.{hook} failed")


class StopTraining(Exception):
    """A callback may raise this from any hook to request a cooperative stop."""


def run_training(
    step_fn: Callable[[Any, int], Any],
    state: Any,
    num_steps: int,
    callbacks: Iterable[Callback] = (),
    ctx: Optional[LoopContext] = None,
    checkpoint_every: Optional[int] = None,
    checkpoint_fn: Optional[Callable[[Any, int], None]] = None,
    validate_every: Optional[int] = None,
    validate_fn: Optional[Callable[[Any, int], dict]] = None,
) -> LoopContext:
    """Drive ``state = step_fn(state, step)`` for ``num_steps`` with hook dispatch.

    Returns the final context (``ctx.state`` is the final train state). Exceptions
    propagate after ``on_exception`` — the inprocess/in-job restart layers above
    decide what a fault means; the loop doesn't swallow it.
    """
    runner = CallbackRunner(callbacks)
    ctx = ctx or LoopContext()
    ctx.state = state
    ctx.max_steps = num_steps
    step = ctx.start_step
    runner.fire("on_train_start", ctx)
    try:
        while step < num_steps and not ctx.should_stop:
            ctx.step = step
            runner.fire("on_step_start", ctx)
            ctx.state = step_fn(ctx.state, step)
            runner.fire("on_step_end", ctx)
            if validate_fn is not None and validate_every and (step + 1) % validate_every == 0:
                runner.fire("on_validation_start", ctx)
                metrics = validate_fn(ctx.state, step) or {}
                ctx.metrics.update(metrics)
                runner.fire("on_validation_end", ctx)
            if checkpoint_fn is not None and checkpoint_every and (step + 1) % checkpoint_every == 0:
                runner.fire("on_checkpoint_start", ctx)
                checkpoint_fn(ctx.state, step)
                runner.fire("on_checkpoint_end", ctx)
            step += 1
        ctx.step = step
        return ctx
    except BaseException as e:
        runner.fire("on_exception", ctx, e)
        raise
    finally:
        runner.fire("on_train_end", ctx)
