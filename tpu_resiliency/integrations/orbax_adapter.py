"""Orbax-backed checkpoint callback: the loop protocol driving a checkpointer
this framework did NOT write.

Proof that the callback seam is a real integration surface rather than
self-referential plumbing (the reference's L5 hooks into a third-party trainer
the same way: ``ptl_resiliency/local_checkpoint_callback.py:101-203`` plugs its
checkpointing into PyTorch Lightning's callback protocol). Here the roles
flip — our :class:`~tpu_resiliency.integrations.loop.Callback` hooks drive
`orbax.checkpoint.CheckpointManager`, the ecosystem-standard global-tier
checkpointer for JAX — and the two tiers compose: Orbax as the durable global
tier, :class:`HierarchicalCheckpointCallback`'s LocalCheckpointManager as the
fast local tier, both on the same loop.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

from tpu_resiliency.integrations.loop import Callback, LoopContext
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class OrbaxCheckpointCallback(Callback):
    """Save the loop's train state through an ``orbax`` ``CheckpointManager``.

    ``to_state_dict`` / ``from_state_dict`` adapt between the loop's train state
    and the saved pytree (identity by default — same adapter contract as
    :class:`HierarchicalCheckpointCallback`). Saves are asynchronous (orbax's
    own async machinery); ``on_train_end`` waits them out.
    """

    def __init__(
        self,
        directory: str,
        every: int,
        max_to_keep: int = 2,
        to_state_dict: Callable[[Any], Any] = lambda s: s,
        from_state_dict: Callable[[Any, Any], Any] = lambda s, loaded: loaded,
        manager: Optional[Any] = None,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.every = every
        self.to_state_dict = to_state_dict
        self.from_state_dict = from_state_dict
        # With an injected manager, directory/max_to_keep are ignored and the
        # caller keeps ownership (close() won't close what it didn't create).
        self._owns_manager = manager is None
        self.manager = manager or ocp.CheckpointManager(
            os.path.abspath(directory),  # orbax requires absolute paths
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )

    # -- loop hooks --------------------------------------------------------

    def on_step_end(self, ctx: LoopContext) -> None:
        if self.every and (ctx.step + 1) % self.every == 0:
            self.manager.save(
                ctx.step,
                args=self._ocp.args.StandardSave(self.to_state_dict(ctx.state)),
            )
            log.info(f"orbax save scheduled @ step {ctx.step}")

    def on_train_end(self, ctx: LoopContext) -> None:
        self.manager.wait_until_finished()

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> int:
        """Newest saved step, or -1."""
        step = self.manager.latest_step()
        return -1 if step is None else int(step)

    def restore_latest(self, ctx: LoopContext) -> bool:
        """Restore the newest checkpoint into ``ctx.state`` (current state used
        as the abstract target, so shardings/dtypes are preserved) and advance
        ``ctx.start_step``. Returns False when nothing is saved yet."""
        step = self.manager.latest_step()
        if step is None:
            return False
        target = self.to_state_dict(ctx.state)
        restored = self.manager.restore(
            step, args=self._ocp.args.StandardRestore(target)
        )
        ctx.state = self.from_state_dict(ctx.state, restored)
        ctx.start_step = int(step) + 1
        log.info(f"orbax restored step {step}; resuming at {ctx.start_step}")
        return True

    def close(self) -> None:
        self.manager.wait_until_finished()
        if self._owns_manager:
            self.manager.close()
