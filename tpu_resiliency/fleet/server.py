"""Fleet HTTP endpoints: the merged view, served.

The fleet twin of the launcher's :class:`~tpu_resiliency.launcher.telemetry.
TelemetryServer` — same stdlib ``ThreadingHTTPServer`` + port-file handshake
discipline, one level up:

- ``GET /fleet/metrics`` — merged Prometheus exposition: every job's series
  under a ``job=`` label, ``fleet:*`` cross-job totals, fleetd's own
  operational metrics.
- ``GET /fleet/goodput`` — the per-job scoreboard (``tpu-fleet-goodput-1``).
- ``GET /fleet/slo`` — jobs ranked worst-first by time-in-restart share with
  detect/recover percentiles (``tpu-fleet-slo-1``).
- ``GET /fleet/incidents`` — the cross-job incident feed
  (``tpu-fleet-incidents-1``).
- ``GET /fleet/hangz`` — the fleet-wide hang census (``tpu-fleet-hangz-1``).
- ``GET /fleet/snapshot`` — the whole fold as one document
  (``tpu-fleet-snapshot-1``; what ``tpu-fleet`` renders offline).
- ``GET /healthz`` — fleetd's own liveness (job count, last scrape age).

Scrapes are TTL-cached behind a lock (``scrape_ttl``): a dashboard storm
hitting five endpoints costs ONE fan-out per TTL, not five — the same
compute-inside-the-lock discipline as the launcher's ``/healthz`` cache. A
failed scrape degrades the served documents (``error`` field), never the
endpoints: every ``/fleet/*`` path answers 200 for as long as fleetd lives,
because the moment something is wrong fleet-wide is exactly when the fleet
view must stay up.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_resiliency.fleet.aggregator import FleetAggregator, FleetView
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

#: default name of the port-file handshake (mirrors telemetry.port)
PORT_FILE_NAME = "fleetd.port"


class FleetServer:
    """Threaded HTTP endpoint over a :class:`FleetAggregator`."""

    def __init__(
        self,
        aggregator: FleetAggregator,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        port_file: Optional[str] = None,
        scrape_ttl: float = 2.0,
    ):
        self.aggregator = aggregator
        self._host = host
        self._want_port = port
        self.port_file = port_file
        #: scrape-result cache lifetime: endpoint storms collapse to one
        #: fan-out per TTL. 0 disables caching (scrapes still serialize).
        self.scrape_ttl = scrape_ttl
        self._view_lock = threading.Lock()
        self._view: Optional[tuple[float, FleetView]] = None
        self._last_error: Optional[str] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_at = time.time()

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive, same as the TelemetryServer: dashboards polling the
            # fleet view reuse one connection per poller.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # no stderr chatter
                log.debug(f"fleetd: {fmt % args}")

            def do_GET(self):
                try:
                    server._handle(self)
                except BrokenPipeError:
                    pass
                except Exception:
                    log.debug("fleetd request failed", exc_info=True)
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleetd-http", daemon=True
        )
        self._thread.start()
        port = self._httpd.server_address[1]
        if self.port_file:
            d = os.path.dirname(self.port_file)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{self.port_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"{port}\n")
            os.replace(tmp, self.port_file)
        log.info(
            f"fleet endpoint on http://{self._host}:{port} "
            f"(/fleet/metrics /fleet/goodput /fleet/slo /fleet/incidents "
            f"/fleet/hangz /fleet/alerts /fleet/snapshot /healthz)"
        )
        return port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.port_file:
            try:
                os.unlink(self.port_file)
            except OSError:
                pass

    # -- view cache ---------------------------------------------------------

    def view(self, max_age: Optional[float] = None) -> Optional[FleetView]:
        """The current fleet view, re-scraped at most once per TTL.
        Compute-inside-the-lock on purpose: concurrent requests during a slow
        fan-out serialize, and the laggards reuse the fresh result. A scrape
        that raises (fleet dir unlinked, interpreter teardown) keeps the last
        good view and records the error for /healthz."""
        ttl = self.scrape_ttl if max_age is None else max_age
        with self._view_lock:
            now = time.monotonic()
            if self._view is not None and now - self._view[0] < ttl:
                return self._view[1]
            try:
                view = self.aggregator.scrape()
                self._last_error = None
            except Exception as e:
                log.warning(f"fleet scrape failed: {e!r}")
                self._last_error = repr(e)
                return self._view[1] if self._view is not None else None
            self._view = (time.monotonic(), view)
            return view

    # -- request handling ---------------------------------------------------

    def _doc_or_degraded(self, build, schema: str) -> dict:
        view = self.view()
        if view is None:
            return {"schema": schema, "error": self._last_error or "no scrape yet"}
        try:
            return build(view)
        except Exception as e:  # a malformed job doc must not down the endpoint
            log.debug("fleet document build failed", exc_info=True)
            return {"schema": schema, "error": repr(e)}

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        from tpu_resiliency.fleet import aggregator as agg_mod

        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/fleet/metrics":
            view = self.view()
            body = (view.to_prometheus() if view is not None else "").encode()
            self._respond(req, 200, body, "text/plain; version=0.0.4")
        elif path == "/fleet/goodput":
            doc = self._doc_or_degraded(
                lambda v: v.goodput_doc(), agg_mod.GOODPUT_SCHEMA
            )
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/fleet/slo":
            doc = self._doc_or_degraded(lambda v: v.slo_doc(), agg_mod.SLO_SCHEMA)
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/fleet/incidents":
            doc = self._doc_or_degraded(
                lambda v: v.incidents_doc(), agg_mod.INCIDENTS_SCHEMA
            )
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/fleet/hangz":
            doc = self._doc_or_degraded(
                lambda v: v.hangz_doc(), agg_mod.HANGZ_SCHEMA
            )
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/fleet/alerts":
            doc = self._doc_or_degraded(
                lambda v: v.alerts_doc(), agg_mod.ALERTS_SCHEMA
            )
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/fleet/snapshot":
            doc = self._doc_or_degraded(
                lambda v: v.snapshot_doc(), agg_mod.SNAPSHOT_SCHEMA
            )
            self._respond(req, 200, _json_body(doc), "application/json")
        elif path == "/healthz":
            doc = self.health()
            status = 200 if doc.get("healthy") else 503
            self._respond(req, status, _json_body(doc), "application/json")
        else:
            self._respond(
                req, 404,
                _json_body({
                    "error": f"unknown path {path!r}",
                    "endpoints": [
                        "/fleet/metrics", "/fleet/goodput", "/fleet/slo",
                        "/fleet/incidents", "/fleet/hangz", "/fleet/alerts",
                        "/fleet/snapshot", "/healthz",
                    ],
                }),
                "application/json",
            )

    def health(self) -> dict:
        """fleetd's own liveness: healthy as long as the last scrape worked
        (an empty fleet is a healthy fleet — zero jobs is a valid answer)."""
        with self._view_lock:
            cached = self._view
            err = self._last_error
        doc = {
            "healthy": err is None,
            "fleet_dir": self.aggregator.fleet_dir,
            "uptime_s": round(time.time() - self._started_at, 3),
        }
        if cached is not None:
            view = cached[1]
            doc.update(
                jobs=len(view.states),
                unreachable=sum(1 for s in view.states if not s["reachable"]),
                last_scrape_age_s=round(time.monotonic() - cached[0], 3),
                last_scrape_s=view.scrape_s,
            )
        if err is not None:
            doc["error"] = err
        return doc

    def write_snapshot(self, path: str) -> None:
        """Persist the current fold atomically (the ``tpu-fleet`` input)."""
        view = self.view()
        if view is None:
            return
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(view.snapshot_doc(), f, indent=2, default=repr)
            f.write("\n")
        os.replace(tmp, path)

    @staticmethod
    def _respond(
        req: BaseHTTPRequestHandler, status: int, body: bytes, ctype: str
    ) -> None:
        req.send_response(status)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)


def _json_body(doc: dict) -> bytes:
    return (json.dumps(doc, indent=2, default=repr) + "\n").encode()
