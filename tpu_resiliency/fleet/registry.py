"""Fleet discovery: job leases in a shared directory.

A launcher with ``--fleet-dir`` set registers its telemetry endpoint by
writing one small JSON lease file (``schema: tpu-fleet-lease-1``) into the
shared directory and refreshing its ``heartbeat_ts`` on a short interval
(``launcher/telemetry.py``). ``fleetd`` discovers jobs by listing the
directory — no central registration RPC, no fleetd restart when jobs come and
go, and the directory can be any shared filesystem the fleet already has
(NFS, GCS fuse, a host path for single-machine fleets).

Failure semantics are lease semantics:

- **atomic**: every write is tmp + ``os.replace`` — a reader never sees a
  torn document; a partially-written or non-JSON file (a foreign tool's
  droppings, a crashed writer's tmp file) is skipped, never fatal.
- **heartbeat-expired**: a job that stops refreshing (crash, SIGKILL, wedged
  launcher) goes stale after ``ttl`` seconds. :func:`live_leases` drops stale
  leases from the view; :func:`expire_stale` (called by fleetd's scrape loop)
  unlinks them so the directory self-cleans without the job's cooperation.
- **newest-wins identity**: the job key is the lease's ``job`` field (the
  launcher's ``--rdzv-id``). A restarted launcher re-registers under the same
  job with a new pid/lease file; :func:`live_leases` keeps only the freshest
  heartbeat per job, so churn never yields duplicate scoreboard rows.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

SCHEMA = "tpu-fleet-lease-1"

#: lease filenames: ``job-<job>-<pid>.json`` — pid-suffixed so two launcher
#: incarnations of one job never clobber each other's writes mid-handoff
LEASE_PREFIX = "job-"
LEASE_SUFFIX = ".json"

#: default staleness horizon: a lease whose heartbeat is older than this is a
#: dead job (the TelemetryServer refreshes every ~5 s, so 3 missed beats)
DEFAULT_TTL_S = 15.0


@dataclasses.dataclass
class JobLease:
    """One job's registration: who it is and where its telemetry lives."""

    job: str
    url: str
    pid: int = 0
    node_id: str = ""
    rdzv_id: str = ""
    started_at: float = 0.0
    heartbeat_ts: float = 0.0
    #: where the lease was read from (empty for a lease built in memory)
    path: str = ""

    def to_doc(self) -> dict:
        return {
            "schema": SCHEMA,
            "job": self.job,
            "url": self.url,
            "pid": self.pid,
            "node_id": self.node_id,
            "rdzv_id": self.rdzv_id or self.job,
            "started_at": self.started_at,
            "heartbeat_ts": self.heartbeat_ts,
        }

    @classmethod
    def from_doc(cls, doc: dict, path: str = "") -> Optional["JobLease"]:
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            return None
        job, url = doc.get("job"), doc.get("url")
        if not isinstance(job, str) or not job or not isinstance(url, str):
            return None
        hb = doc.get("heartbeat_ts")
        return cls(
            job=job,
            url=url.rstrip("/"),
            pid=doc.get("pid") if isinstance(doc.get("pid"), int) else 0,
            node_id=str(doc.get("node_id") or ""),
            rdzv_id=str(doc.get("rdzv_id") or job),
            started_at=(
                doc["started_at"]
                if isinstance(doc.get("started_at"), (int, float)) else 0.0
            ),
            heartbeat_ts=hb if isinstance(hb, (int, float)) else 0.0,
            path=path,
        )


def lease_path(fleet_dir: str, job: str, pid: int) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in job)
    return os.path.join(fleet_dir, f"{LEASE_PREFIX}{safe}-{pid}{LEASE_SUFFIX}")


def write_lease(fleet_dir: str, lease: JobLease) -> str:
    """Atomically write/refresh a lease (stamping ``heartbeat_ts`` now).
    Returns the lease file path."""
    os.makedirs(fleet_dir, exist_ok=True)
    lease.heartbeat_ts = time.time()
    path = lease.path or lease_path(fleet_dir, lease.job, lease.pid)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(lease.to_doc(), f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    lease.path = path
    return path


def remove_lease(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def read_leases(fleet_dir: str) -> list[JobLease]:
    """Every parseable lease in the directory (stale included). Torn/partial
    JSON, foreign files, and in-flight ``.tmp.`` writes are skipped — the
    write side is atomic, so a bad file is garbage, not a race to retry."""
    out: list[JobLease] = []
    try:
        names = sorted(os.listdir(fleet_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(LEASE_PREFIX) and name.endswith(LEASE_SUFFIX)):
            continue
        path = os.path.join(fleet_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        lease = JobLease.from_doc(doc, path=path)
        if lease is not None:
            out.append(lease)
    return out


def live_leases(
    fleet_dir: str, ttl: float = DEFAULT_TTL_S, now: Optional[float] = None
) -> dict[str, JobLease]:
    """``job -> freshest live lease``: stale heartbeats dropped, and when one
    job left several incarnations' files behind (restart churn), only the
    newest heartbeat represents it — one scoreboard row per job, always."""
    now = time.time() if now is None else now
    live: dict[str, JobLease] = {}
    for lease in read_leases(fleet_dir):
        if now - lease.heartbeat_ts > ttl:
            continue
        prev = live.get(lease.job)
        if prev is None or lease.heartbeat_ts > prev.heartbeat_ts:
            live[lease.job] = lease
    return live


def expire_stale(
    fleet_dir: str, ttl: float = DEFAULT_TTL_S, now: Optional[float] = None
) -> list[str]:
    """Unlink leases whose heartbeat is older than ``ttl``; returns the
    removed paths. fleetd calls this each scrape so dead jobs disappear from
    the directory without anyone restarting anything."""
    now = time.time() if now is None else now
    removed: list[str] = []
    for lease in read_leases(fleet_dir):
        if now - lease.heartbeat_ts > ttl:
            remove_lease(lease.path)
            removed.append(lease.path)
            log.info(f"expired stale fleet lease {lease.path} (job {lease.job!r})")
    return removed
