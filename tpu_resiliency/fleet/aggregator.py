"""Fleet aggregation: parallel scrape fan-out + tree-merge into fleet documents.

One :meth:`FleetAggregator.scrape` is one control-plane heartbeat: read the
live leases (:mod:`tpu_resiliency.fleet.registry`), fan out ONE bounded-timeout
HTTP GET per job (the launcher's consolidated ``/snapshot`` document — metrics
snapshot, goodput summary, health, hang census, incident feed in a single
round trip), and fold the per-job answers into the fleet view:

- **metrics** — every reachable job's snapshot merged under a ``job=`` label
  (``MetricsRegistry.merge(extra_labels=...)``), so two jobs'
  ``tpu_restarts_total`` stay distinct series; the same snapshots are also
  folded *unlabelled* into an explicit fleet-total view re-exposed as
  ``fleet:<name>`` families (the recording-rule namespace: ``fleet:``-prefixed
  series are cross-job sums by construction). fleetd's own operational
  metrics (``tpu_fleet_jobs``, ``tpu_fleet_scrape_seconds``,
  ``tpu_fleet_scrape_errors_total{job}``) ride the same registry.
- **goodput scoreboard** (``tpu-fleet-goodput-1``) — per-job rows ranked by
  goodput ratio, plus a fleet aggregate (train-seconds-weighted ratio).
- **SLO ranking** (``tpu-fleet-slo-1``) — jobs ranked worst-first by
  time-in-restart share, with time-to-detect / time-to-recover percentiles
  interpolated from the merged histogram buckets (:func:`bucket_quantile` —
  merged snapshots transport buckets, not quantile reservoirs).
- **incident feed** (``tpu-fleet-incidents-1``) and **hang census**
  (``tpu-fleet-hangz-1``) — cross-job, each entry stamped with its job.

Failure containment is per job by design: a crashed, hung, or mid-restart job
costs one timed-out GET and a ``status: unreachable`` row (+
``fleet_job_unreachable`` event); it never degrades a fleet endpoint and
never blocks the other jobs' scrapes (parallel fan-out — the wall clock of a
scrape is the slowest single job, not the sum, which is what keeps scrape
cost sub-linear in job count; ``scripts/bench_fleet.py`` gates it).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from tpu_resiliency.fleet.registry import DEFAULT_TTL_S, expire_stale, live_leases
from tpu_resiliency.utils import events as events_mod
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.metrics import MetricsRegistry, observe_record

log = get_logger(__name__)

GOODPUT_SCHEMA = "tpu-fleet-goodput-1"
SLO_SCHEMA = "tpu-fleet-slo-1"
INCIDENTS_SCHEMA = "tpu-fleet-incidents-1"
HANGZ_SCHEMA = "tpu-fleet-hangz-1"
ALERTS_SCHEMA = "tpu-fleet-alerts-1"
SNAPSHOT_SCHEMA = "tpu-fleet-snapshot-1"

#: cross-job alert sort: most urgent severity first (watchtower grades)
_SEVERITY_RANK = {"page": 0, "warn": 1, "info": 2}

#: family-name prefix of the explicit fleet-total series (Prometheus reserves
#: the ``:`` namespace for aggregated/recorded series — which these are)
FLEET_TOTAL_PREFIX = "fleet:"

#: fan-out breadth cap: enough to keep a hundreds-of-jobs scrape near
#: slowest-single-job wall clock without unbounded thread growth
MAX_FANOUT = 32


def bucket_quantile(bounds, counts, q: float) -> Optional[float]:
    """Nearest-rank quantile linearly interpolated inside Prometheus-style
    cumulative buckets (``counts`` has the +Inf tail, ``len(bounds) + 1``).

    Merged snapshots carry exact bucket counts but no sample reservoirs, so
    this is the fleet's only quantile path — same estimate
    ``histogram_quantile`` would give a real Prometheus. Returns None on an
    empty histogram; the +Inf bucket answers with the highest finite bound
    (quantiles beyond instrumented range are clamped, not invented)."""
    if not bounds or len(counts) != len(bounds) + 1:
        return None
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, n in enumerate(counts):
        if n <= 0:
            continue
        if cum + n >= target:
            if i >= len(bounds):  # +Inf tail
                return float(bounds[-1])
            hi = float(bounds[i])
            lo = float(bounds[i - 1]) if i > 0 else min(0.0, hi)
            return lo + (hi - lo) * max(0.0, min(1.0, (target - cum) / n))
        cum += n
    return float(bounds[-1])


def _hist_stats(metrics: dict, family: str) -> dict:
    """count / p50 / p95 of one histogram family from a snapshot's ``metrics``
    dict, entries bucket-summed across label sets (matching-bounds only)."""
    bounds: Optional[tuple] = None
    counts: list = []
    total = 0
    for e in metrics.get(family) or []:
        if not isinstance(e, dict) or e.get("type") != "histogram":
            continue
        b = e.get("buckets") or {}
        eb, ec = tuple(b.get("bounds") or ()), list(b.get("counts") or [])
        if not eb or len(ec) != len(eb) + 1:
            continue
        if bounds is None:
            bounds, counts = eb, [0] * len(ec)
        elif eb != bounds:
            continue
        for i, n in enumerate(ec):
            counts[i] += int(n or 0)
        total += int(e.get("count") or 0)
    if bounds is None or total == 0:
        return {"count": 0, "p50": None, "p95": None}
    return {
        "count": total,
        "p50": bucket_quantile(bounds, counts, 0.50),
        "p95": bucket_quantile(bounds, counts, 0.95),
    }


def _counter_total(metrics: dict, family: str) -> float:
    return sum(
        e.get("value") or 0.0
        for e in (metrics.get(family) or [])
        if isinstance(e, dict) and e.get("type") == "counter"
        and isinstance(e.get("value"), (int, float))
    )


class FleetAggregator:
    """Stateless-per-scrape fold of N jobs' telemetry into one fleet view.

    ``registry`` holds fleetd's OWN operational metrics across scrapes (gauge
    of live jobs, scrape-latency histogram, per-job error counters); the
    per-job merged registry is rebuilt fresh each scrape so departed jobs'
    series age out with their leases instead of lingering forever.
    """

    def __init__(
        self,
        fleet_dir: str,
        *,
        lease_ttl: float = DEFAULT_TTL_S,
        timeout: float = 2.0,
        expire: bool = True,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.fleet_dir = fleet_dir
        self.lease_ttl = lease_ttl
        self.timeout = timeout
        self.expire = expire
        self.registry = registry if registry is not None else MetricsRegistry()
        # Scrape-cost flatness machinery: a persistent fan-out pool (thread
        # creation is a per-job linear cost otherwise) and one keep-alive
        # HTTP/1.1 connection per job (TCP handshake + server-side handler
        # thread spawn are per-request linear costs otherwise). Scrapes are
        # serialized — concurrent callers would race the connections.
        self._pool: Optional[ThreadPoolExecutor] = None
        self._conns: dict[str, http.client.HTTPConnection] = {}
        self._scrape_lock = threading.Lock()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:
                pass
        self._conns.clear()

    # -- scrape fan-out ------------------------------------------------------

    def _ensure_pool(self, njobs: int) -> ThreadPoolExecutor:
        want = min(MAX_FANOUT, max(4, njobs))
        if self._pool is None or self._pool._max_workers < want:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            self._pool = ThreadPoolExecutor(
                max_workers=want, thread_name_prefix="fleet-scrape"
            )
        return self._pool

    def _fetch_snapshot(self, url: str) -> dict:
        parsed = urllib.parse.urlsplit(url)
        # Up to two attempts, but only when the first used a kept-alive
        # connection the job has since closed (restart, idle teardown): that
        # one is re-dialed fresh. A job that is actually down fails its
        # fresh connect once — never a doubled timeout.
        for _ in (0, 1):
            conn = self._conns.pop(url, None)
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    parsed.hostname, parsed.port, timeout=self.timeout
                )
            try:
                conn.request("GET", "/snapshot")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"/snapshot answered {resp.status}")
                if not resp.will_close:
                    self._conns[url] = conn  # keep alive for the next scrape
                else:
                    conn.close()
                doc = json.loads(body)
                if not isinstance(doc, dict):
                    raise ValueError("job snapshot is not a JSON object")
                return doc
            except Exception:
                try:
                    conn.close()
                except Exception:
                    pass
                if fresh:
                    raise
        raise AssertionError("unreachable")

    def _scrape_job(self, lease) -> dict:
        t0 = time.monotonic()
        state = {
            "job": lease.job,
            "url": lease.url,
            "node_id": lease.node_id,
            "pid": lease.pid,
            "started_at": lease.started_at,
            "heartbeat_ts": lease.heartbeat_ts,
            "reachable": False,
            "error": None,
            "scrape_s": None,
            "doc": None,
        }
        try:
            state["doc"] = self._fetch_snapshot(lease.url)
            state["reachable"] = True
        except Exception as e:
            state["error"] = repr(e)
        state["scrape_s"] = round(time.monotonic() - t0, 6)
        return state

    def scrape(self) -> "FleetView":
        """One full fleet scrape: discover, fan out, fold. Never raises for
        a job's sake — every per-job failure is a row, not an exception.
        Serialized (concurrent callers would race the kept-alive
        connections); the FleetServer's view cache already collapses scrape
        storms before they get here."""
        with self._scrape_lock:
            return self._scrape_locked()

    def _scrape_locked(self) -> "FleetView":
        t0 = time.monotonic()
        if self.expire:
            expire_stale(self.fleet_dir, self.lease_ttl)
        leases = live_leases(self.fleet_dir, self.lease_ttl)
        states: list[dict] = []
        if leases:
            pool = self._ensure_pool(len(leases))
            states = list(
                pool.map(self._scrape_job, [leases[j] for j in sorted(leases)])
            )
        duration = time.monotonic() - t0
        unreachable = [s for s in states if not s["reachable"]]
        # Audit + self-metrics through the one shared kind→metric mapping, so
        # fleetd's live registry and a post-hoc aggregate of its events agree.
        self._observe(
            "fleet_scrape",
            jobs=len(states),
            unreachable=len(unreachable),
            duration_s=round(duration, 6),
        )
        for s in unreachable:
            self._observe("fleet_job_unreachable", job=s["job"], error=s["error"])
        return FleetView(self, states, duration)

    def _observe(self, kind: str, **payload) -> None:
        events_mod.record("fleetd", kind, **payload)
        observe_record({"kind": kind, "ts": time.time(), **payload}, self.registry)


class FleetView:
    """One scrape's fold: the documents every ``/fleet/*`` endpoint serves."""

    def __init__(self, agg: FleetAggregator, states: list[dict], duration: float):
        self.ts = time.time()
        self.fleet_dir = agg.fleet_dir
        self.scrape_s = round(duration, 6)
        self.states = states
        self.registry = self._merged_registry(agg)

    # -- merged metrics ------------------------------------------------------

    def _merged_registry(self, agg: FleetAggregator) -> MetricsRegistry:
        merged = MetricsRegistry()
        totals = MetricsRegistry()
        for s in self.states:
            metrics = (s["doc"] or {}).get("metrics")
            if not isinstance(metrics, dict):
                continue
            try:
                # The federation fold: same-named series of different jobs
                # stay separate under the injected job label...
                merged.merge(metrics, extra_labels={"job": s["job"]})
                # ...and still sum in the explicit fleet-total families.
                totals.merge(metrics)
            except (ValueError, TypeError):
                log.debug(f"unmergeable metrics from job {s['job']!r}", exc_info=True)
        tot = totals.snapshot()
        merged.merge({
            "ts": tot.get("ts"),
            "metrics": {
                f"{FLEET_TOTAL_PREFIX}{name}": entries
                for name, entries in (tot.get("metrics") or {}).items()
            },
        })
        merged.merge(agg.registry.snapshot())
        return merged

    def to_prometheus(self) -> str:
        return self.registry.to_prometheus()

    # -- per-job helpers -----------------------------------------------------

    def _row_base(self, s: dict) -> dict:
        return {
            "job": s["job"],
            "status": "ok" if s["reachable"] else "unreachable",
            "url": s["url"],
            "node_id": s["node_id"],
            "error": s["error"],
            "scrape_s": s["scrape_s"],
        }

    # -- documents -----------------------------------------------------------

    def goodput_doc(self) -> dict:
        """The scoreboard: reachable jobs ranked by goodput ratio (best
        first), unreachable jobs listed after them — present, named, and
        explicitly degraded rather than silently missing."""
        rows = []
        train_sum = wall_sum = 0.0
        for s in self.states:
            row = self._row_base(s)
            gp = (s["doc"] or {}).get("goodput")
            if isinstance(gp, dict):
                phases = gp.get("phases") or {}
                row.update(
                    goodput_ratio=gp.get("goodput_ratio"),
                    wall_clock_s=gp.get("wall_clock_s"),
                    steps=gp.get("steps"),
                    phases=phases,
                )
                if isinstance(gp.get("wall_clock_s"), (int, float)):
                    wall_sum += gp["wall_clock_s"]
                    train = phases.get("train")
                    if isinstance(train, (int, float)):
                        train_sum += train
            rows.append(row)
        rows.sort(
            key=lambda r: (
                r["status"] != "ok",
                -(r.get("goodput_ratio") or 0.0),
                r["job"],
            )
        )
        return {
            "schema": GOODPUT_SCHEMA,
            "ts": self.ts,
            "jobs": rows,
            "fleet": {
                "jobs": len(rows),
                "reachable": sum(1 for r in rows if r["status"] == "ok"),
                "wall_clock_s": round(wall_sum, 6),
                "train_s": round(train_sum, 6),
                "goodput_ratio": (
                    round(train_sum / wall_sum, 6) if wall_sum > 0 else 0.0
                ),
            },
        }

    def slo_doc(self) -> dict:
        """Jobs ranked worst-first by time-in-restart share, with
        time-to-detect / time-to-recover percentiles from the merged
        incident histograms — the page an on-call reads top-down."""
        rows = []
        for s in self.states:
            row = self._row_base(s)
            doc = s["doc"] or {}
            gp = doc.get("goodput") if isinstance(doc.get("goodput"), dict) else {}
            phases = gp.get("phases") or {}
            wall = gp.get("wall_clock_s")
            restart_s = phases.get("restart")
            incident_s = phases.get("incident")
            row.update(
                wall_clock_s=wall,
                restart_s=restart_s,
                incident_s=incident_s,
                restart_share=(
                    round(restart_s / wall, 6)
                    if isinstance(restart_s, (int, float))
                    and isinstance(wall, (int, float)) and wall > 0 else None
                ),
                goodput_ratio=gp.get("goodput_ratio"),
            )
            metrics = doc.get("metrics")
            m = metrics.get("metrics") if isinstance(metrics, dict) else None
            if isinstance(m, dict):
                row.update(
                    restarts=int(_counter_total(m, "tpu_restarts_total")),
                    incidents=int(_counter_total(m, "tpu_incidents_total")),
                    time_to_detect_s=_hist_stats(
                        m, "tpu_incident_time_to_detect_seconds"
                    ),
                    time_to_recover_s=_hist_stats(
                        m, "tpu_incident_time_to_recover_seconds"
                    ),
                )
            rows.append(row)
        # Worst first: unreachable jobs lead (they ARE the incident), then by
        # restart share descending.
        rows.sort(
            key=lambda r: (
                r["status"] == "ok",
                -(r.get("restart_share") or 0.0),
                r["job"],
            )
        )
        return {"schema": SLO_SCHEMA, "ts": self.ts, "jobs": rows}

    def incidents_doc(self) -> dict:
        """The cross-job incident feed: every job's recent ``tpu-incident-1``
        summaries stamped with their job, newest first."""
        feed = []
        by_job: dict[str, int] = {}
        for s in self.states:
            incidents = (s["doc"] or {}).get("incidents")
            if not isinstance(incidents, list):
                continue
            for inc in incidents:
                if not isinstance(inc, dict):
                    continue
                feed.append({"job": s["job"], **inc})
                by_job[s["job"]] = by_job.get(s["job"], 0) + 1
        feed.sort(
            key=lambda i: (
                -(i.get("opened_ts") if isinstance(i.get("opened_ts"), (int, float))
                  else 0.0),
                i["job"],
            )
        )
        return {
            "schema": INCIDENTS_SCHEMA,
            "ts": self.ts,
            "incidents": feed,
            "jobs": dict(sorted(by_job.items())),
            "unreachable": sorted(
                s["job"] for s in self.states if not s["reachable"]
            ),
        }

    def hangz_doc(self) -> dict:
        """The fleet-wide hang census: each job's ``/hangz`` document plus a
        flattened cross-job suspect ranking."""
        jobs = []
        suspects = []
        for s in self.states:
            row = self._row_base(s)
            hz = (s["doc"] or {}).get("hangz")
            if isinstance(hz, dict):
                row["census"] = hz
                for sus in hz.get("suspects") or []:
                    if isinstance(sus, dict):
                        suspects.append({"job": s["job"], **sus})
            jobs.append(row)
        suspects.sort(key=lambda x: (-(x.get("score") or 0.0), x["job"]))
        return {
            "schema": HANGZ_SCHEMA,
            "ts": self.ts,
            "jobs": jobs,
            "suspects": suspects,
        }

    def alerts_doc(self) -> dict:
        """The severity-ranked cross-job alert feed: every job's active
        watchtower alerts stamped with their job, pages first. An unreachable
        job degrades to its row (status ``unreachable``) — its last-known
        alerts are gone with its endpoint, but the job itself never vanishes
        from the feed, and the endpoint never answers non-200 for it."""
        jobs = []
        active = []
        firing_jobs: dict[str, int] = {}
        for s in self.states:
            row = self._row_base(s)
            al = (s["doc"] or {}).get("alerts")
            if isinstance(al, dict):
                row.update(
                    active=len(al.get("active") or []),
                    rules=len(al.get("rules") or []),
                    alerts_error=al.get("error"),
                )
                for a in al.get("active") or []:
                    if isinstance(a, dict):
                        active.append({"job": s["job"], **a})
                        firing_jobs[s["job"]] = firing_jobs.get(s["job"], 0) + 1
            jobs.append(row)
        active.sort(
            key=lambda a: (
                _SEVERITY_RANK.get(a.get("severity"), 9),
                -(a.get("fire_ts") if isinstance(a.get("fire_ts"), (int, float))
                  else 0.0),
                a["job"],
                str(a.get("rule")),
            )
        )
        return {
            "schema": ALERTS_SCHEMA,
            "ts": self.ts,
            "active": active,
            "jobs": jobs,
            "firing_jobs": dict(sorted(firing_jobs.items())),
            "unreachable": sorted(
                s["job"] for s in self.states if not s["reachable"]
            ),
        }

    def snapshot_doc(self) -> dict:
        """The whole fold as one offline-renderable artifact (what
        ``tpu-fleetd --snapshot`` persists and ``tpu-fleet`` renders)."""
        return {
            "schema": SNAPSHOT_SCHEMA,
            "ts": self.ts,
            "fleet_dir": self.fleet_dir,
            "scrape_s": self.scrape_s,
            "goodput": self.goodput_doc(),
            "slo": self.slo_doc(),
            "incidents": self.incidents_doc(),
            "hangz": self.hangz_doc(),
            "alerts": self.alerts_doc(),
            "metrics": self.registry.snapshot(),
        }
