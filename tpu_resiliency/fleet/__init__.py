"""Fleet federation: one control plane over many jobs.

Every launcher serves ``/metrics``, ``/goodput``, ``/healthz``, ``/hangz``,
``/autoscale`` — for exactly one job. This package is the layer above: jobs
announce themselves through atomic lease files in a shared ``--fleet-dir``
(:mod:`tpu_resiliency.fleet.registry`), a standalone aggregator fans out
bounded-timeout scrapes and tree-merges the per-job documents
(:mod:`tpu_resiliency.fleet.aggregator`), and a fleet HTTP server renders the
merged view — scoreboard, incident feed, hang census, SLO ranking
(:mod:`tpu_resiliency.fleet.server`, daemonized by ``tools/fleetd.py``).

The merge algebra is the one PR 7 proved associative + commutative
(``MetricsRegistry.merge``: counters sum, gauges LWW, histograms bucket-add) —
hierarchical federation is just that fold applied one level up, with a
``job=`` label injected so distinct jobs' same-named series never collide.
"""

from tpu_resiliency.fleet.registry import (  # noqa: F401
    JobLease,
    expire_stale,
    live_leases,
    read_leases,
    remove_lease,
    write_lease,
)
