"""Restart-coordination protocol over the platform KV store.

One place defining the key schema and operations shared by the three restart actors —
the wrapper restart loop (``wrap.py``), the in-rank monitor thread
(``monitor_thread.py``), and the out-of-process monitor (``monitor_process.py``) — the
re-design of the reference's ``inprocess/store.py`` ``StoreMixin`` contract plus the
barrier-completion duties of ``monitor_process.py:260-282`` / ``sibling_monitor.py``.

Schema (under the wrapper's store prefix):

- ``iteration``                  — current restart iteration (every live rank re-sets it)
- ``terminated``                 — cumulative set of dead/excluded initial ranks
- ``hb/{rank}``                  — per-rank monitor-process heartbeats (wall time)
- ``iter/{i}/interrupted``       — flag: some rank was interrupted this iteration
- ``iter/{i}/interruptions``     — list of InterruptionRecord
- ``iter/{i}/completed``         — flag: some active rank finished the wrapped fn
- ``barrier/iteration/{i}``      — end-of-round resync barrier (full initial world)
- ``barrier/completion/{i}``     — success-path barrier (full initial world)

Barriers always declare the **initial** world size; ranks that can't join themselves
are joined on-behalf (idempotently) by their own monitor process or by the sibling
watcher that detected their death. That keeps barrier membership static — survivors
never need to agree on a shrinking world mid-round (the subtle correctness core called
out in SURVEY §7).
"""

from __future__ import annotations

import time
from typing import Optional

from tpu_resiliency.exceptions import BarrierTimeout, StoreError, StoreTimeoutError
from tpu_resiliency.inprocess.attribution import Interruption, InterruptionRecord
from tpu_resiliency.platform.store import StoreView


class CompletionInterrupted(Exception):
    """Raised out of the completion-barrier wait when a peer's interruption record
    lands first: the completer must fall back into the restart path with everyone
    else instead of burning the full barrier timeout."""

    def __init__(self, iteration: int):
        super().__init__(f"interruption recorded during completion of iter {iteration}")
        self.iteration = iteration


class RestartCoordinator:
    def __init__(self, store: StoreView, world_size: int):
        self.store = store
        self.world_size = world_size

    # -- iteration tracking ------------------------------------------------

    def publish_iteration(self, iteration: int) -> None:
        self.store.set("iteration", iteration)

    def current_iteration(self, timeout: float = 0.0) -> Optional[int]:
        try:
            return self.store.get("iteration", timeout=timeout)
        except StoreTimeoutError:
            return None

    def set_job_done(self) -> None:
        self.store.set("job_done", True)

    def job_done(self) -> bool:
        return bool(self.store.try_get("job_done", False))

    # -- interruption records ---------------------------------------------

    def record_interruption(
        self,
        iteration: int,
        rank: int,
        kind: Interruption,
        message: str | None = None,
    ) -> None:
        rec = InterruptionRecord(rank=rank, interruption=kind, message=message)
        self.store.list_append(f"iter/{iteration}/interruptions", rec)
        self.store.set(f"iter/{iteration}/interrupted", True)

    def wait_interrupted(self, iteration: int, timeout: float) -> bool:
        try:
            self.store.get(f"iter/{iteration}/interrupted", timeout=timeout)
            return True
        except StoreTimeoutError:
            return False

    def is_interrupted(self, iteration: int) -> bool:
        return bool(self.store.try_get(f"iter/{iteration}/interrupted", False))

    def get_interruptions(self, iteration: int) -> list[InterruptionRecord]:
        return self.store.list_get(f"iter/{iteration}/interruptions")

    # -- completion --------------------------------------------------------

    def mark_completed(self, iteration: int) -> None:
        self.store.set(f"iter/{iteration}/completed", True)

    def is_completed(self, iteration: int) -> bool:
        return bool(self.store.try_get(f"iter/{iteration}/completed", False))

    # -- terminated ranks --------------------------------------------------

    def record_terminated(self, ranks) -> None:
        self.store.set_add("terminated", list(ranks))

    def terminated_ranks(self) -> frozenset[int]:
        return frozenset(self.store.set_get("terminated"))

    # -- degraded ranks (health-vector policy) -----------------------------

    def set_degraded(self, ranks) -> None:
        """Replace the advisory degraded set (telemetry policy output). Unlike
        ``terminated``, degraded status is reversible — a recovered rank leaves the
        set — so this is a plain value, not a grow-only set."""
        self.store.set("degraded", sorted(int(r) for r in ranks))

    def degraded_ranks(self) -> frozenset[int]:
        return frozenset(self.store.try_get("degraded", ()) or ())

    # -- heartbeats (monitor processes) ------------------------------------

    def heartbeat(self, rank: int) -> None:
        """Stamped with the *server's* clock so staleness never depends on cross-host
        NTP agreement (a 35 s clock step must not read as a 35 s-stale heartbeat)."""
        self.store.touch(f"hb/{rank}")

    def heartbeats(self) -> dict[int, float]:
        raw = self.store.prefix_get("hb/")
        out: dict[int, float] = {}
        for k, v in raw.items():
            try:
                out[int(k.rsplit("/", 1)[-1])] = float(v)
            except (ValueError, TypeError):
                continue
        return out

    def stale_peers(self, max_age: float) -> dict[int, float]:
        """Ranks whose heartbeat is older than `max_age` by the server clock, as
        ``{rank: age}``. The server returns only the stale set, so the per-tick
        liveness poll costs O(stale) on the wire regardless of world size."""
        raw = self.store.stale_keys("hb/", max_age)
        out: dict[int, float] = {}
        for k, age in raw.items():
            try:
                out[int(k.rsplit("/", 1)[-1])] = float(age)
            except (ValueError, TypeError):
                continue
        return out

    # -- barriers ----------------------------------------------------------

    def join_iteration_barrier(self, iteration: int, rank: int, timeout: float) -> None:
        self.store.barrier_join(
            f"barrier/iteration/{iteration}", rank, self.world_size, timeout
        )

    def join_completion_barrier(
        self,
        iteration: int,
        rank: int,
        timeout: float,
        poll_interval: float = 0.5,
    ) -> None:
        """Wait on the success-path barrier, but keep watching the interruption flag.

        A completer must not sit blind for the whole `timeout` while a peer's fault is
        already on record — that stall would outlast the faulted peer's iteration
        barrier and eject a healthy rank. So: register arrival without blocking, then
        poll barrier release vs. interruption; an interruption wins immediately and
        surfaces as :class:`CompletionInterrupted`.
        """
        name = f"barrier/completion/{iteration}"
        status = self.store.barrier_status(name)
        start_gen = status["generation"] if status else 0
        self.store.barrier_join(name, rank, self.world_size, timeout=0.0, wait=False)
        deadline = time.monotonic() + timeout
        while True:
            try:
                status = self.store.barrier_status(name)
                if status is not None and status["generation"] > start_gen:
                    return
                if self.is_interrupted(iteration):
                    raise CompletionInterrupted(iteration)
            except StoreError:
                # The coordinator (rank 0 hosts the server in-process) tore the store
                # down — it only does that after ITS completion barrier released, so
                # the round completed; treat server loss between polls as release.
                return
            if time.monotonic() >= deadline:
                raise BarrierTimeout(f"barrier {name!r} timed out after {timeout}s")
            time.sleep(poll_interval)

    def complete_barriers_for(self, iteration: int, rank: int) -> None:
        """Non-blocking on-behalf join of both of an iteration's barriers (idempotent)."""
        for name in (f"barrier/iteration/{iteration}", f"barrier/completion/{iteration}"):
            self.store.barrier_join(
                name, rank, self.world_size, timeout=0.0, wait=False, on_behalf=True
            )

    # -- garbage collection ------------------------------------------------

    def cleanup_iteration(self, iteration: int) -> None:
        """Drop a finished iteration's records, flags, and barriers. Called once the
        *next* iteration's resync barrier has released, at which point no live rank —
        and no proxy, which always targets the current iteration — can touch round
        `iteration` again; without this the store grows for the job's lifetime."""
        if iteration < 0:
            return
        self.store.prefix_clear(f"iter/{iteration}/")
        # Exact deletes: a prefix match on "barrier/iteration/1" would also take
        # iterations 10..19 with it.
        self.store.barrier_del(f"barrier/iteration/{iteration}")
        self.store.barrier_del(f"barrier/completion/{iteration}")
