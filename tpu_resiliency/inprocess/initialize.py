"""Per-iteration initializers: gates that run before each (re)start of the wrapped fn.

Analogue of reference ``inprocess/initialize.py``: ``RetryController`` bounds restart
iterations and minimum world sizes, raising :class:`RestartAbort` to make the whole
wrapper give up (``initialize.py:53-93``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tpu_resiliency.exceptions import RestartAbort
from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Initialize:
    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


@dataclasses.dataclass
class RetryController(Initialize):
    max_iterations: Optional[int] = None
    min_world_size: int = 1
    min_active_world_size: int = 1

    def __call__(self, state: FrozenState) -> FrozenState:
        if self.max_iterations is not None and state.iteration >= self.max_iterations:
            raise RestartAbort(f"reached max_iterations={self.max_iterations}")
        if state.world_size < self.min_world_size:
            raise RestartAbort(
                f"world_size {state.world_size} < min_world_size {self.min_world_size}"
            )
        if (
            state.active_world_size is not None
            and state.active_world_size < self.min_active_world_size
        ):
            raise RestartAbort(
                f"active_world_size {state.active_world_size} < "
                f"min_active_world_size {self.min_active_world_size}"
            )
        return state
