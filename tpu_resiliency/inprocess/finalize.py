"""Finalizers: user cleanup run after a fault, before the health check.

Analogue of reference ``inprocess/finalize.py``: ``ThreadedFinalize`` runs the user's
cleanup function in a thread with a timeout so a wedged cleanup cannot hang the
restart loop (``finalize.py:64-108``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional

from tpu_resiliency.exceptions import InternalError
from tpu_resiliency.inprocess.state import FrozenState


class Finalize:
    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


@dataclasses.dataclass
class ThreadedFinalize(Finalize):
    timeout: float
    fn: Callable[..., Any]
    args: tuple = ()
    kwargs: Optional[dict] = None

    def __call__(self, state: FrozenState) -> FrozenState:
        err: list[BaseException] = []
        done = threading.Event()

        def body() -> None:
            try:
                self.fn(*self.args, **(self.kwargs or {}))
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=body, name="inprocess-finalize", daemon=True)
        t.start()
        if not done.wait(self.timeout):
            raise InternalError(f"finalize did not complete within {self.timeout}s")
        if err:
            raise InternalError(f"finalize raised: {err[0]!r}") from err[0]
        return state
