"""Terminal callbacks: run once when the wrapper finishes or gives up.

Analogues of reference ``inprocess/completion.py:27`` and ``terminate.py:24``.
"""

from __future__ import annotations

from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Completion:
    """Called once after the wrapped function returns successfully."""

    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


class LogCompletion(Completion):
    def __call__(self, state: FrozenState) -> FrozenState:
        log.info(f"rank {state.rank}: wrapped function completed at iteration {state.iteration}")
        return state


class Terminate:
    """Called once when the restart loop aborts permanently (RestartAbort / fatal)."""

    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


class LogTerminate(Terminate):
    def __call__(self, state: FrozenState) -> FrozenState:
        log.error(f"rank {state.rank}: restart loop terminated at iteration {state.iteration}")
        return state
