"""Functional composition of pluggable restart callbacks.

Analogue of reference ``inprocess/compose.py:66-118``: chain N callables of the same
plugin family into one, preserving the family type for validation. The reference
computes the lowest common MRO ancestor so a composed ``Abort`` still isinstance-checks
as ``Abort``; here composition returns a :class:`Compose` wrapper that records its
members, and type checks use :func:`isinstance_or_composed`.
"""

from __future__ import annotations

from typing import Any, Callable


class Compose:
    """Left-to-right chain: ``Compose(f, g)(x) == g(f(x))`` — each callback receives
    the previous one's return value (state-threading convention of the plugin API)."""

    def __init__(self, *callbacks: Callable):
        if not callbacks:
            raise ValueError("Compose requires at least one callback")
        self.callbacks = callbacks

    def __call__(self, value: Any) -> Any:
        for cb in self.callbacks:
            value = cb(value)
        return value

    def __repr__(self) -> str:
        inner = ", ".join(repr(c) for c in self.callbacks)
        return f"Compose({inner})"


def isinstance_or_composed(obj: Any, cls: type) -> bool:
    """True if obj is a `cls`, or a Compose whose members all are."""
    if isinstance(obj, Compose):
        return all(isinstance_or_composed(c, cls) for c in obj.callbacks)
    return isinstance(obj, cls)
