"""Abort: tear down distributed/device state so a faulted iteration can't wedge us.

Analogue of reference ``inprocess/abort.py``: ``AbortTorchDistributed`` aborts NCCL
communicators in parallel threads then destroys the process group (``abort.py:58-105``).

There is no NCCL-communicator-abort equivalent for an in-flight XLA computation
(SURVEY §7 "hard parts"): a hung collective blocks ``block_until_ready`` until the
runtime notices peer loss. What *can* and must be torn down host-side:

- the JAX distributed client (coordination-service connection) — so the restarted
  iteration can re-`initialize` with the new world;
- compiled-computation caches pinned to the old mesh/world shape;
- our own coordination-store connections scoped to the aborted iteration.

The escalation ladder for truly stuck device programs is the same as the reference's:
soft (this abort) → hard (monitor process signals the OS process; the in-job launcher
restarts it).
"""

from __future__ import annotations

import dataclasses

from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Abort:
    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


@dataclasses.dataclass
class AbortJaxDistributed(Abort):
    """Shut down the JAX distributed client (multi-host coordination connection)."""

    def __call__(self, state: FrozenState) -> FrozenState:
        import jax

        try:
            if jax._src.distributed.global_state.client is not None:  # noqa: SLF001
                jax.distributed.shutdown()
                log.info("abort: jax.distributed shut down")
        except Exception as e:  # abort must never fail the restart loop
            log.warning(f"abort: jax.distributed.shutdown failed: {e!r}")
        return state


@dataclasses.dataclass
class AbortCompilationCache(Abort):
    """Drop compiled programs pinned to the previous world's mesh shapes.

    After rank reassignment the mesh changes; executables compiled for the old device
    assignment must not be reused (and on CPU/TPU they pin device buffers).
    """

    def __call__(self, state: FrozenState) -> FrozenState:
        import jax

        try:
            jax.clear_caches()
            log.info("abort: cleared jit/pjit compilation caches")
        except Exception as e:
            log.warning(f"abort: clear_caches failed: {e!r}")
        return state
