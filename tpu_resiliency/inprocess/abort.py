"""Abort: tear down distributed/device state so a faulted iteration can't wedge us.

Analogue of reference ``inprocess/abort.py``: ``AbortTorchDistributed`` aborts NCCL
communicators in parallel threads then destroys the process group (``abort.py:58-105``).

There is no NCCL-communicator-abort equivalent for an in-flight XLA computation
(SURVEY §7 "hard parts"): a hung collective blocks ``block_until_ready`` until the
runtime notices peer loss. What *can* and must be torn down host-side:

- the JAX distributed client (coordination-service connection) — so the restarted
  iteration can re-`initialize` with the new world;
- compiled-computation caches pinned to the old mesh/world shape;
- our own coordination-store connections scoped to the aborted iteration.

The escalation ladder for truly stuck device programs is the same as the reference's:
soft (this abort) → hard (monitor process signals the OS process; the in-job launcher
restarts it).
"""

from __future__ import annotations

import dataclasses

from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Abort:
    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


@dataclasses.dataclass
class AbortJaxDistributed(Abort):
    """Shut down the JAX distributed client/service and clear the XLA backends so
    the restarted iteration can ``jax.distributed.initialize`` a NEW world.

    Clearing backends is not optional: the public ``initialize`` refuses while
    backends are live, and executables/buffers of the old world pin the dead
    runtime. Requires the job to have initialized via
    :func:`tpu_resiliency.platform.distributed.initialize` (recoverable client) —
    otherwise peer death terminates this process before any abort can run.
    Backends are only torn down when a distributed client was actually active, so
    single-process jobs don't pay a pointless recompile. Proven end-to-end by
    ``tests/inprocess/test_abort_reinit.py``.
    """

    def __call__(self, state: FrozenState) -> FrozenState:
        from tpu_resiliency.platform import distributed

        # Never raises: the restart loop must proceed regardless.
        distributed.shutdown_for_restart()
        return state


@dataclasses.dataclass
class AbortCompilationCache(Abort):
    """Drop compiled programs pinned to the previous world's mesh shapes.

    After rank reassignment the mesh changes; executables compiled for the old device
    assignment must not be reused (and on CPU/TPU they pin device buffers).
    """

    def __call__(self, state: FrozenState) -> FrozenState:
        import jax

        try:
            jax.clear_caches()
            log.info("abort: cleared jit/pjit compilation caches")
        except Exception as e:
            log.warning(f"abort: clear_caches failed: {e!r}")
        return state
