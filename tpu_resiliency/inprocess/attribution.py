"""Interruption attribution: why a restart round started.

Analogue of reference ``inprocess/attribution.py:7-45``. Records are tiny picklable
tuples pushed into the coordination store's interruption list; every rank's monitor
thread reads them to log *why* it is restarting.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class Interruption(enum.Enum):
    EXCEPTION = enum.auto()  # wrapped fn raised on this rank
    SOFT_TIMEOUT = enum.auto()  # progress timestamp stale past soft limit
    HARD_TIMEOUT = enum.auto()  # stale past hard limit; rank was signalled
    TERMINATED = enum.auto()  # rank deliberately terminated (policy / control request)
    UNRESPONSIVE = enum.auto()  # sibling heartbeat ring found the rank dead
    MONITOR_PROCESS_DEAD = enum.auto()  # rank's main process exited; monitor reported it
    RESTART_REQUESTED = enum.auto()  # explicit user-requested restart


@dataclasses.dataclass(frozen=True)
class InterruptionRecord:
    rank: int
    interruption: Interruption
    message: Optional[str] = None

    def describe(self) -> str:
        msg = f": {self.message}" if self.message else ""
        return f"rank {self.rank} {self.interruption.name}{msg}"
