"""Layered-restart protocol glue: in-process restarter announces its state machine.

Analogue of reference ``inprocess/nested_restarter.py:34-107``: the in-process and
in-job restarters coordinate *by log-line contract* — machine-parseable
``[NestedRestarter] name=[InProcess] state=...`` lines that the in-job launcher's rank
monitor consumes (reference ``rank_monitor_state_machine.py:127-145``). The state
machine implementation is shared with the in-job side (``watchdog/state_machine.py``);
one :class:`NestedRestarter` owns it and exposes callbacks for the wrapper's plugin
slots so every transition is announced from the right place in the restart loop.
"""

from __future__ import annotations

from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.watchdog.state_machine import RestarterState, RestarterStateMachine


class NestedRestarter:
    """One per process; wire its callbacks into the Wrapper plugin slots:

    - ``.on_initialize`` → ``Wrapper.initialize`` (announces INITIALIZE on the first
      iteration, HANDLING_PROCESSING/COMPLETED when re-entering after a fault)
    - ``.on_abort`` → ``Wrapper.abort`` (announces HANDLING_START)
    - ``.on_completion`` → ``Wrapper.completion`` (announces FINALIZED)
    - ``.on_terminate`` → ``Wrapper.terminate`` (announces ABORTED)
    """

    def __init__(self, name: str = "InProcess"):
        # Non-strict: plugin slots may fire in fault-dependent orders (e.g. abort can
        # run twice when both the monitor and the local path handle a round).
        self.machine = RestarterStateMachine(name=name, strict=False)
        self.on_initialize = _Initialize(self)
        self.on_abort = _Abort(self)
        self.on_completion = _Completion(self)
        self.on_terminate = _Terminate(self)


class _Bound:
    def __init__(self, owner: NestedRestarter):
        self.owner = owner


class _Initialize(_Bound):
    def __call__(self, state: FrozenState) -> FrozenState:
        m = self.owner.machine
        if state.iteration == 0:
            m.initialize()
        else:
            if m.state == RestarterState.HANDLING_START:
                m.handling_processing(f"iteration={state.iteration}")
            if m.state == RestarterState.HANDLING_PROCESSING:
                m.handling_completed(f"iteration={state.iteration}")
        return state


class _Abort(_Bound):
    def __call__(self, state: FrozenState) -> FrozenState:
        if self.owner.machine.state != RestarterState.HANDLING_START:
            self.owner.machine.handling_start(f"iteration={state.iteration}")
        return state


class _Completion(_Bound):
    def __call__(self, state: FrozenState) -> FrozenState:
        self.owner.machine.finalized()
        return state


class _Terminate(_Bound):
    def __call__(self, state: FrozenState) -> FrozenState:
        self.owner.machine.aborted()
        return state
