"""In-process restart: recover from faults without killing the training process.

TPU-native re-design of the reference's ``inprocess/`` package (SURVEY §2.4): wrap the
training function with :class:`Wrapper`; on any fault the engine aborts, finalizes,
health-checks, reassigns ranks, and re-enters — skipping scheduler launch, container
start, interpreter init, and device-runtime creation on the recovery path.
"""

from tpu_resiliency.inprocess.abort import Abort, AbortCompilationCache, AbortJaxDistributed
from tpu_resiliency.inprocess.attribution import Interruption, InterruptionRecord
from tpu_resiliency.inprocess.completion import (
    Completion,
    LogCompletion,
    LogTerminate,
    Terminate,
)
from tpu_resiliency.inprocess.compose import Compose, isinstance_or_composed
from tpu_resiliency.inprocess.coordination import RestartCoordinator
from tpu_resiliency.inprocess.finalize import Finalize, ThreadedFinalize
from tpu_resiliency.inprocess.health_check import FaultCounter, HealthCheck, JaxHealthCheck
from tpu_resiliency.inprocess.initialize import Initialize, RetryController
from tpu_resiliency.inprocess.monitor_thread import MonitorThread, RankShouldRestart
from tpu_resiliency.inprocess.monitor_process import MonitorConfig, MonitorProcess
from tpu_resiliency.inprocess.nested_restarter import NestedRestarter
from tpu_resiliency.inprocess.progress_watchdog import ProgressWatchdog
from tpu_resiliency.inprocess.rank_assignment import (
    ActivateAllRanks,
    ActiveWorldSizeDivisibleBy,
    DemoteDegraded,
    FillGaps,
    FilterCountGroupedByKey,
    Layer,
    LayerFlag,
    MaxActiveWorldSize,
    RankAssignmentCtx,
    ShiftRanks,
    Tree,
)
from tpu_resiliency.inprocess.state import FrozenState, Mode, State
from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper

__all__ = [
    "Abort",
    "AbortCompilationCache",
    "AbortJaxDistributed",
    "ActivateAllRanks",
    "ActiveWorldSizeDivisibleBy",
    "CallWrapper",
    "Completion",
    "Compose",
    "FaultCounter",
    "FillGaps",
    "FilterCountGroupedByKey",
    "Finalize",
    "FrozenState",
    "HealthCheck",
    "Initialize",
    "Interruption",
    "InterruptionRecord",
    "JaxHealthCheck",
    "Layer",
    "LayerFlag",
    "LogCompletion",
    "LogTerminate",
    "DemoteDegraded",
    "MaxActiveWorldSize",
    "Mode",
    "MonitorConfig",
    "MonitorProcess",
    "MonitorThread",
    "NestedRestarter",
    "ProgressWatchdog",
    "RankAssignmentCtx",
    "RankShouldRestart",
    "RestartCoordinator",
    "RetryController",
    "ShiftRanks",
    "State",
    "Terminate",
    "ThreadedFinalize",
    "Tree",
    "Wrapper",
    "isinstance_or_composed",
]
