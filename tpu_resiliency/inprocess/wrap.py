"""The in-process restart engine: ``Wrapper`` decorator + ``CallWrapper`` loop.

Re-design of the reference's ``inprocess/wrap.py`` (``Wrapper:75``, ``CallWrapper:246``,
restart loop ``:394-588``) for JAX/TPU training functions. The contract preserved
(SURVEY §7): any fault — local exception, peer interruption record, monitor soft/hard
timeout, sibling-detected death — routes every surviving rank through

    abort → finalize → health check → iteration barrier → rank reassignment → re-enter

with per-iteration store scoping, while spare (INACTIVE) ranks wait in reserve and
barrier membership stays fixed at the initial world size (dead ranks' barriers are
completed by their monitor proxies — see ``coordination.py``).

What is TPU-native here: the abort chain tears down the JAX distributed client and
compiled-program caches instead of NCCL communicators (``abort.py``); the health check
is a compiled-probe liveness test (``health_check.py``); rank reassignment can use ICI
topology keys (``rank_assignment.Tree``); and the wrapped fn re-creates its mesh and
re-jits against the new world on re-entry (XLA recompiles; weights come back from the
local checkpoint layer).

Faults the engine does NOT try to unwind in place: an XLA program truly stuck on device
has no abort path — the escalation ladder ends with the monitor process signalling the
OS process and the in-job launcher restarting it (same ladder as the reference,
``monitor_process.py:242-258``).
"""

from __future__ import annotations

import dataclasses
import gc
import inspect
import os
import signal
import threading
import time
from typing import Any, Callable, Optional

from tpu_resiliency.exceptions import (
    BarrierOverflow,
    BarrierTimeout,
    HealthCheckError,
    RestartAbort,
    StoreError,
)
from tpu_resiliency.inprocess.attribution import Interruption
from tpu_resiliency.inprocess.coordination import CompletionInterrupted, RestartCoordinator
from tpu_resiliency.inprocess.monitor_process import MonitorConfig, MonitorProcess
from tpu_resiliency.inprocess.monitor_thread import MonitorThread, RankShouldRestart
from tpu_resiliency.inprocess.progress_watchdog import ProgressWatchdog
from tpu_resiliency.inprocess.rank_assignment import (
    RankAssignmentCtx,
    ShiftRanks,
)
from tpu_resiliency.inprocess.state import Mode, State
from tpu_resiliency.platform.store import host_store, store_addr_from_env
from tpu_resiliency.utils import flight_recorder, location
from tpu_resiliency.utils.events import record as record_event
from tpu_resiliency.utils.logging import get_logger
from tpu_resiliency.utils.tracing import span

log = get_logger(__name__)


@dataclasses.dataclass
class Wrapper:
    """Decorator configuring the restart engine (reference ``wrap.py:75-236``).

    Pluggable chains receive and return ``FrozenState`` and may be composed with
    :class:`~tpu_resiliency.inprocess.compose.Compose`. Timeout ordering is validated
    at construction (reference ``wrap.py:184-191``).
    """

    initialize: Optional[Callable] = None
    abort: Optional[Callable] = None
    finalize: Optional[Callable] = None
    health_check: Optional[Callable] = None
    rank_assignment: Callable = dataclasses.field(default_factory=ShiftRanks)
    completion: Optional[Callable] = None
    terminate: Optional[Callable] = None

    monitor_interval: float = 1.0
    last_call_wait: float = 1.0
    soft_timeout: float = 60.0
    hard_timeout: float = 90.0
    heartbeat_interval: float = 1.0
    heartbeat_timeout: float = 30.0
    barrier_timeout: float = 120.0
    completion_timeout: float = 120.0
    termination_signal: int = int(signal.SIGTERM)
    #: How long the rank hosting the coordination server keeps it alive after a
    #: clean completion, so a straggler that was proxy-completed (declared dead
    #: under load but actually alive) can still read ``job_done`` and stand down
    #: instead of crashing on a dead socket.
    server_linger: float = 5.0

    enable_monitor_process: bool = True
    store_host: Optional[str] = None
    store_port: Optional[int] = None
    store_prefix: str = "inprocess/"

    def __post_init__(self) -> None:
        checks = [
            (self.monitor_interval <= self.soft_timeout, "monitor_interval <= soft_timeout"),
            (self.soft_timeout < self.hard_timeout, "soft_timeout < hard_timeout"),
            (self.heartbeat_interval < self.heartbeat_timeout, "heartbeat_interval < heartbeat_timeout"),
            (self.heartbeat_timeout <= self.barrier_timeout, "heartbeat_timeout <= barrier_timeout"),
            (self.hard_timeout <= self.barrier_timeout, "hard_timeout <= barrier_timeout"),
            (self.last_call_wait < self.soft_timeout, "last_call_wait < soft_timeout"),
        ]
        for ok, what in checks:
            if not ok:
                raise ValueError(f"timeout ordering violated: require {what}")

    def __call__(self, fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            return CallWrapper(self, fn, args, kwargs).run()

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__wrapped__ = fn
        return wrapped


class CallWrapper:
    """One wrapped invocation: owns the store, monitors, and the restart loop.

    Public API usable from inside the wrapped fn (injected when the fn has a parameter
    annotated ``CallWrapper`` — reference param injection, ``wrap.py:426-433``):

    - ``atomic()``: reentrant critical section shielded from async restart injection
      (reference ``wrap.py:372-391``).
    - ``ping()``: manual progress mark feeding the watchdog.
    - ``state``: this rank's frozen state (iteration, active rank/world, mode).
    """

    def __init__(self, wrapper: Wrapper, fn: Callable, args: tuple, kwargs: dict):
        self.w = wrapper
        self.fn = fn
        self.fn_args = args
        self.fn_kwargs = kwargs

        self.state = State.from_env()
        self._atomic_lock = threading.RLock()

        # Persistent compilation cache (launcher --compile-cache-dir): applied
        # BEFORE the wrapped fn can trace/compile anything, so a restarted
        # incarnation's first step loads the previous round's executables.
        # One-shot per process; records the compile_cache event
        # (hit / miss / miss_corrupt + bytes) that feeds
        # tpu_compile_cache_total{outcome} and the goodput ledger's restart
        # attribution. Failures degrade to a cold compile, never an error.
        try:
            from tpu_resiliency.platform import compile_cache

            compile_cache.apply_from_env()
        except Exception:
            log.debug("compile cache apply failed", exc_info=True)

        host, port = store_addr_from_env()
        if wrapper.store_host is not None:
            host = wrapper.store_host
        if wrapper.store_port is not None:
            port = wrapper.store_port
        prefix = wrapper.store_prefix
        external = os.environ.get("TPU_RESILIENCY_STORE_EXTERNAL") == "1"
        if external:
            # Layered restart: we run under a launcher that already hosts the
            # coordination store — connect as a client (rank 0 must NOT bind the
            # port again), and scope this incarnation's restart state by the
            # launcher round so a respawned process never sees its dead
            # predecessor's terminated/interrupted records (the in-job ↔
            # in-process coupling, reference ``in_job_and_in_process_example``).
            launcher_round = os.environ.get("TPU_FT_RESTART_COUNT", "0")
            prefix = f"{prefix}r{launcher_round}/"
            # Factory, not the constructor: under a launcher-hosted store
            # CLIQUE ($TPU_RESILIENCY_STORE_SHARDS) every key must route
            # through the same shard map the launcher's clients use.
            from tpu_resiliency.platform.shardstore import connect_store

            self.store = connect_store(host, port, prefix=prefix)
            self.server = None
        else:
            self.store, self.server = host_store(
                self.state.rank, host, port, prefix=prefix
            )
            if self.server is not None:
                # Overwrite, not setdefault: when WE host, the env must carry
                # the port actually bound — a caller-provided "0" (host on an
                # ephemeral port) left in place would send any descendant that
                # resolves store_addr_from_env() to 127.0.0.1:0.
                os.environ["TPU_RESILIENCY_STORE_PORT"] = str(self.server.port)
        # Resolved coordinator address, for the fresh-connection job_done probe a
        # rank makes when its persistent client hits a dead server mid-restart.
        self._store_addr = (
            ("127.0.0.1", self.server.port) if self.server is not None else (host, port)
        )
        self._store_prefix = prefix
        self.coord = RestartCoordinator(self.store, self.state.world_size)

        self.monitor_process: Optional[MonitorProcess] = None
        if wrapper.enable_monitor_process:
            self.monitor_process = MonitorProcess(
                MonitorConfig(
                    rank=self.state.rank,
                    world_size=self.state.world_size,
                    store_host="127.0.0.1" if self.server is not None else host,
                    store_port=self.server.port if self.server is not None else port,
                    store_prefix=wrapper.store_prefix,
                    monitor_interval=wrapper.monitor_interval,
                    heartbeat_interval=wrapper.heartbeat_interval,
                    heartbeat_timeout=wrapper.heartbeat_timeout,
                    soft_timeout=wrapper.soft_timeout,
                    hard_timeout=wrapper.hard_timeout,
                    termination_signal=wrapper.termination_signal,
                )
            )
            self.monitor_process.start()

        self.watchdog = ProgressWatchdog(
            interval=wrapper.heartbeat_interval, report=self._report_progress
        )
        self.watchdog.start()

        # All ranks meet before the first iteration (reference initial_barrier,
        # ``store.py:293``). Span'd: the wait is the cross-rank skew at start
        # (and a straggling peer shows up as THIS rank's long barrier slice).
        with span("inprocess", "barrier.initial", rank=self.state.rank):
            self.store.barrier_join(
                "barrier/initial", self.state.rank, self.state.world_size,
                wrapper.barrier_timeout,
            )

    # -- API exposed to the wrapped fn -------------------------------------

    def atomic(self):
        return self._atomic_lock

    def ping(self) -> None:
        self.watchdog.ping()

    @property
    def frozen_state(self):
        return self.state.freeze()

    @property
    def iteration(self) -> int:
        return self.state.iteration

    # -- internals ---------------------------------------------------------

    def _report_progress(self, kind: str, t: float) -> None:
        if self.monitor_process is not None:
            self.monitor_process.report_timestamp(kind, t)

    def _chain(self, chain: Optional[Callable], frozen):
        return frozen if chain is None else chain(frozen)

    def _maybe_inject_self(self, kwargs: dict) -> dict:
        try:
            sig = inspect.signature(self.fn)
        except (TypeError, ValueError):
            return kwargs
        for name, param in sig.parameters.items():
            if name in kwargs:
                continue
            if param.annotation is CallWrapper or param.annotation == "CallWrapper":
                kwargs = dict(kwargs)
                kwargs[name] = self
        return kwargs

    def _reserve_wait(self, iteration: int) -> bool:
        """INACTIVE spare: wait until some active rank completes or a fault occurs
        (reference ``reserve_fn``, ``wrap.py:57-72``). Returns True if the job
        completed while the coordinator went away (stand down — the caller must
        skip the completion coordination). A transient transport hiccup (server
        still reachable) resumes polling; a genuinely lost coordinator raises
        :class:`RestartAbort` so an idle spare never masks a failed job with a
        clean exit."""
        while True:
            try:
                if self.coord.is_completed(iteration):
                    return False
                if self.coord.is_interrupted(iteration):
                    raise RankShouldRestart
            except StoreError as se:
                done = self._probe_job_done()
                if done is True:
                    return True
                if done is None:
                    raise RestartAbort(
                        f"coordination store lost while in reserve: {se!r}"
                    ) from se
                # Reachable but not done: transient hiccup — keep reserving (the
                # persistent client reconnects on the next call).
            time.sleep(self.w.monitor_interval)

    def _leave(self) -> None:
        """This rank permanently exits the job: peers' barriers are proxied by our
        monitor process from now on."""
        try:
            self.coord.record_terminated([self.state.rank])
        except StoreError:
            pass  # coordinator already gone — nothing left to tell
        self.watchdog.shutdown()
        if self.monitor_process is not None:
            # Dropping the link makes the monitor treat us as dead → barrier proxy.
            self.monitor_process.abandon()

    @staticmethod
    def _quiesce(monitor) -> None:
        """Retry ``monitor.acknowledge()`` through late async deliveries: an
        injection scheduled just before the handler ran can land on the CALL
        bytecode itself or anywhere inside acknowledge — catch it here and go
        again (acknowledge is idempotent). Convergence: every retry re-clears
        ``_armed``/re-sets ``_ack``, and the monitor never schedules a new
        injection once ack is set, so the pending count only falls."""
        while True:
            try:
                monitor.acknowledge()
                return
            except (RankShouldRestart, SystemError):
                continue

    def _terminate_and_leave(self, monitor, state) -> None:
        """Rank-departure cleanup shared by the abort and BaseException exits:
        silence the monitor, run the terminate chain, and leave the job. Full
        quiesce (not a bare acknowledge): this is also the exit for fn-raised
        RestartAbort/HealthCheckError, which bypasses the restart handler's
        quiesce — a pending injection must not tear the terminate chain or the
        record_terminated store write."""
        self._quiesce(monitor)
        try:
            monitor.shutdown()
        except Exception:
            pass
        self._chain(self.w.terminate, state.freeze())
        self._leave()

    def _shutdown_clean(self) -> None:
        try:
            self.coord.set_job_done()
        except Exception:
            pass  # rank 0 may already have torn the server down
        self.watchdog.shutdown()
        if self.monitor_process is not None:
            self.monitor_process.shutdown()
        self.store.close()
        if self.server is not None:
            # All ranks are past the completion barrier. The server lingers briefly
            # (daemon timer; dies with the process either way) so a proxy-completed
            # straggler can still read job_done and stand down cleanly.
            if self.w.server_linger > 0:
                t = threading.Timer(self.w.server_linger, self.server.close)
                t.daemon = True
                t.start()
            else:
                self.server.close()

    def _probe_job_done(self) -> Optional[bool]:
        """The persistent store client hit a transport error. Probe with a fresh
        short-lived connection: ``True`` — job completed without us (we were
        declared dead during a completion round; stand down). ``False`` — server
        reachable, job not done (transient hiccup). ``None`` — coordinator
        unreachable (genuinely lost; surface loudly)."""
        from tpu_resiliency.platform.shardstore import connect_store

        host, port = self._store_addr
        try:
            probe = connect_store(
                host, port, prefix=self._store_prefix, timeout=2.0, connect_retries=2
            )
            try:
                return bool(probe.try_get("job_done", False))
            finally:
                probe.close()
        except StoreError:
            return None

    def _stand_down(self, monitor, iteration: int, reason: str) -> None:
        """Exit cleanly as the odd rank out of a completed job: the coordinator is
        gone and ``job_done`` (or reserve-loss semantics) says the job finished
        without us."""
        log.warning(f"rank {self.state.rank}: standing down (iter {iteration}): {reason}")
        record_event(
            "inprocess", "stood_down", iteration=iteration,
            initial_rank=self.state.initial_rank, reason=reason,
        )
        try:
            monitor.shutdown()
        except Exception:
            pass
        self.watchdog.shutdown()
        if self.monitor_process is not None:
            self.monitor_process.shutdown()
        self.store.close()
        if self.server is not None:
            self.server.close()

    # -- the restart loop --------------------------------------------------

    def _restart_transition(self, monitor, abort_fn, state, iteration: int):
        """Everything between a fault and re-entering the wrapped fn: finalize →
        health check → iteration barrier → rank reassignment → advance.

        Returns the advanced state, or ``None`` when this rank stood down (the
        job completed without it); raises ``RestartAbort``/``HealthCheckError``
        to leave the restart loop."""
        w, coord = self.w, self.coord
        if self.monitor_process is not None:
            self.monitor_process.set_phase("coord")
        monitor.shutdown()
        if abort_fn is not None and not monitor.fired:
            # Local exception path: the monitor thread never ran the abort
            # chain (we acknowledged before it fired) — run it here so abort
            # semantics hold on every restart (reference routes local
            # exceptions through the monitor for the same guarantee).
            with self._atomic_lock:
                abort_fn()
        frozen = state.freeze()
        self._chain(w.finalize, frozen)
        self._chain(w.health_check, frozen)  # raises to exclude this rank
        # Check the terminated set BEFORE joining: a falsely-declared-dead
        # rank's barriers were already proxy-joined, so a waiting join here
        # would overflow rather than surface the real condition.
        try:
            # Job already completed without us? (We were proxy-completed out
            # of a finishing round after being starved.) Checking BEFORE the
            # barrier join is what makes the server_linger rescue work: a
            # straggler that parks on the next round's barrier would only be
            # kicked out at teardown, when the job_done probe can no longer
            # answer.
            if coord.job_done():
                self._stand_down(
                    monitor, iteration, "job completed while this rank restarted"
                )
                return None
            if state.initial_rank in coord.terminated_ranks():
                raise RestartAbort(
                    f"rank {state.initial_rank} was declared terminated by peers"
                )
            try:
                # The barrier wait is where a restart stalls when a peer is
                # slow to unwind — its own slice inside inprocess.restart.
                with span("inprocess", "barrier.iteration", iteration=iteration):
                    coord.join_iteration_barrier(
                        iteration, state.rank, w.barrier_timeout
                    )
            except BarrierOverflow as e:
                # Our slot was proxy-joined between the check and the join.
                raise RestartAbort(
                    f"rank {state.initial_rank} was declared terminated by peers"
                ) from e
            except BarrierTimeout as e:
                raise RestartAbort(
                    f"iteration barrier timed out after {w.barrier_timeout}s: "
                    f"unproxied dead ranks or store loss"
                ) from e
            terminated = coord.terminated_ranks()
            degraded = coord.degraded_ranks()
        except StoreError as se:
            # The coordinator is gone. A rank that was proxy-completed out
            # of a finishing round (declared dead under load but actually
            # alive) lands here when rank 0 tears the store down: stand
            # down if the job completed, abort loudly otherwise.
            if self._probe_job_done() is True:
                self._stand_down(
                    monitor, iteration, "coordinator gone mid-restart; job done"
                )
                return None
            raise RestartAbort(
                f"coordination store lost mid-restart: {se!r}"
            ) from se
        ctx = RankAssignmentCtx(state, terminated, degraded)
        state = w.rank_assignment(ctx).state
        if state.mode == Mode.TERMINATED:
            raise RestartAbort("excluded by rank assignment")
        state.advance()
        state.set_distributed_vars()
        self.state = state
        if state.rank == 0 and iteration > 0:
            # The round-(i) resync barrier released, so nothing can touch
            # round i-1 anymore: reclaim its records/flags/barriers.
            coord.cleanup_iteration(iteration - 1)
        gc.collect()
        return state

    def run(self) -> Any:
        w, state, coord = self.w, self.state, self.coord

        # Initial assignment (reference ``wrap.py:404-406``).
        ctx = RankAssignmentCtx(
            state, coord.terminated_ranks(), coord.degraded_ranks()
        )
        state = w.rank_assignment(ctx).state
        state.set_distributed_vars()

        while True:
            iteration = state.iteration
            coord.publish_iteration(iteration)
            if self.monitor_process is not None:
                self.monitor_process.start_iteration(iteration)

            frozen = state.freeze()
            location.note_step(iteration)
            record_event(
                "inprocess", "iteration_start", iteration=iteration,
                initial_rank=state.initial_rank, active_rank=state.active_rank,
                active_world=state.active_world_size, mode=state.mode.name,
            )
            abort_fn = (
                (lambda: self._chain(w.abort, state.freeze())) if w.abort else None
            )
            monitor = MonitorThread(
                coord,
                iteration,
                threading.main_thread().ident,
                self._atomic_lock,
                abort_fn=abort_fn,
                interval=w.monitor_interval,
                last_call_wait=w.last_call_wait,
            )
            monitor.start()
            restart = False
            try:
                try:
                    self._chain(w.initialize, frozen)
                    state.set_distributed_vars()
                    if self.monitor_process is not None:
                        self.monitor_process.set_phase("running")
                    monitor.arm()
                    if state.mode in (Mode.ACTIVE, Mode.INITIALIZED):
                        kwargs = self._maybe_inject_self(self.fn_kwargs)
                        ret = self.fn(*self.fn_args, **kwargs)
                    else:
                        if self._reserve_wait(iteration):
                            monitor.disarm()
                            self._stand_down(
                                monitor, iteration, "coordinator gone while in reserve"
                            )
                            return None
                        ret = None
                    monitor.disarm()
                    if self.monitor_process is not None:
                        self.monitor_process.set_phase("coord")
                    try:
                        coord.mark_completed(iteration)
                        with span(
                            "inprocess", "barrier.completion", iteration=iteration
                        ):
                            coord.join_completion_barrier(
                                iteration, state.rank, w.completion_timeout
                            )
                    except CompletionInterrupted:
                        # A peer faulted while we were completing; fall back into
                        # the restart path with everyone else immediately — sitting
                        # out the full barrier timeout here would outlast the faulted
                        # rank's iteration-barrier wait and eject a healthy rank.
                        raise RankShouldRestart from None
                    except StoreError as se:
                        # Coordinator died while we completed. If the job is done
                        # (peers completed and tore the store down), our own result
                        # stands; otherwise the loss is fatal (a retry of the
                        # completion join after a half-registered arrival would
                        # overflow, so a reachable-but-unfinished server is fatal
                        # here too).
                        if self._probe_job_done() is True:
                            self._stand_down(
                                monitor, iteration, "coordinator gone at completion"
                            )
                            return ret
                        raise RestartAbort(
                            f"coordination store lost at completion: {se!r}"
                        ) from se
                    self._chain(w.completion, state.freeze())
                    record_event(
                        "inprocess", "completed", iteration=iteration,
                        initial_rank=state.initial_rank,
                    )
                    monitor.shutdown()  # before the store closes under its poll loop
                    self._shutdown_clean()
                    return ret
                except (RestartAbort, HealthCheckError):
                    raise
                except BaseException as e:
                    # ONE handler for every other unwind — restart signal, user
                    # exception, process-leaving BaseException — so the uncovered
                    # async-delivery window is a single handler entry, not three.
                    # Quiesce BEFORE any store traffic: while the monitor is armed,
                    # an injection can land inside the store client and escape this
                    # handler, killing a healthy rank (the round-2 delivery race).
                    # After _quiesce() the thread is acknowledged and drained, so
                    # the coordination calls below cannot be torn.
                    self._quiesce(monitor)
                    if isinstance(e, RankShouldRestart) or (
                        isinstance(e, SystemError) and monitor.fired
                    ):
                        # A mangled delivery (SystemError out of a returning C call
                        # while an injection was pending) is the restart signal it
                        # was meant to be — this rank is healthy.
                        log.info(
                            f"rank {state.rank}: restart signalled (iter {iteration}, {e!r})"
                        )
                        record_event(
                            "inprocess", "restart_signalled", iteration=iteration,
                            initial_rank=state.initial_rank,
                        )
                        restart = True
                    elif isinstance(e, Exception):
                        state.fn_exception = e
                        try:
                            coord.record_interruption(
                                iteration, state.rank, Interruption.EXCEPTION, repr(e)
                            )
                        except StoreError:
                            pass  # dead coordinator: the restart transition resolves it
                        log.warning(
                            f"rank {state.rank}: wrapped fn raised {e!r} (iter {iteration})"
                        )
                        record_event(
                            "inprocess", "fn_exception", iteration=iteration,
                            initial_rank=state.initial_rank, error=repr(e),
                        )
                        # The last seconds before this exception are exactly
                        # what a postmortem wants — snapshot them now, while
                        # this incarnation still owns its ring.
                        flight_recorder.flush("fn_exception", detail=repr(e))
                        restart = True
                    else:
                        # SystemExit / KeyboardInterrupt mean the rank is leaving,
                        # not restarting: record it terminated so peers restart
                        # without us, run the terminate chain, and re-raise
                        # (reference restarts only on Exception; its outer handler
                        # re-raises, ``wrap.py:558``).
                        state.fn_exception = e
                        try:
                            coord.record_interruption(
                                iteration, state.rank, Interruption.TERMINATED, repr(e)
                            )
                        except StoreError:
                            pass  # dead coordinator — still run the local exit path
                        log.warning(
                            f"rank {state.rank}: wrapped fn raised {e!r} — terminating rank"
                        )
                        record_event(
                            "inprocess", "rank_terminated", iteration=iteration,
                            initial_rank=state.initial_rank, error=repr(e),
                        )
                        flight_recorder.flush("rank_terminated", detail=repr(e))
                        self._terminate_and_leave(monitor, state)
                        raise

                # ---- restart path ----
                # One span per restart transition: its duration is the
                # fault→re-entry recovery time (abort chain ran already in the
                # monitor; this covers finalize → health check → barrier →
                # reassignment), the headline the paper's restart benchmarks
                # decompose.
                with span(
                    "inprocess", "inprocess.restart", iteration=iteration,
                    initial_rank=state.initial_rank,
                ):
                    new_state = self._restart_transition(
                        monitor, abort_fn, state, iteration
                    )
                if new_state is None:
                    return None  # stood down: job completed without us
                state = new_state
            except (RestartAbort, HealthCheckError) as e:
                log.error(f"rank {state.rank}: leaving restart loop: {e!r}")
                flight_recorder.flush("restart_abort", detail=repr(e))
                self._terminate_and_leave(monitor, state)
                raise
            finally:
                if not restart and monitor._thread.is_alive():
                    try:
                        monitor.shutdown()
                    except Exception:
                        pass
