"""Distributed state carried across in-process restart iterations.

Analogue of the reference's ``inprocess/state.py:23-124``: the restart loop's view of
this rank's identity — initial (as launched) vs active (after rank reassignment) —
plus the iteration counter and the mode lattice INITIALIZED → ACTIVE/INACTIVE →
TERMINATED. ``set_distributed_vars`` rewrites the environment variables the training
function reads so a reassigned rank transparently becomes its new identity
(reference ``state.py:94-96``); on TPU the variables are the ones
``jax.distributed.initialize`` and our launcher consume.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Optional


class Mode(enum.Enum):
    INITIALIZED = enum.auto()
    ACTIVE = enum.auto()  # runs the wrapped function
    INACTIVE = enum.auto()  # healthy spare: waits in reserve_fn for a slot
    TERMINATED = enum.auto()  # excluded from the job


@dataclasses.dataclass
class State:
    rank: int
    world_size: int
    active_rank: Optional[int] = None
    active_world_size: Optional[int] = None
    initial_rank: int = -1
    initial_world_size: int = -1
    iteration: int = 0
    mode: Mode = Mode.INITIALIZED
    fn_exception: Optional[BaseException] = None

    def __post_init__(self) -> None:
        if self.initial_rank < 0:
            self.initial_rank = self.rank
        if self.initial_world_size < 0:
            self.initial_world_size = self.world_size
        if self.active_rank is None:
            self.active_rank = self.rank
        if self.active_world_size is None:
            self.active_world_size = self.world_size

    @classmethod
    def from_env(cls) -> "State":
        """Identity from launcher-injected env (reference ``state.py:84``)."""
        rank = int(os.environ.get("TPU_RESILIENCY_RANK", os.environ.get("RANK", "0")))
        world = int(
            os.environ.get("TPU_RESILIENCY_WORLD_SIZE", os.environ.get("WORLD_SIZE", "1"))
        )
        return cls(rank=rank, world_size=world)

    def set_distributed_vars(self) -> None:
        """Expose the *active* identity to the wrapped function via env."""
        if self.mode == Mode.ACTIVE:
            os.environ["RANK"] = str(self.active_rank)
            os.environ["WORLD_SIZE"] = str(self.active_world_size)
            os.environ["TPU_RESILIENCY_ACTIVE_RANK"] = str(self.active_rank)
            os.environ["TPU_RESILIENCY_ACTIVE_WORLD_SIZE"] = str(self.active_world_size)

    def advance(self) -> None:
        self.iteration += 1
        self.fn_exception = None

    def freeze(self) -> "FrozenState":
        return FrozenState(
            rank=self.rank,
            world_size=self.world_size,
            active_rank=self.active_rank,
            active_world_size=self.active_world_size,
            initial_rank=self.initial_rank,
            initial_world_size=self.initial_world_size,
            iteration=self.iteration,
            mode=self.mode,
            fn_exception=self.fn_exception,
        )


@dataclasses.dataclass(frozen=True)
class FrozenState:
    """Immutable snapshot handed to user-pluggable callbacks (reference ``FrozenState``)."""

    rank: int
    world_size: int
    active_rank: Optional[int]
    active_world_size: Optional[int]
    initial_rank: int
    initial_world_size: int
    iteration: int
    mode: Mode
    #: the local exception that triggered this restart round, if any — ``None`` when
    #: the round was triggered by a peer (lets per-rank fault accounting distinguish
    #: "this rank faulted" from "the job restarted")
    fn_exception: Optional[BaseException] = None
