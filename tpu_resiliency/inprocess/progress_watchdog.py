"""Progress watchdog: automatic proof that the main thread is alive and scheduling.

Analogue of reference ``inprocess/progress_watchdog.py:47-195``. The key trick is
identical because it is a CPython property, not a device one: a side thread schedules a
trampoline onto the **main thread** via ``Py_AddPendingCall``; the trampoline can only
run if the main thread is executing Python bytecode with a responsive eval loop. If the
main thread is wedged — C extension deadlock, GIL held forever, runaway native call —
pending calls never execute, timestamps stop, and the monitor process escalates
soft → hard timeout. ``ping()`` is the manual variant for marking forward progress
explicitly from the train loop.

Timestamps are *reported*, not stored: each observed heartbeat is pushed over the
monitor-process socket (``MonitorLink``), so the watcher works even when this process
subsequently dies.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Callable, Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)

try:
    # Pure-C pending-call trampoline (native/probe.c). Preferred: it executes no
    # Python bytecode on the main thread, so a PyThreadState_SetAsyncExc-injected
    # restart exception can never be delivered (and swallowed) inside the probe.
    from tpu_resiliency import _probe_native
except ImportError:  # pragma: no cover - depends on build_ext having run
    _probe_native = None

_PENDING_CALLBACK_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


class ProgressWatchdog:
    """Daemon thread that emits a timestamp whenever the main thread proves alive.

    ``report(kind, timestamp)`` is called from this watchdog thread with
    ``kind="auto"`` (pending-call round-trip completed) or ``kind="manual"``
    (user ping). Pause/resume fences the automatic probing during restart
    coordination (reference ``progress_watchdog.py:47-195`` pause protocol).
    """

    def __init__(
        self,
        interval: float,
        report: Callable[[str, float], None],
        use_native: bool | None = None,
    ):
        self.interval = interval
        self.report = report
        self.native = _probe_native is not None if use_native is None else use_native
        if self.native and _probe_native is None:
            raise RuntimeError("native probe requested but _probe_native is not built")
        self._executed = threading.Event()
        self._paused = threading.Event()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Keep a reference: the C callback must outlive every scheduled pending call.
        self._c_callback = _PENDING_CALLBACK_T(self._trampoline)

    def _trampoline(self, _arg) -> int:
        # Runs ON THE MAIN THREAD inside the eval loop (ctypes fallback path only).
        # An async-injected RankShouldRestart can be delivered inside this frame;
        # swallowing it here would eat the restart signal, so re-arm it for delivery
        # at the next bytecode boundary outside the callback.
        try:
            self._executed.set()
        except BaseException as e:  # noqa: BLE001 - deliberate async-exc shield
            try:
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(threading.main_thread().ident), ctypes.py_object(type(e))
                )
            except Exception:
                pass
        return 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="progress-watchdog", daemon=True
        )
        self._thread.start()

    def shutdown(self, timeout: float = 5.0) -> None:
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def ping(self) -> None:
        """Manual progress mark (callable from any thread)."""
        try:
            self.report("manual", time.monotonic())
        except Exception:
            log.warning("progress ping failed", exc_info=True)

    # -- probe loop --------------------------------------------------------

    def _loop(self) -> None:
        while not self._shutdown.is_set():
            if self._paused.is_set():
                self._shutdown.wait(self.interval)
                continue
            if self._probe_round_trip():
                try:
                    self.report("auto", time.monotonic())
                except Exception:
                    log.warning("progress report failed", exc_info=True)
                # Pace the probes.
                self._shutdown.wait(self.interval)
            # else: main thread did not schedule within interval — no timestamp.

    def _probe_round_trip(self) -> bool:
        """Schedule one main-thread probe and wait up to `interval` for it to run."""
        if self.native:
            before = _probe_native.count()
            if not _probe_native.schedule():
                self._shutdown.wait(self.interval)
                return False
            deadline = time.monotonic() + self.interval
            poll = min(max(self.interval / 20.0, 0.001), 0.05)
            while time.monotonic() < deadline and not self._shutdown.is_set():
                if _probe_native.count() > before:
                    return True
                time.sleep(poll)
            return _probe_native.count() > before
        self._executed.clear()
        rc = ctypes.pythonapi.Py_AddPendingCall(self._c_callback, None)
        if rc != 0:
            # Pending-call queue full; try again next round.
            self._shutdown.wait(self.interval)
            return False
        return self._executed.wait(self.interval)
