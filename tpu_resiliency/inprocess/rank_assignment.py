"""Rank reassignment after failures: filters, shifts, and the topology tree.

Analogue of the reference's ``inprocess/rank_assignment.py`` (filters ``:123-236``,
reassignments ``FillGaps:709`` / ``ShiftRanks:760`` / ``FilterCountGroupedByKey:812``,
and the multi-layer ``Tree:388-680``). Every rank runs the same assignment callable on
the same inputs — ``(world_size, terminated initial-ranks set)`` plus deterministic
topology keys — so all ranks independently compute identical global assignments and
read off their own slot; no extra collective is needed.

TPU re-design notes: topology keys naturally encode the ICI hierarchy (host, slice /
pod, superpod). A ``Tree`` with ``Layer(key_or_fn=lambda r: r // ranks_per_host,
flag=BACKFILL | RESERVE)`` keeps replacement ranks within a failed rank's host group
when possible, so post-restart meshes keep collectives on ICI rather than DCN. The
reference's ``Tree`` algorithm (RESERVE spare-pool search + BACKFILL swap + shift,
``rank_assignment.py:402-453``) is re-implemented here with explicitly documented
semantics rather than translated.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional, Sequence, Union

from tpu_resiliency.exceptions import RestartAbort
from tpu_resiliency.inprocess.state import Mode, State


@dataclasses.dataclass
class RankAssignmentCtx:
    """Input/output of a rank-assignment chain (reference ``rank_assignment.py:42``).

    ``state`` is this rank's state (mutated in place); ``terminated_ranks`` holds
    *initial* ranks confirmed dead this round. Assignments may also raise
    :class:`RestartAbort` when the surviving pool cannot satisfy constraints.
    """

    state: State
    terminated_ranks: frozenset[int] = frozenset()
    #: advisory: ranks the health-vector policy holds degraded (alive but slow);
    #: assignments may demote them to spares but must not require their absence
    degraded_ranks: frozenset[int] = frozenset()


RankAssignment = Callable[[RankAssignmentCtx], RankAssignmentCtx]


def _survivors(ctx: RankAssignmentCtx) -> list[int]:
    return [
        r for r in range(ctx.state.initial_world_size) if r not in ctx.terminated_ranks
    ]


def _apply_global(ctx: RankAssignmentCtx, assignment: dict[int, Optional[int]]) -> RankAssignmentCtx:
    """Write this rank's slot from a globally-computed {initial_rank: active_rank|None}."""
    me = ctx.state.initial_rank
    if me in ctx.terminated_ranks:
        ctx.state.mode = Mode.TERMINATED
        ctx.state.active_rank = None
        return ctx
    active_world = sum(1 for v in assignment.values() if v is not None)
    slot = assignment.get(me)
    if slot is None:
        ctx.state.mode = Mode.INACTIVE
        ctx.state.active_rank = None
    else:
        ctx.state.mode = Mode.ACTIVE
        ctx.state.active_rank = slot
    ctx.state.active_world_size = active_world
    return ctx


# -- filters (choose ACTIVE vs INACTIVE) -----------------------------------


class ActivateAllRanks:
    """Every survivor is active, renumbered densely (reference ``:123``)."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        return _apply_global(ctx, {r: i for i, r in enumerate(surv)})


class ShiftRanks:
    """Survivors keep relative order, shifted left over gaps (reference ``:760``)."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        return _apply_global(ctx, {r: i for i, r in enumerate(surv)})


class FillGaps:
    """Survivors keep their slot when possible; tail survivors move into gaps left by
    the terminated (reference ``:709``). Minimizes the number of ranks whose identity
    changes — fewer recompilations / resharded restores after restart."""

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        n = len(surv)
        keep = [r for r in surv if r < n]
        movers = [r for r in surv if r >= n]
        gaps = sorted(set(range(n)) - set(keep))
        assignment: dict[int, Optional[int]] = {r: r for r in keep}
        for gap, mover in zip(gaps, movers):
            assignment[mover] = gap
        return _apply_global(ctx, assignment)


@dataclasses.dataclass
class MaxActiveWorldSize:
    """Cap the active world; excess survivors become INACTIVE spares (reference ``:146``)."""

    max_active_world_size: Optional[int] = None

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        cap = self.max_active_world_size
        surv = _survivors(ctx)
        n = len(surv) if cap is None else min(cap, len(surv))
        assignment: dict[int, Optional[int]] = {}
        for i, r in enumerate(surv):
            assignment[r] = i if i < n else None
        return _apply_global(ctx, assignment)


@dataclasses.dataclass
class DemoteDegraded:
    """Health-vector demotion: degraded-but-alive ranks yield their active slots to
    healthy spares (the decisions loop of BASELINE target 5).

    Survivors are ordered healthy-first (each group keeping ascending initial-rank
    order) and the first ``max_active_world_size`` become ACTIVE — so a degraded
    rank drops to INACTIVE reserve exactly when a healthy rank exists to take its
    place, and fills in (better slow than absent) when none does. With
    ``max_active_world_size=None`` every survivor stays active and degraded ranks
    are merely renumbered last (useful to pin them to the tail of the mesh).
    """

    max_active_world_size: Optional[int] = None

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        healthy = [r for r in surv if r not in ctx.degraded_ranks]
        degraded = [r for r in surv if r in ctx.degraded_ranks]
        ordered = healthy + degraded
        cap = self.max_active_world_size
        n = len(ordered) if cap is None else min(cap, len(ordered))
        assignment: dict[int, Optional[int]] = {}
        for i, r in enumerate(ordered):
            assignment[r] = i if i < n else None
        return _apply_global(ctx, assignment)


@dataclasses.dataclass
class ActiveWorldSizeDivisibleBy:
    """Round the active world down to a multiple (mesh-shape constraint; reference ``:188``)."""

    divisor: int = 1

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        n = (len(surv) // self.divisor) * self.divisor
        if n == 0:
            raise RestartAbort(
                f"{len(surv)} survivors cannot form a world divisible by {self.divisor}"
            )
        assignment: dict[int, Optional[int]] = {}
        for i, r in enumerate(surv):
            assignment[r] = i if i < n else None
        return _apply_global(ctx, assignment)


@dataclasses.dataclass
class FilterCountGroupedByKey:
    """Keep only groups whose survivor count satisfies a predicate (reference ``:812``).

    ``key_or_fn`` maps an initial rank to its group key (e.g. host index); groups
    failing ``count_predicate`` have all their members demoted to INACTIVE.
    """

    key_or_fn: Callable[[int], object]
    count_predicate: Callable[[int], bool]

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        surv = _survivors(ctx)
        groups: dict[object, list[int]] = {}
        for r in surv:
            groups.setdefault(self.key_or_fn(r), []).append(r)
        kept = [r for key, members in groups.items() if self.count_predicate(len(members)) for r in members]
        kept.sort()
        return _apply_global(ctx, {r: (kept.index(r) if r in kept else None) for r in surv})


# -- topology tree ---------------------------------------------------------


class LayerFlag(enum.Flag):
    NONE = 0
    #: demoted/spare ranks at this layer stay usable as backfill elsewhere
    RESERVE = enum.auto()
    #: groups at this layer accept backfill ranks into termination holes
    BACKFILL = enum.auto()


@dataclasses.dataclass
class Layer:
    """One level of the topology hierarchy (reference ``rank_assignment.py:245``).

    ``key_or_fn``: maps initial rank → group key at this layer (``None`` = one group).
    ``min_ranks``: a group with fewer live members is dissolved (members → spare pool).
    ``max_ranks``: live members beyond this cap are demoted (lowest ranks kept).
    """

    min_ranks: int = 1
    max_ranks: Optional[int] = None
    key_or_fn: Optional[Union[Callable[[int], object], Sequence[object]]] = None
    flag: LayerFlag = LayerFlag.NONE

    def key(self, rank: int) -> object:
        if self.key_or_fn is None:
            return 0
        if callable(self.key_or_fn):
            return self.key_or_fn(rank)
        return self.key_or_fn[rank]


@dataclasses.dataclass
class Tree:
    """Multi-layer topology-aware assignment (re-design of reference ``Tree:388-680``).

    Semantics (deterministic, identical on every rank):

    1. Ranks are grouped hierarchically by each layer's key, outermost layer first.
    2. Bottom-up, each group's *live* member count is checked against the layer's
       ``min_ranks``/``max_ranks``. Under-minimum groups dissolve into the spare pool
       of their parent; over-maximum groups demote their highest-ranked extras.
    3. Where a layer has ``BACKFILL``, groups below that layer's ``max_ranks`` are
       topped back up from the spare pool (lowest spare rank first, groups visited in
       deterministic key order). Spares only exist where some layer flagged
       ``RESERVE`` contributed them, and they surface to the nearest enclosing
       ``BACKFILL`` layer — so keys that mirror the ICI hierarchy keep repairs local.
    4. Surviving active ranks are densely renumbered in initial-rank order (shift).

    ``world_size_filter`` optionally post-constrains the total (e.g. divisibility for
    a fixed mesh shape).
    """

    layers: list[Layer]
    world_size_filter: Optional[Callable[[int], int]] = None

    def __call__(self, ctx: RankAssignmentCtx) -> RankAssignmentCtx:
        world = ctx.state.initial_world_size
        alive = [r for r in range(world) if r not in ctx.terminated_ranks]
        if not self.layers:
            return ActivateAllRanks()(ctx)

        paths = {r: tuple(layer.key(r) for layer in self.layers) for r in alive}
        active, spares = self._assign_level(alive, paths, level=0)

        if self.world_size_filter is not None:
            target = self.world_size_filter(len(active))
            if target <= 0:
                raise RestartAbort(
                    f"world_size_filter reduced {len(active)} active ranks to {target}"
                )
            if target < len(active):
                demoted = sorted(active)[target:]
                spares.extend(demoted)
                active = sorted(active)[:target]

        assignment: dict[int, Optional[int]] = {r: None for r in alive}
        for i, r in enumerate(sorted(active)):
            assignment[r] = i
        return _apply_global(ctx, assignment)

    # The recursion returns (active ranks, spare ranks) for one subtree.
    def _assign_level(
        self, ranks: list[int], paths: dict[int, tuple], level: int
    ) -> tuple[list[int], list[int]]:
        if level == len(self.layers):
            return list(ranks), []
        layer = self.layers[level]
        groups: dict[object, list[int]] = {}
        for r in ranks:
            groups.setdefault(paths[r][level], []).append(r)

        group_active: dict[object, list[int]] = {}
        pool: list[int] = []  # spares available at this level
        for key in sorted(groups, key=repr):
            sub_active, sub_spares = self._assign_level(groups[key], paths, level + 1)
            pool.extend(sub_spares)
            sub_active.sort()
            if layer.max_ranks is not None and len(sub_active) > layer.max_ranks:
                extras = sub_active[layer.max_ranks :]
                sub_active = sub_active[: layer.max_ranks]
                if layer.flag & LayerFlag.RESERVE:
                    pool.extend(extras)
            if len(sub_active) < layer.min_ranks:
                # Group dissolved; members become spares if this layer reserves them.
                if layer.flag & LayerFlag.RESERVE:
                    pool.extend(sub_active)
                continue
            group_active[key] = sub_active

        if layer.flag & LayerFlag.BACKFILL and pool:
            pool.sort()
            cap = layer.max_ranks
            for key in sorted(group_active, key=repr):
                if cap is None:
                    break  # no defined target size to fill toward
                members = group_active[key]
                while len(members) < cap and pool:
                    members.append(pool.pop(0))
                members.sort()

        active = [r for members in group_active.values() for r in members]
        return active, pool


