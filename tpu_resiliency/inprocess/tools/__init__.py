from tpu_resiliency.inprocess.tools.inject_fault import Fault, InjectedFault, inject_fault

__all__ = ["Fault", "InjectedFault", "inject_fault"]
