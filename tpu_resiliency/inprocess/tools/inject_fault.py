"""Fault injection for resiliency testing.

Analogue of reference ``inprocess/tools/inject_fault.py:34-92``: a registry of fault
kinds that tests and examples trigger deterministically (by iteration/step) or after a
delay, exercising every detector: exceptions (monitor-thread path), async exceptions,
SIGKILL / segfault (sibling + monitor-process death paths), GIL lockup (progress
watchdog hard-timeout path), and sleeps (soft-timeout path).

Faults are destructive by design; they are for tests of THIS framework only.
"""

from __future__ import annotations

import ctypes
import enum
import os
import signal
import threading
import time
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Fault(enum.Enum):
    EXC = enum.auto()  # raise in the calling thread
    ASYNC_EXC = enum.auto()  # async-raise into the main thread from a helper thread
    SIGKILL = enum.auto()  # kill the process
    SIGTERM = enum.auto()
    SIGSTOP = enum.auto()  # stop (simulates a wedged-but-alive process)
    SEGFAULT = enum.auto()  # native crash
    LOCK_GIL = enum.auto()  # hold the GIL forever in a helper thread
    SLEEP = enum.auto()  # block the calling thread (soft timeout)
    GIL_SLEEP = enum.auto()  # hold the GIL in long chunks for `duration` seconds
    EXIT = enum.auto()  # os._exit without cleanup
    DEVICE_HANG = enum.auto()  # dispatch a never-terminating compiled program
    DEVICE_ERROR = enum.auto()  # kill the XLA runtime: every later dispatch raises


class InjectedFault(Exception):
    pass


def _segfault() -> None:
    ctypes.memmove(1, 2, 3)  # write to an unmapped address


def _lock_gil() -> None:
    # PyEval-level spin with the GIL held: pure-Python hot loop in a thread that
    # never yields via C calls barely exists in CPython; use ctypes to call a
    # blocking C function while holding the GIL instead.
    libc = ctypes.CDLL(None, use_errno=True)
    pythonapi = ctypes.pythonapi
    pythonapi.PyGILState_Ensure.restype = ctypes.c_void_p
    pythonapi.PyGILState_Ensure()
    libc.sleep(3600)  # blocks holding the GIL: no other thread can run Python


#: seconds per GIL-holding chunk of :data:`Fault.GIL_SLEEP`. Detection design
#: point: a chunk must exceed the heartbeat timeout under test (no beat can
#: land mid-chunk), while the ~instantaneous gap between chunks is the moment
#: the hang-forensics stack capture (``utils/stackdump.py``) can run — a
#: bounded, observable version of the unbounded LOCK_GIL wedge.
GIL_SLEEP_CHUNK_S = 2.0


def _gil_sleep(duration: float, chunk_s: Optional[float] = None) -> None:
    """Hold the GIL in ``chunk_s`` blocks until ``duration`` elapses.

    ``ctypes.PyDLL`` calls do NOT release the GIL (unlike ``CDLL``), so every
    other Python thread — heartbeats included — freezes for each chunk;
    between chunks the interpreter briefly schedules the starved threads,
    which is where a requested stack dump captures this frame. ``chunk_s``
    defaults to :data:`GIL_SLEEP_CHUNK_S` at call time so tests can retune
    the module constant against their detection timeouts."""
    if chunk_s is None:
        chunk_s = GIL_SLEEP_CHUNK_S
    libc = ctypes.PyDLL(None, use_errno=True)
    deadline = time.monotonic() + duration
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        hold_us = int(min(chunk_s, remaining) * 1e6)
        if hold_us > 0:
            libc.usleep(hold_us)  # GIL held for the whole call


def _device_hang() -> None:
    """Block the calling thread in a device wait that never completes — the
    reference's GPU_SLEEP analogue (``tools/inject_fault.py:34-47``): a genuinely
    executing program (compiled ``while_loop`` whose carry never changes), not a
    host sleep, so the thread is parked in C++ ``block_until_ready`` where async
    exceptions cannot reach it — exactly a wedged collective/runtime. Only the
    monitor process's hard-timeout ladder gets a rank out of this state."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    # The exit condition is analytically never true (sin <= 1 < 2) but not
    # provable by XLA, so the loop can't be constant-folded away (a carry the
    # optimizer CAN reason about — e.g. ``c * 0`` — gets folded and returns).
    f = jax.jit(
        lambda: lax.while_loop(
            lambda c: jnp.sin(c) < 2.0, lambda c: c + 1.0, jnp.float32(0)
        )
    )
    jax.block_until_ready(f())  # never returns


_DEAD_PLATFORM = "__injected_dead_device__"
_saved_platforms: list = []


def _device_error() -> None:
    """Kill the device runtime: tear down live XLA backends and point jax at a
    platform that does not exist, so every subsequent dispatch raises — the
    closest a simulation gets to the reference's injected CUDA errors
    (GPU_ERROR). Persistent (unlike a one-shot exception): the liveness probe
    and :class:`JaxHealthCheck` both observe the dead runtime until
    :func:`heal_device_error` or a backend re-initialize."""
    import jax

    from tpu_resiliency.platform.distributed import clear_backends

    _saved_platforms.append(jax.config.jax_platforms)
    jax.config.update("jax_platforms", _DEAD_PLATFORM)
    # Compiled executables pin the old runtime's client and would keep
    # dispatching happily past the dead backend — drop them too.
    jax.clear_caches()
    clear_backends()


def heal_device_error() -> None:
    """Undo :data:`Fault.DEVICE_ERROR` (for tests and abort-chain recovery)."""
    import jax

    from tpu_resiliency.platform.distributed import clear_backends

    if _saved_platforms:
        jax.config.update("jax_platforms", _saved_platforms.pop())
        jax.clear_caches()
        clear_backends()


def inject_fault(
    fault: Fault,
    delay: float = 0.0,
    duration: float = 30.0,
    in_thread: bool = False,
) -> Optional[threading.Thread]:
    """Trigger ``fault`` after ``delay`` seconds (in a helper thread if requested or
    inherently asynchronous)."""

    def fire() -> None:
        if delay > 0:
            time.sleep(delay)
        log.warning(f"injecting fault {fault.name} (pid {os.getpid()})")
        if fault == Fault.EXC:
            raise InjectedFault(f"injected {fault.name}")
        if fault == Fault.ASYNC_EXC:
            main_id = threading.main_thread().ident
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(main_id), ctypes.py_object(InjectedFault)
            )
            return
        if fault in (Fault.SIGKILL, Fault.SIGTERM, Fault.SIGSTOP):
            sig = {
                Fault.SIGKILL: signal.SIGKILL,
                Fault.SIGTERM: signal.SIGTERM,
                Fault.SIGSTOP: signal.SIGSTOP,
            }[fault]
            os.kill(os.getpid(), sig)
            return
        if fault == Fault.SEGFAULT:
            _segfault()
            return
        if fault == Fault.LOCK_GIL:
            _lock_gil()
            return
        if fault == Fault.SLEEP:
            time.sleep(duration)
            return
        if fault == Fault.GIL_SLEEP:
            _gil_sleep(duration)
            return
        if fault == Fault.EXIT:
            os._exit(3)
        if fault == Fault.DEVICE_HANG:
            _device_hang()
            return
        if fault == Fault.DEVICE_ERROR:
            _device_error()
            return
        raise ValueError(f"unknown fault {fault}")

    needs_thread = in_thread or fault in (Fault.ASYNC_EXC, Fault.LOCK_GIL)
    if delay > 0 or needs_thread:
        t = threading.Thread(target=fire, name=f"fault-{fault.name}", daemon=True)
        t.start()
        return t
    fire()
    return None
