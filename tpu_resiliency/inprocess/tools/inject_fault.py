"""Fault injection for resiliency testing.

Analogue of reference ``inprocess/tools/inject_fault.py:34-92``: a registry of fault
kinds that tests and examples trigger deterministically (by iteration/step) or after a
delay, exercising every detector: exceptions (monitor-thread path), async exceptions,
SIGKILL / segfault (sibling + monitor-process death paths), GIL lockup (progress
watchdog hard-timeout path), and sleeps (soft-timeout path).

Faults are destructive by design; they are for tests of THIS framework only.
"""

from __future__ import annotations

import ctypes
import enum
import os
import signal
import threading
import time
from typing import Optional

from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class Fault(enum.Enum):
    EXC = enum.auto()  # raise in the calling thread
    ASYNC_EXC = enum.auto()  # async-raise into the main thread from a helper thread
    SIGKILL = enum.auto()  # kill the process
    SIGTERM = enum.auto()
    SIGSTOP = enum.auto()  # stop (simulates a wedged-but-alive process)
    SEGFAULT = enum.auto()  # native crash
    LOCK_GIL = enum.auto()  # hold the GIL forever in a helper thread
    SLEEP = enum.auto()  # block the calling thread (soft timeout)
    EXIT = enum.auto()  # os._exit without cleanup


class InjectedFault(Exception):
    pass


def _segfault() -> None:
    ctypes.memmove(1, 2, 3)  # write to an unmapped address


def _lock_gil() -> None:
    # PyEval-level spin with the GIL held: pure-Python hot loop in a thread that
    # never yields via C calls barely exists in CPython; use ctypes to call a
    # blocking C function while holding the GIL instead.
    libc = ctypes.CDLL(None, use_errno=True)
    pythonapi = ctypes.pythonapi
    pythonapi.PyGILState_Ensure.restype = ctypes.c_void_p
    pythonapi.PyGILState_Ensure()
    libc.sleep(3600)  # blocks holding the GIL: no other thread can run Python


def inject_fault(
    fault: Fault,
    delay: float = 0.0,
    duration: float = 30.0,
    in_thread: bool = False,
) -> Optional[threading.Thread]:
    """Trigger ``fault`` after ``delay`` seconds (in a helper thread if requested or
    inherently asynchronous)."""

    def fire() -> None:
        if delay > 0:
            time.sleep(delay)
        log.warning(f"injecting fault {fault.name} (pid {os.getpid()})")
        if fault == Fault.EXC:
            raise InjectedFault(f"injected {fault.name}")
        if fault == Fault.ASYNC_EXC:
            main_id = threading.main_thread().ident
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(main_id), ctypes.py_object(InjectedFault)
            )
            return
        if fault in (Fault.SIGKILL, Fault.SIGTERM, Fault.SIGSTOP):
            sig = {
                Fault.SIGKILL: signal.SIGKILL,
                Fault.SIGTERM: signal.SIGTERM,
                Fault.SIGSTOP: signal.SIGSTOP,
            }[fault]
            os.kill(os.getpid(), sig)
            return
        if fault == Fault.SEGFAULT:
            _segfault()
            return
        if fault == Fault.LOCK_GIL:
            _lock_gil()
            return
        if fault == Fault.SLEEP:
            time.sleep(duration)
            return
        if fault == Fault.EXIT:
            os._exit(3)
        raise ValueError(f"unknown fault {fault}")

    needs_thread = in_thread or fault in (Fault.ASYNC_EXC, Fault.LOCK_GIL)
    if delay > 0 or needs_thread:
        t = threading.Thread(target=fire, name=f"fault-{fault.name}", daemon=True)
        t.start()
        return t
    fire()
    return None
