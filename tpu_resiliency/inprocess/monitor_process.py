"""Out-of-process watcher: one daemonized monitor per rank.

Analogue of reference ``inprocess/monitor_process.py`` (double-fork ``daemonize_fn``
``:78-118``, message protocol ``:37-44``, soft/hard timeout enforcement ``:242-258``,
dead-main barrier completion ``:260-282``) fused with ``sibling_monitor.py`` (ring
heartbeat ``:26-57,110-151``) — on TPU hosts both jobs are host-side watchers over the
same store, so they share one loop.

The monitor is double-forked (setsid between forks) so it survives its rank's death and
is outside the rank's process group — a SIGKILL storm that takes out the trainer leaves
the watcher standing. It talks to its rank over an inherited socketpair:

- ``{"kind":"ts"}``            progress timestamps from the :class:`ProgressWatchdog`
- ``{"kind":"phase"}``         ``running`` (fn active; soft/hard timeouts armed) vs
                               ``coord`` (restart coordination; timeouts suspended —
                               barrier/store timeouts cover that phase)
- ``{"kind":"iter"}``          iteration starts
- ``{"kind":"shutdown"}``      clean exit

Duties each tick: forward own heartbeat into the store; watch the ring neighbor's
heartbeat (rank+1 mod N) and report it UNRESPONSIVE when stale, completing barriers on
its behalf; enforce soft (record interruption) and hard (record terminated + SIGCONT +
termination signal, then SIGKILL) progress timeouts; on main-process death, become its
barrier proxy: mark it terminated and complete every subsequent iteration's barriers
until the job ends.
"""

from __future__ import annotations

import dataclasses
import os
import select
import signal
import socket
import time
from typing import Optional

from tpu_resiliency.inprocess.attribution import Interruption
from tpu_resiliency.inprocess.coordination import RestartCoordinator
from tpu_resiliency.platform import framing
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class MonitorConfig:
    rank: int
    world_size: int
    store_host: str
    store_port: int
    store_prefix: str
    monitor_interval: float = 1.0
    heartbeat_interval: float = 1.0
    heartbeat_timeout: float = 30.0
    soft_timeout: float = 60.0
    hard_timeout: float = 90.0
    termination_signal: int = int(signal.SIGTERM)
    sigkill_grace: float = 15.0
    auth_key: Optional[str] = None
    #: monitor log destination; None = /dev/null (a detached daemon MUST drop the
    #: inherited stdio — holding the parent's pipes open makes `cmd | tail` style
    #: consumers wait forever for EOF)
    log_file: Optional[str] = None
    #: proxy gives up when the job makes no progress for this long (defense in depth
    #: against orphan daemons outliving a wedged job)
    proxy_idle_limit: float = 600.0


class MonitorProcess:
    """Parent-side handle: forks the daemonized watcher and streams messages to it."""

    def __init__(self, cfg: MonitorConfig):
        self.cfg = cfg
        self._sock: Optional[socket.socket] = None
        self.pid: Optional[int] = None

    def start(self) -> None:
        parent_sock, child_sock = socket.socketpair()
        main_pid = os.getpid()
        first = os.fork()
        if first == 0:
            # First child: new session, fork again, exit — grandchild is reparented
            # to init and detached from the rank's session/process group.
            try:
                parent_sock.close()
                os.setsid()
                second = os.fork()
                if second == 0:
                    try:
                        _detach_stdio(self.cfg.log_file)
                        # Drop every other inherited fd — most critically rank 0's
                        # KVServer listening socket: holding it would keep the store
                        # port bound (EADDRINUSE on relaunch) and park peers'
                        # reconnects in a dead socket's backlog after the rank dies.
                        _close_fds_except({child_sock.fileno(), 0, 1, 2})
                        _monitor_loop(self.cfg, child_sock, main_pid)
                    finally:
                        os._exit(0)
            finally:
                os._exit(0)
        child_sock.close()
        os.waitpid(first, 0)  # reap the intermediate child
        self._sock = parent_sock

    def _send(self, msg: dict) -> None:
        if self._sock is None:
            return
        try:
            framing.send_obj(self._sock, msg)
        except (BrokenPipeError, ConnectionError, OSError):
            log.warning("monitor process link lost")
            self._sock = None

    def report_timestamp(self, kind: str, t: float) -> None:
        self._send({"kind": "ts", "source": kind, "t": t})

    def set_phase(self, phase: str) -> None:
        self._send({"kind": "phase", "phase": phase})

    def start_iteration(self, iteration: int) -> None:
        self._send({"kind": "iter", "iteration": iteration})

    def shutdown(self) -> None:
        self._send({"kind": "shutdown"})
        self.abandon()

    def abandon(self) -> None:
        """Drop the link without a goodbye: the monitor sees EOF, treats the rank as
        dead, and becomes its barrier proxy — how a rank leaves the job for good."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


def _detach_stdio(log_file: Optional[str]) -> None:
    """Drop inherited stdio: a reparented daemon keeping the parent's stdout pipe
    open blocks every downstream pipe reader's EOF."""
    devnull = os.open(os.devnull, os.O_RDWR)
    if log_file:
        target = os.open(log_file, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    else:
        target = devnull
    os.dup2(devnull, 0)
    os.dup2(target, 1)
    os.dup2(target, 2)
    if target is not devnull and target > 2:
        os.close(target)
    if devnull > 2:
        os.close(devnull)


def _close_fds_except(keep: set[int]) -> None:
    """Close every open fd not in `keep` (the daemonization hygiene step)."""
    try:
        open_fds = [int(fd) for fd in os.listdir("/proc/self/fd")]
    except OSError:
        open_fds = range(3, 1024)
    for fd in open_fds:
        if fd in keep:
            continue
        try:
            os.close(fd)
        except OSError:
            pass


def _monitor_loop(cfg: MonitorConfig, sock: socket.socket, main_pid: int) -> None:
    """Watcher body (grandchild process)."""
    from tpu_resiliency.platform.shardstore import connect_store

    try:
        store = connect_store(
            cfg.store_host,
            cfg.store_port,
            prefix=cfg.store_prefix,
            timeout=60.0,
            auth_key=cfg.auth_key,
        )
    except Exception:
        log.exception("monitor: cannot connect to store; exiting")
        return
    coord = RestartCoordinator(store, cfg.world_size)

    last_ts = time.monotonic()
    phase = "coord"
    iteration = 0
    main_dead = False
    soft_reported_iter: Optional[int] = None
    hard_fired_at: Optional[float] = None
    reported_stale: set[int] = set()
    last_hb = 0.0
    consecutive_failures = 0

    def now() -> float:
        return time.monotonic()

    while True:
        # -- receive messages from the rank --------------------------------
        if not main_dead:
            try:
                ready, _, _ = select.select([sock], [], [], cfg.monitor_interval)
            except OSError:
                ready = []
            if ready:
                try:
                    msg = framing.recv_obj(sock)
                except (EOFError, ConnectionError, OSError):
                    main_dead = True
                    msg = None
                if msg is not None:
                    kind = msg.get("kind")
                    if kind == "ts":
                        last_ts = now()
                    elif kind == "phase":
                        phase = msg["phase"]
                        last_ts = now()
                    elif kind == "iter":
                        iteration = msg["iteration"]
                        soft_reported_iter = None
                        hard_fired_at = None
                        last_ts = now()
                    elif kind == "shutdown":
                        log.info(f"monitor[{cfg.rank}]: clean shutdown")
                        return
        else:
            time.sleep(cfg.monitor_interval)

        try:
            # -- own heartbeat + sibling ring -------------------------------
            if now() - last_hb >= cfg.heartbeat_interval:
                coord.heartbeat(cfg.rank)
                last_hb = now()
                if cfg.world_size > 1:
                    _check_peers(cfg, coord, reported_stale)

            if coord.job_done():
                log.info(f"monitor[{cfg.rank}]: job done; exiting")
                return

            cur = coord.current_iteration()
            if cur is not None and cur > iteration and main_dead:
                iteration = cur

            # -- main-process death: become the rank's barrier proxy --------
            if not main_dead and not _pid_alive(main_pid):
                main_dead = True
            if main_dead:
                coord.record_terminated([cfg.rank])
                coord.record_interruption(
                    iteration if cur is None else cur,
                    cfg.rank,
                    Interruption.TERMINATED,
                    "main process exited",
                )
                _proxy_barriers_until_done(cfg, coord, iteration)
                return

            # -- progress timeouts (only while the wrapped fn runs) ---------
            stale = now() - last_ts
            if phase == "running":
                if stale > cfg.hard_timeout and hard_fired_at is None:
                    log.error(
                        f"monitor[{cfg.rank}]: hard timeout ({stale:.1f}s); signalling"
                    )
                    coord.record_interruption(
                        iteration, cfg.rank, Interruption.HARD_TIMEOUT, f"{stale:.1f}s"
                    )
                    coord.record_terminated([cfg.rank])
                    coord.complete_barriers_for(iteration, cfg.rank)
                    _signal_rank(main_pid, cfg.termination_signal)
                    hard_fired_at = now()
                elif stale > cfg.soft_timeout and soft_reported_iter != iteration:
                    log.warning(
                        f"monitor[{cfg.rank}]: soft timeout ({stale:.1f}s); reporting"
                    )
                    coord.record_interruption(
                        iteration, cfg.rank, Interruption.SOFT_TIMEOUT, f"{stale:.1f}s"
                    )
                    soft_reported_iter = iteration
            if hard_fired_at is not None and now() - hard_fired_at > cfg.sigkill_grace:
                if _pid_alive(main_pid):
                    log.error(f"monitor[{cfg.rank}]: escalating to SIGKILL")
                    _signal_rank(main_pid, signal.SIGKILL)
                hard_fired_at = now() + 3600.0  # fire SIGKILL once
            consecutive_failures = 0
        except Exception:
            # The watcher must outlive *transient* store failures — but a store
            # that never comes back (rank 0 died) means the job is over; a
            # detached daemon must not spin forever.
            consecutive_failures += 1
            if consecutive_failures >= 30:
                log.error(
                    f"monitor[{cfg.rank}]: store unreachable for "
                    f"{consecutive_failures} ticks; assuming job over"
                )
                return
            log.exception(f"monitor[{cfg.rank}]: tick failed; continuing")


def _check_peers(
    cfg: MonitorConfig,
    coord: RestartCoordinator,
    reported_stale: set[int],
) -> None:
    """Watch every peer's heartbeat; report and barrier-proxy stale ones.

    A pure ring (watch rank+1 only) leaves ranks unwatched when a whole host with
    multiple ranks dies — their watchers die with them and their barriers are never
    proxied, deadlocking the survivors. So every watcher asks the server for the
    *stale set*: ages are computed against the server clock (immune to cross-host
    NTP offset) and the response carries only stale ranks, keeping N watchers' polls
    O(stale) on the wire instead of O(N²) full-table scans. Duplicate reports from
    concurrent watchers are tolerated: termination is a set union and on-behalf
    barrier joins are idempotent.
    """
    stale_now = coord.stale_peers(cfg.heartbeat_timeout)
    reported_stale.difference_update(
        r for r in list(reported_stale) if r not in stale_now
    )
    terminated: Optional[frozenset[int]] = None
    cur = coord.current_iteration()
    for peer, age in stale_now.items():
        if peer == cfg.rank:
            continue
        if terminated is None:
            terminated = coord.terminated_ranks()
        if peer in terminated:
            # Known-dead: don't re-report (spurious restarts), but keep proxying —
            # its own monitor may have died with the host.
            if cur is not None:
                coord.complete_barriers_for(cur, peer)
            continue
        if peer not in reported_stale:
            log.error(
                f"monitor[{cfg.rank}]: rank {peer} heartbeat stale "
                f"({age:.1f}s); reporting UNRESPONSIVE"
            )
            coord.record_interruption(
                cur or 0, peer, Interruption.UNRESPONSIVE, f"heartbeat stale {age:.1f}s"
            )
            coord.record_terminated([peer])
            reported_stale.add(peer)
        if cur is not None:
            coord.complete_barriers_for(cur, peer)


def _proxy_barriers_until_done(
    cfg: MonitorConfig, coord: RestartCoordinator, start_iteration: int
) -> None:
    """After main death: complete every iteration's barriers until the job ends."""
    iteration = start_iteration
    last_progress = time.monotonic()
    while time.monotonic() - last_progress < cfg.proxy_idle_limit:
        try:
            coord.complete_barriers_for(iteration, cfg.rank)
            if coord.job_done():
                return
            cur = coord.current_iteration()
            if cur is not None and cur > iteration:
                iteration = cur
                last_progress = time.monotonic()
                continue
        except Exception:
            # Store gone ⇒ the job is over.
            return
        time.sleep(cfg.monitor_interval)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _signal_rank(pid: int, sig: int) -> None:
    try:
        os.kill(pid, signal.SIGCONT)  # wake a stopped process first
        os.kill(pid, sig)
    except (ProcessLookupError, PermissionError) as e:
        log.warning(f"signal {sig} to pid {pid} failed: {e!r}")
