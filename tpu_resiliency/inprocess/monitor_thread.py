"""In-rank monitor thread: turns a store interruption flag into an async exception.

Analogue of reference ``inprocess/monitor_thread.py:155-184``: a per-iteration daemon
thread blocks on the iteration's ``interrupted`` flag; when any rank records an
interruption, it runs the abort chain (under the atomic lock, so user-designated
critical sections are never torn), then repeatedly injects :class:`RankShouldRestart`
into the main thread via ``PyThreadState_SetAsyncExc`` until the restart loop
acknowledges — the CPython trick is identical to the reference's because it is a
property of the interpreter, not the device (``monitor_thread.py:56-105``).

Raise/acknowledge protocol: the thread only injects while ``armed`` (main is inside the
wrapped fn). The main handler calls ``acknowledge()``, which disarms and waits for the
quiesce event, then drains any already-pending injection with short interruptible
sleeps — closing the unavoidable window between "injection scheduled" and "injection
delivered".
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Callable, Optional

from tpu_resiliency.exceptions import InternalError
from tpu_resiliency.inprocess.coordination import RestartCoordinator
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class RankShouldRestart(BaseException):
    """Injected into the main thread to unwind the wrapped fn. BaseException so user
    ``except Exception`` blocks cannot swallow it (reference ``monitor_thread.py:32``)."""


def async_raise(thread_id: int, exc_type: type[BaseException]) -> None:
    """Schedule ``exc_type`` in the thread with ``thread_id`` (reference ``:56``)."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_type)
    )
    if res == 0:
        raise InternalError(f"no thread with id {thread_id}")
    if res > 1:
        # Undo: we hit more than one thread state (should not happen).
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(thread_id), None)
        raise InternalError("PyThreadState_SetAsyncExc affected multiple threads")


class MonitorThread:
    """Watches one iteration's interruption flag; aborts and unwinds the main thread."""

    def __init__(
        self,
        coord: RestartCoordinator,
        iteration: int,
        main_thread_id: int,
        atomic_lock: threading.RLock,
        abort_fn: Optional[Callable[[], None]] = None,
        interval: float = 1.0,
        last_call_wait: float = 0.0,
    ):
        self.coord = coord
        self.iteration = iteration
        self.main_thread_id = main_thread_id
        self.atomic_lock = atomic_lock
        self.abort_fn = abort_fn
        self.interval = interval
        self.last_call_wait = last_call_wait

        self._armed = threading.Event()
        self._ack = threading.Event()
        self._quiesced = threading.Event()
        self._shutdown = threading.Event()
        self._fired = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"inprocess-monitor-{iteration}", daemon=True
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def arm(self) -> None:
        """Main is entering the wrapped fn: injections allowed."""
        self._armed.set()

    def disarm(self) -> None:
        self._armed.clear()

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def acknowledge(self, drain: bool = True) -> None:
        """Main has taken the restart path: stop injecting, then drain stragglers.

        Every step is retried through a late delivery: an injection scheduled just
        before ``_armed`` cleared can land at any bytecode boundary in here, and a
        delivery that surfaces while a C call is returning can be mangled into
        ``SystemError("error return without exception set")`` — the CPython hazard the
        reference guards with its ``sys.unraisablehook`` re-raise
        (``/root/reference/src/nvidia_resiliency_ext/inprocess/monitor_thread.py:87-105``).
        After this returns, no injection is scheduled, pending, or deliverable: the
        caller's subsequent store/barrier work cannot be torn.
        """
        quiesced = False
        clean = 0
        attempts = 0
        while True:
            # One covered region for the whole body: a delivery at ANY internal
            # boundary (loop checks, assignments, the except body itself) lands
            # back in this try on the next pass. The irreducible escape window is
            # the few handler-entry bytecodes between a delivery and re-entering
            # the try — unavoidable in pure CPython, and orders of magnitude
            # smaller than one store round-trip.
            try:
                self._armed.clear()
                self._ack.set()
                if not quiesced:
                    # Monitor loop exits on ack; after the quiesce event no new
                    # injection can be scheduled.
                    self._quiesced.wait(timeout=10.0)
                    quiesced = True
                if not drain or not self._fired.is_set():
                    # Never fired ⇒ async_raise was never called ⇒ nothing can be
                    # pending: the common local-exception restart skips the drain.
                    return
                # At most one injection can still be pending (scheduled before
                # _armed cleared, not yet delivered). Async exceptions deliver at
                # the next eval-loop boundary, so require a streak of clean sleeps
                # before declaring the thread drained.
                while clean < 3 and attempts < 400:
                    attempts += 1
                    time.sleep(0.005)
                    clean += 1
                return
            except (RankShouldRestart, SystemError):
                clean = 0
                continue

    def shutdown(self, timeout: float = 10.0) -> None:
        self._ack.set()
        self._shutdown.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise InternalError("monitor thread did not shut down")

    # -- body --------------------------------------------------------------

    def _run(self) -> None:
        from tpu_resiliency.exceptions import StoreError

        try:
            while not self._shutdown.is_set() and not self._ack.is_set():
                try:
                    fired = self.coord.wait_interrupted(self.iteration, timeout=self.interval)
                except StoreError:
                    return  # store gone: the job is shutting down
                if fired:
                    self._interrupt()
                    return
        finally:
            self._quiesced.set()

    def _interrupt(self) -> None:
        self._fired.set()
        if self.last_call_wait > 0:
            # Let other ranks' in-flight records land BEFORE reading, so the
            # attribution log covers every fault of the round, not just the first
            # (reference last_call_wait, ``monitor_thread.py:155-184``).
            time.sleep(self.last_call_wait)
        try:
            records = self.coord.get_interruptions(self.iteration)
            for rec in records:
                log.warning(f"interruption: {rec.describe()}")
        except Exception:
            log.warning("could not read interruption records", exc_info=True)
        # Abort under the atomic lock: user critical sections are never torn.
        with self.atomic_lock:
            if self.abort_fn is not None:
                try:
                    self.abort_fn()
                except Exception:
                    log.exception("abort chain failed")
        # Inject until acknowledged.
        while not self._ack.is_set() and not self._shutdown.is_set():
            if self._armed.is_set():
                with self.atomic_lock:
                    if self._ack.is_set() or not self._armed.is_set():
                        break
                    try:
                        async_raise(self.main_thread_id, RankShouldRestart)
                    except InternalError:
                        log.exception("async raise failed")
                        return
            if self._ack.wait(timeout=self.interval):
                break
