"""Post-fault rank health checks.

Analogue of the reference's ``inprocess/health_check.py``: ``CudaHealthCheck`` proves
the GPU still answers by running two ``torch.cuda.synchronize`` calls under a timeout
thread (``:70-110``); ``FaultCounter`` caps faults per rank (``:122-146``).

The TPU analogue of "does the device still answer": compile-and-run a tiny addition and
``block_until_ready`` it, twice, each under a watchdog timeout — the first run flushes
any poisoned program state; the second proves steady-state liveness. A hung XLA
computation blocks ``block_until_ready`` forever, which is exactly what the timeout
thread detects (there is no CUDA-context-style query to poll on TPU).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from tpu_resiliency.exceptions import HealthCheckError
from tpu_resiliency.inprocess.state import FrozenState
from tpu_resiliency.utils.logging import get_logger

log = get_logger(__name__)


class HealthCheck:
    """Interface: called with the frozen state after finalize; raise to exclude rank."""

    def __call__(self, state: FrozenState) -> FrozenState:
        raise NotImplementedError


def _run_with_timeout(fn, timeout: float, what: str) -> None:
    err: list[BaseException] = []
    done = threading.Event()

    def body() -> None:
        try:
            fn()
        except BaseException as e:
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=body, name=f"health-{what}", daemon=True)
    t.start()
    if not done.wait(timeout):
        raise HealthCheckError(f"{what} did not complete within {timeout}s")
    if err:
        raise HealthCheckError(f"{what} failed: {err[0]!r}") from err[0]


@dataclasses.dataclass
class JaxHealthCheck(HealthCheck):
    """Device liveness probe: two tiny compiled adds under a timeout (the direct
    analogue of ``CudaHealthCheck``'s double ``synchronize``)."""

    timeout: float = 30.0

    def __call__(self, state: FrozenState) -> FrozenState:
        import jax
        import jax.numpy as jnp

        def probe() -> None:
            x = jnp.asarray([1.0, 2.0])
            jax.block_until_ready(x + x)

        _run_with_timeout(probe, self.timeout, "device probe (1/2)")
        _run_with_timeout(probe, self.timeout, "device probe (2/2)")
        return state


@dataclasses.dataclass
class FaultCounter(HealthCheck):
    """Exclude a rank after too many faults (reference ``health_check.py:122-146``)."""

    max_rank_faults: Optional[int] = None

    def __post_init__(self) -> None:
        self._count = 0

    def __call__(self, state: FrozenState) -> FrozenState:
        # The health chain runs on EVERY survivor each restart round; only rounds
        # where THIS rank's fn raised count as this rank's faults.
        if state.fn_exception is None:
            return state
        self._count += 1
        if self.max_rank_faults is not None and self._count > self.max_rank_faults:
            raise HealthCheckError(
                f"rank {state.rank} exceeded {self.max_rank_faults} faults"
            )
        return state
